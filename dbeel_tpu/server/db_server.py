"""The public document API server — msgpack over TCP.

Wire-compatible with /root/reference/src/tasks/db_server.rs: one listener
per shard at ``port + shard_id``; requests are u16-LE length-prefixed
msgpack maps; responses are u32-LE length-prefixed payloads with one
trailing type byte (Err=0, Ok=1, Bytes=2); errors cross as
``[name, message]``; the connection closes after each response.  The
reference's own 49-line python client (/root/reference/dbeel.py) works
against this server unchanged.
"""

from __future__ import annotations

import asyncio
import logging
import struct
import time

from typing import Optional

import msgpack

from ..errors import (
    BadFieldType,
    CasConflict,
    CorruptedFile,
    DbeelError,
    KeyNotFound,
    KeyNotOwnedByShard,
    MissingField,
    ERROR_CLASS_OTHER,
    ERROR_CLASS_OVERLOAD,
    Overloaded,
    PeerDead,
    Timeout,
    UnsupportedField,
    classify_error,
)
from ..cluster import messages as msgs
from ..cluster.messages import (
    ShardRequest,
    ShardResponse,
    pack_message,
)
from ..storage.entry import TOMBSTONE
from ..utils.murmur import hash_bytes, murmur3_32
from ..utils.timestamps import now_nanos
from . import framed
from . import qos as qos_mod
from . import trace as trace_mod
from .shard import MyShard

log = logging.getLogger(__name__)

RESPONSE_ERR = 0
RESPONSE_OK = 1
RESPONSE_BYTES = 2

DEFAULT_SET_TIMEOUT_MS = 5000  # db_server.rs:31-32
DEFAULT_GET_TIMEOUT_MS = 5000

# "No local read happened yet" marker for the RF>1 get path (None is
# a legitimate local read result: key absent).
_NO_LOCAL_READ = object()


def _quorum_error(my_shard: MyShard, op: str, op_status: dict):
    """Error for a quorum deadline expiry: ``PeerDead`` when a
    fan-out target was marked Dead during (or just before) the wait —
    the op stalled on a dead replica, distinct from a merely slow
    quorum; ``Overloaded`` when a replica SHED the request (its
    governor past the hard limit / propagated deadline expired / our
    capped outbound queue refused it) — the client should back off,
    not immediately hammer the next replica; else plain
    ``Timeout``."""
    targets = op_status.get("targets", ())
    if op_status.get("peer_dead") or any(
        t in my_shard.dead_nodes for t in targets
    ):
        return PeerDead(
            f"{op}: replica marked Dead during quorum wait"
        )
    if op_status.get("peer_overloaded"):
        return Overloaded(
            f"{op}: replica shed the request during quorum wait"
        )
    return Timeout(op)


def _wall_deadline_ms(request: dict, timeout_ms: int) -> int:
    """Absolute wall-clock deadline (ms) to propagate on peer frames:
    the client's own budget when it sent one (deadline_ms), else this
    op's timeout from receipt.  Wall clock like the LWW timestamps —
    replicas on loosely-synced clocks share the caveat the data model
    already accepts."""
    deadline_ms = request.get("deadline_ms")
    if isinstance(deadline_ms, int) and deadline_ms > 0:
        return deadline_ms
    return int(time.time() * 1000) + timeout_ms


# Ops the governor may shed at the hard limit.  Admin/observability
# (get_stats, metadata, rearm, collection DDL) always serve: an
# operator must be able to see into — and command — an overloaded
# node, and DDL is rare enough to never be the overload source.
_SHEDDABLE_OPS = frozenset(
    {
        "set",
        "get",
        "delete",
        "multi_set",
        "multi_get",
        # Atomic plane (ISSUE 19): conditional writes are data ops —
        # sheddable, deadline-droppable, QoS-laned like any set.
        "cas",
        "atomic_batch",
    }
)


def _note_completion(
    my_shard: MyShard,
    op: str,
    started: float,
    timeout_ms: Optional[int],
    deadline_ms: Optional[int],
) -> None:
    """Feed the governor's dead-completion signal: a data op that
    finished after the budget its client gave it (propagated
    deadline, or its own timeout field) produced a response nobody
    was waiting for."""
    if op not in _SHEDDABLE_OPS:
        return
    if isinstance(deadline_ms, int) and deadline_ms > 0:
        dead = time.time() * 1000.0 > deadline_ms
    else:
        elapsed_ms = (time.monotonic() - started) * 1000.0
        dead = elapsed_ms > float(
            timeout_ms or DEFAULT_SET_TIMEOUT_MS
        )
    my_shard.governor.note_completion(dead)


def _deadline_dead_on_arrival(my_shard: MyShard, request: dict) -> bool:
    """Client-supplied absolute deadline already expired at dispatch
    (the frame sat in a backlogged queue longer than the client was
    willing to wait): drop the work instead of computing a dead
    response."""
    deadline_ms = request.get("deadline_ms")
    if not isinstance(deadline_ms, int) or deadline_ms <= 0:
        return False
    if time.time() * 1000.0 <= deadline_ms:
        return False
    my_shard.governor.deadline_drops += 1
    return True


def _extract(map_: dict, field: str):
    if field not in map_:
        raise MissingField(field)
    return map_[field]


def _client_trace_id(request: dict) -> Optional[int]:
    """Client-stamped trace id on the request frame (tracing plane):
    a positive int under the ``trace`` key forces a full span for
    this op — the C parsers punt such frames so the interpreted path
    (which owns the stage marks) always serves them."""
    tid = request.get("trace")
    if isinstance(tid, int) and tid > 0:
        return tid
    return None


def _trace_id_for_peers(ctx) -> Optional[int]:
    """Trace id to stamp on fan-out peer frames: replicas serving a
    traced frame piggyback their own stage summary on the response."""
    return ctx.trace_id if ctx is not None else None


def _qos_for_peers(request: dict) -> Optional[int]:
    """QoS class to stamp on fan-out peer frames (QoS plane): the
    client's class, or None for STANDARD so default traffic keeps the
    pre-QoS peer dialects byte-for-byte (old replicas treat an absent
    element as standard anyway)."""
    cls = qos_mod.request_class(request)
    return cls if cls != qos_mod.QOS_STANDARD else None


def _encode_field(value) -> bytes:
    """Keys/values are stored as their msgpack encoding
    (db_server.rs:93-104)."""
    return msgpack.packb(value, use_bin_type=True)


def extract_key(my_shard: MyShard, map_: dict, replica_index: int) -> bytes:
    key = _encode_field(_extract(map_, "key"))
    key_hash = map_.get("hash")
    if not isinstance(key_hash, int):
        key_hash = hash_bytes(key)
    if not my_shard.owns_key(key_hash, replica_index):
        raise KeyNotOwnedByShard(
            f"shard {my_shard.shard_name} does not own hash {key_hash}"
        )
    return key


def _check_membership_epoch(my_shard: MyShard, request: dict) -> None:
    """Epoch fence (elastic membership plane): a write stamped with a
    membership epoch older than this shard's — WHILE a migration is
    live — was routed by an outdated ring view and may land on an arc
    that is mid-handoff; refuse it retryably (`not-owned` class) so
    the client resyncs metadata and re-routes.  Unstamped writes (old
    clients, the C client) are never fenced — for them the ownership
    check + anti-entropy remain the convergence story, exactly as
    before this plane existed.  Once the last migration drains the
    fence lifts even for stale stamps: a long-converged cluster must
    not refuse a client that simply hasn't polled metadata lately."""
    epoch = request.get("epoch")
    if (
        isinstance(epoch, int)
        and epoch > 0
        and epoch < my_shard.membership_epoch
        and my_shard._migration_tasks
    ):
        my_shard.fence_refusals += 1
        raise KeyNotOwnedByShard(
            f"stale membership epoch {epoch} < "
            f"{my_shard.membership_epoch} during migration"
        )


async def handle_request(
    my_shard: MyShard, request: dict
) -> Optional[bytes]:
    """Returns the response payload (None => plain 'OK')."""
    timestamp = now_nanos()
    rtype = request.get("type")

    if rtype in _SHEDDABLE_OPS and _deadline_dead_on_arrival(
        my_shard, request
    ):
        # Deadline propagation, coordinator side: the client's budget
        # expired while this frame waited its turn — every cycle
        # spent on it now (local read, quorum fan-out) would compute
        # a response nobody reads.  Retryable by taxonomy; the client
        # has long since walked on.
        raise Overloaded("client deadline expired before dispatch")

    if rtype == "get_cluster_metadata":
        return msgpack.packb(
            my_shard.get_cluster_metadata().to_wire(), use_bin_type=True
        )

    if rtype == "get_stats":
        # Observability extension (no reference analog).
        return msgpack.packb(my_shard.get_stats(), use_bin_type=True)

    if rtype == "trace_dump":
        # Tracing plane (PR 9): the flight recorder's ring — sampled
        # spans plus every slow/error op.  Always served, like
        # get_stats: the slow tail of an overload must be readable
        # DURING the overload (not in _SHEDDABLE_OPS).
        return msgpack.packb(
            my_shard.trace_recorder.dump(), use_bin_type=True
        )

    if rtype == "cluster_stats":
        # Telemetry plane (PR 11): the gossip-aggregated per-node
        # health view — ask ANY node, see the whole cluster.  Always
        # served (an overloaded or degraded cluster is exactly when
        # the operator needs the rollup).
        return msgpack.packb(
            my_shard.cluster_stats(), use_bin_type=True
        )

    if rtype == "telemetry_dump":
        # Telemetry plane (PR 11): this shard's full time-series ring
        # + derived rates + health verdict.  Always served, like
        # get_stats/trace_dump.
        return msgpack.packb(
            my_shard.telemetry.dump(), use_bin_type=True
        )

    if rtype == "rearm":
        # Admin: exit sticky degraded read-only mode after disk
        # replacement, no restart — re-runs the free-space/WAL-append
        # pre-checks, re-registers the native write plane, and fans
        # the verb out to this node's sibling shards over the REARM
        # peer frame (every shard of the node shares the replaced
        # disk; the peer handler never re-enters this path, so the
        # fan-out cannot recurse).  Errors (pre-check still failing
        # on any shard) surface as the usual error frame; the shard
        # stays degraded.
        await my_shard.rearm()
        await my_shard.send_request_to_local_shards(
            ShardRequest.rearm(), ShardResponse.REARM
        )
        return None

    if rtype == "create_collection":
        name = _extract(request, "name")
        rf = request.get("replication_factor")
        if not isinstance(rf, int):
            rf = my_shard.config.default_replication_factor
        # DDL-carried tenant-quota overrides (ISSUE 15 satellite):
        # per-collection ops/bytes rates that beat the --tenant-*
        # flag defaults, round-tripped through collection metadata.
        quotas = None
        if isinstance(request.get("ops_per_sec"), int) or isinstance(
            request.get("bytes_per_sec"), int
        ):
            quotas = {}
            if isinstance(request.get("ops_per_sec"), int):
                quotas["ops_per_sec"] = request["ops_per_sec"]
            if isinstance(request.get("bytes_per_sec"), int):
                quotas["bytes_per_sec"] = request["bytes_per_sec"]
        # Secondary-index DDL (ISSUE 17): optional list of value
        # fields to maintain persisted per-SSTable index runs for.
        # Sanitized shard-side; junk entries are dropped there.
        index = request.get("index")
        if not isinstance(index, (list, tuple)):
            index = None
        from ..errors import CollectionAlreadyExists

        if name in my_shard.collections:
            raise CollectionAlreadyExists(name)
        await my_shard.create_collection(name, rf, quotas, index)
        await my_shard.send_request_to_local_shards(
            ShardRequest.create_collection(name, rf, quotas, index),
            ShardResponse.CREATE_COLLECTION,
        )
        await my_shard.gossip(
            msgs.GossipEvent.create_collection(name, rf, quotas, index)
        )
        return None

    if rtype == "get_collection":
        name = _extract(request, "name")
        col = my_shard.get_collection(name)
        body = {"replication_factor": col.replication_factor}
        if col.quotas:
            body["quotas"] = col.quotas
        if col.index_fields:
            body["index"] = col.index_fields
        return msgpack.packb(body, use_bin_type=True)

    if rtype == "drop_collection":
        name = _extract(request, "name")
        await my_shard.drop_collection(name)
        await my_shard.send_request_to_local_shards(
            ShardRequest.drop_collection(name),
            ShardResponse.DROP_COLLECTION,
        )
        await my_shard.gossip(msgs.GossipEvent.drop_collection(name))
        return None

    if rtype in ("set", "delete"):
        ctx = trace_mod.current()
        collection_name = _extract(request, "collection")
        timeout_ms = request.get("timeout") or DEFAULT_SET_TIMEOUT_MS
        replica_index = request.get("replica_index") or 0
        col = my_shard.get_collection(collection_name)
        key = extract_key(my_shard, request, replica_index)
        _check_membership_epoch(my_shard, request)
        rf = col.replication_factor

        if rtype == "set":
            value = _encode_field(_extract(request, "value"))
        else:
            value = TOMBSTONE

        consistency = request.get("consistency")
        if not isinstance(consistency, int):
            consistency = rf
        consistency = min(consistency, rf)
        if ctx is not None:
            # Ownership check + key/value encode + admission.
            ctx.mark("prep")

        async def local_write():
            # stale_abort: if our capacity wait spans a flush swap
            # that lands a NEWER write for this key, a blind insert
            # would put our older ts in a layer above it and
            # first-match reads would serve it — apply read-guarded
            # instead (LWW: whichever ts is newer wins), the same
            # contract as the replica-side handle_shard_set_message.
            t_local = time.monotonic()
            if not await col.tree.set_with_timestamp(
                key, value, timestamp, stale_abort=True
            ):
                await my_shard.apply_if_newer(
                    col.tree, key, value, timestamp
                )
            if ctx is not None:
                # Overlapping detail: the local memtable+WAL write
                # runs concurrently with the quorum fan-out, so it is
                # attributed beside the stages, not among them.
                ctx.note(
                    "local_write_us",
                    (time.monotonic() - t_local) * 1e6,
                )

        if rf > 1:
            peer_deadline = _wall_deadline_ms(request, timeout_ms)
            peer_qos = _qos_for_peers(request)
            remote_request = (
                ShardRequest.set(
                    collection_name, key, value, timestamp,
                    deadline_ms=peer_deadline,
                    trace_id=_trace_id_for_peers(ctx),
                    qos=peer_qos,
                )
                if rtype == "set"
                else ShardRequest.delete(
                    collection_name, key, timestamp,
                    deadline_ms=peer_deadline,
                    trace_id=_trace_id_for_peers(ctx),
                    qos=peer_qos,
                )
            )
            expected = (
                ShardResponse.SET
                if rtype == "set"
                else ShardResponse.DELETE
            )
            op_status: dict = {}
            remote = my_shard.send_request_to_replicas(
                remote_request,
                consistency - 1,
                rf - replica_index - 1,
                expected,
                op_status=op_status,
                key_hash=hash_bytes(key),
            )
            try:
                await asyncio.wait_for(
                    asyncio.gather(local_write(), remote),
                    timeout_ms / 1000,
                )
            except asyncio.TimeoutError as e:
                raise _quorum_error(
                    my_shard, rtype, op_status
                ) from e
            finally:
                if ctx is not None:
                    # Wall time of the overlapped local write +
                    # replica fan-out up to the consistency-th ack.
                    ctx.mark("quorum")
        else:
            try:
                await asyncio.wait_for(local_write(), timeout_ms / 1000)
            except asyncio.TimeoutError as e:
                raise Timeout(rtype) from e
            finally:
                if ctx is not None:
                    ctx.mark("local")
        return None

    if rtype in ("multi_set", "multi_get"):
        return await _handle_multi(my_shard, request, timestamp, rtype)

    if rtype == "cas":
        # Atomic plane (ISSUE 19): conditional single-key write,
        # decided at the key's arc owner under the per-arc lock.
        return await _handle_cas(my_shard, request)

    if rtype == "atomic_batch":
        # Atomic plane (ISSUE 19): all-or-nothing multi-key
        # conditional batch on ONE ring arc.
        return await _handle_atomic_batch(my_shard, request)

    if rtype in ("scan", "scan_next"):
        # Streaming scan plane (PR 12): one governor-admitted chunk
        # per frame — byte-budgeted, merged across every ring arc's
        # replicas, resumable via the opaque cursor in the payload.
        # Shedding/pacing and the scan stats block live in the plane;
        # a shed surfaces as the retryable Overloaded and the CURSOR
        # SURVIVES (it is client-held state), so the client backs off
        # and resumes where it left.
        return await my_shard.scan_plane.handle(request, rtype)

    if rtype in ("watch", "watch_next"):
        # Watch/CDC streaming plane (ISSUE 20): one chunk of change
        # events per frame with a self-contained resumable cursor in
        # EVERY chunk — the stream survives coordinator death, sheds
        # (retryable Overloaded; the cursor is client-held state),
        # arc handoff (durable-state catch-up, dup-flagged) and the
        # membership-epoch fence (retryable not-owned → resync).
        return await my_shard.watch_plane.handle(request, rtype)

    if rtype == "get":
        ctx = trace_mod.current()
        collection_name = _extract(request, "collection")
        timeout_ms = request.get("timeout") or DEFAULT_GET_TIMEOUT_MS
        replica_index = request.get("replica_index") or 0
        col = my_shard.get_collection(collection_name)
        key = extract_key(my_shard, request, replica_index)
        rf = col.replication_factor

        consistency = request.get("consistency")
        if not isinstance(consistency, int):
            consistency = rf
        consistency = min(consistency, rf)
        if ctx is not None:
            ctx.mark("prep")

        if rf > 1:
            deadline = (
                asyncio.get_event_loop().time() + timeout_ms / 1000
            )
            op_status = {}
            local_value = _NO_LOCAL_READ
            if _digest_reads_enabled():
                # Digest round: local read first (it anchors the
                # predicted digest bytes), then (ts, hash) fan-out —
                # full entries move only when a replica is newer.
                try:
                    # consistency=1 means this local read may be the
                    # ONLY evidence: shadow-suspect hits must demote
                    # to (guarded) misses.  With consistency>1 the
                    # quorum merge outvotes staleness by timestamp.
                    local_value = await asyncio.wait_for(
                        col.tree.get_entry(
                            key, suspect_guard=consistency == 1
                        ),
                        timeout_ms / 1000,
                    )
                except asyncio.TimeoutError as e:
                    raise Timeout("get") from e
                finally:
                    if ctx is not None:
                        # Local memtable/table probe anchoring the
                        # predicted digest bytes.
                        ctx.mark("probe")
                digest_agreed = await _digest_quorum_round(
                    my_shard,
                    collection_name,
                    col,
                    key,
                    local_value,
                    consistency,
                    rf - replica_index - 1,
                    max(
                        0.001,
                        deadline - asyncio.get_event_loop().time(),
                    ),
                    op_status=op_status,
                    deadline_ms=_wall_deadline_ms(
                        request, timeout_ms
                    ),
                    trace_id=_trace_id_for_peers(ctx),
                    qos=_qos_for_peers(request),
                )
                if ctx is not None:
                    ctx.mark("digest")
                if digest_agreed:
                    if (
                        local_value is None
                        or bytes(local_value[0]) == TOMBSTONE
                    ):
                        if (
                            local_value is None
                            and consistency == 1
                            and col.tree.reads_suspect
                        ):
                            # No replica corroborated this absence
                            # (consistency=1 ends the digest round
                            # immediately): unproven during a pending
                            # repair — error retryably.
                            raise CorruptedFile(
                                "local miss is suspect: quarantined "
                                "table pending repair"
                            )
                        raise KeyNotFound(repr(key))
                    return bytes(local_value[0])
            remote = my_shard.send_request_to_replicas(
                ShardRequest.get(
                    collection_name,
                    key,
                    deadline_ms=_wall_deadline_ms(
                        request, timeout_ms
                    ),
                    trace_id=_trace_id_for_peers(ctx),
                    qos=_qos_for_peers(request),
                ),
                consistency - 1,
                rf - replica_index - 1,
                ShardResponse.GET,
                op_status=op_status,
                key_hash=hash_bytes(key),
            )
            try:
                if local_value is _NO_LOCAL_READ:
                    local_value, values = await asyncio.wait_for(
                        asyncio.gather(
                            col.tree.get_entry(
                                key, suspect_guard=consistency == 1
                            ),
                            remote,
                        ),
                        max(
                            0.001,
                            deadline
                            - asyncio.get_event_loop().time(),
                        ),
                    )
                else:
                    # The digest round already read the local entry;
                    # don't pay a second tree lookup on fallback.
                    values = await asyncio.wait_for(
                        remote,
                        max(
                            0.001,
                            deadline
                            - asyncio.get_event_loop().time(),
                        ),
                    )
            except asyncio.TimeoutError as e:
                raise _quorum_error(my_shard, "get", op_status) from e
            finally:
                if ctx is not None:
                    ctx.mark("quorum")
            return _merge_quorum_get(
                my_shard,
                collection_name,
                col,
                key,
                local_value,
                values,
                rf - replica_index - 1,
            )
        try:
            entry = await asyncio.wait_for(
                col.tree.get_entry(key, suspect_guard=True),
                timeout_ms / 1000,
            )
        except asyncio.TimeoutError as e:
            raise Timeout("get") from e
        finally:
            if ctx is not None:
                ctx.mark("probe")
        if entry is not None and bytes(entry[0]) != TOMBSTONE:
            return bytes(entry[0])
        if entry is None and col.tree.reads_suspect:
            # RF=1 read on a tree with a quarantine pending repair:
            # absence is unproven (the key may have lived in the
            # dropped table) — surface the retryable corruption
            # error, not a confident KeyNotFound.  A TOMBSTONE hit
            # that survived the suspect guard is newest evidence and
            # stays a confident KeyNotFound.
            raise CorruptedFile(
                "local miss is suspect: quarantined table "
                "pending repair"
            )
        raise KeyNotFound(repr(key))

    if isinstance(rtype, str):
        raise UnsupportedField(rtype)
    raise BadFieldType("type")


# Batched multi-op bounds: ops per frame (the u16 request framing is
# its own byte bound; this caps the per-frame allocation fan).
MULTI_MAX_OPS = 4096


async def _handle_multi(
    my_shard: MyShard, request: dict, timestamp: int, rtype: str
) -> bytes:
    """One multi_set/multi_get frame: N sub-ops in, ONE response frame
    with N aligned results out.  Each result is ``[0, payload]`` (ok —
    payload is the value bytes for gets, nil for sets) or
    ``[1, [kind, message]]`` (per-sub-op error in the standard wire
    error shape), so a client can fail over individual keys without
    losing the rest of the batch.

    The batch shares everything a per-op loop would repeat: ownership
    checks ride one ring lookup each but the storage work batches —
    one memtable capacity check + one WAL append_batch + one wal-sync
    ticket for sets (group commit), one sstable-list acquire for gets
    (LSMTree.multi_get) — and RF>1 batches fan out ONE peer frame per
    replica (ShardRequest.multi_set/multi_get) with a single quorum
    wait for the whole batch, instead of a frame per sub-op."""
    collection_name = _extract(request, "collection")
    ops = _extract(request, "ops")
    if not isinstance(ops, (list, tuple)):
        raise BadFieldType("ops")
    if len(ops) > MULTI_MAX_OPS:
        raise BadFieldType(f"ops: batch above {MULTI_MAX_OPS}")
    is_set = rtype == "multi_set"
    timeout_ms = request.get("timeout") or (
        DEFAULT_SET_TIMEOUT_MS if is_set else DEFAULT_GET_TIMEOUT_MS
    )
    replica_index = request.get("replica_index") or 0
    col = my_shard.get_collection(collection_name)
    if is_set:
        # Frame-level fence: the epoch stamps the client's ring VIEW,
        # which routed the whole batch — a stale view refuses the
        # frame, not individual sub-ops.
        _check_membership_epoch(my_shard, request)
    rf = col.replication_factor
    consistency = request.get("consistency")
    if not isinstance(consistency, int):
        consistency = rf
    consistency = min(consistency, rf)
    my_shard.metrics.record_batch_size(len(ops))

    results: list = [None] * len(ops)
    keyed: list = []  # (result_index, key_bytes[, value_bytes])
    min_fields = 3 if is_set else 2
    for i, op in enumerate(ops):
        try:
            if (
                not isinstance(op, (list, tuple))
                or len(op) < min_fields
            ):
                raise BadFieldType("ops")
            key = _encode_field(op[0])
            key_hash = op[1]
            if not isinstance(key_hash, int):
                key_hash = hash_bytes(key)
            if not my_shard.owns_key(key_hash, replica_index):
                raise KeyNotOwnedByShard(
                    f"shard {my_shard.shard_name} does not own "
                    f"hash {key_hash}"
                )
            if is_set:
                keyed.append((i, key, _encode_field(op[2])))
            else:
                keyed.append((i, key))
        except DbeelError as e:
            my_shard.metrics.record_error(classify_error(e))
            results[i] = [1, e.to_wire()]
    if not keyed:
        return msgpack.packb(results, use_bin_type=True)

    peer_qos = _qos_for_peers(request)
    if is_set:
        await _multi_set_keyed(
            my_shard,
            collection_name,
            col,
            keyed,
            results,
            timestamp,
            consistency,
            rf,
            replica_index,
            timeout_ms,
            peer_qos,
        )
    else:
        await _multi_get_keyed(
            my_shard,
            collection_name,
            col,
            keyed,
            results,
            consistency,
            rf,
            replica_index,
            timeout_ms,
            peer_qos,
        )
    return msgpack.packb(results, use_bin_type=True)


def _group_keyed_by_replica_set(
    my_shard: MyShard, keyed: list, number_of_nodes: int
) -> list:
    """Group multi-op sub-ops by their keys' replica sets (elastic
    membership plane): under vnodes one coordinator shard owns many
    arcs, and keys on different arcs fan to DIFFERENT downstream
    replica nodes — one peer frame per distinct replica set keeps
    placement exact.  With one token per shard every owned key shares
    the shard's lone arc, so this collapses to a single group: the
    legacy one-frame-per-batch behavior, byte for byte.  Returns
    ``[(items, anchor_key_hash), ...]`` in first-seen order."""
    groups: dict = {}
    order: list = []
    for item in keyed:
        kh = hash_bytes(item[1])
        names = tuple(
            n
            for n, _c in my_shard._replica_connections(
                number_of_nodes, kh
            )
        )
        g = groups.get(names)
        if g is None:
            g = groups[names] = (list(), kh)
            order.append(g)
        g[0].append(item)
    return order


async def _multi_set_keyed(
    my_shard: MyShard,
    collection_name: str,
    col,
    keyed: list,
    results: list,
    timestamp: int,
    consistency: int,
    rf: int,
    replica_index: int,
    timeout_ms: int,
    peer_qos: Optional[int] = None,
) -> None:
    entries = [(key, value, timestamp) for _i, key, value in keyed]
    op_status: dict = {}
    ctx = trace_mod.current()
    if ctx is not None:
        ctx.mark("prep")

    async def local_batch():
        # stale_abort mirrors the single-set coordinator path: a
        # capacity wait spanning a flush swap must not land our
        # older ts above a flushed newer value — rejected entries
        # apply read-guarded (LWW).
        rejected = await col.tree.set_batch_with_timestamp(
            entries, stale_abort=True
        )
        for k, v, ts in rejected:
            await my_shard.apply_if_newer(col.tree, k, v, ts)

    try:
        local = local_batch()
        if rf > 1:
            remotes = [
                my_shard.send_request_to_replicas(
                    ShardRequest.multi_set(
                        collection_name,
                        [
                            [key, value, timestamp]
                            for _i, key, value in items
                        ],
                        deadline_ms=int(time.time() * 1000)
                        + timeout_ms,
                        trace_id=_trace_id_for_peers(ctx),
                        qos=peer_qos,
                    ),
                    consistency - 1,
                    rf - replica_index - 1,
                    ShardResponse.MULTI_SET,
                    op_status=op_status,
                    key_hash=anchor,
                )
                for items, anchor in _group_keyed_by_replica_set(
                    my_shard, keyed, rf - replica_index - 1
                )
            ]
            await asyncio.wait_for(
                asyncio.gather(local, *remotes), timeout_ms / 1000
            )
        else:
            await asyncio.wait_for(local, timeout_ms / 1000)
    except asyncio.TimeoutError:
        err = _quorum_error(my_shard, "multi_set", op_status)
        my_shard.metrics.record_error(classify_error(err))
        wire = err.to_wire()
        for i, *_rest in keyed:
            results[i] = [1, wire]
        return
    finally:
        # In a finally like the single-op paths: the timed-out multi
        # ops are exactly the ones whose quorum wait must not be
        # misattributed to "respond".
        if ctx is not None:
            ctx.mark("quorum" if rf > 1 else "local")
    for i, *_rest in keyed:
        results[i] = [0, None]


async def _multi_get_keyed(
    my_shard: MyShard,
    collection_name: str,
    col,
    keyed: list,
    results: list,
    consistency: int,
    rf: int,
    replica_index: int,
    timeout_ms: int,
    peer_qos: Optional[int] = None,
) -> None:
    keys = [key for _i, key in keyed]
    op_status: dict = {}
    number_of_nodes = rf - replica_index - 1
    ctx = trace_mod.current()
    if ctx is not None:
        ctx.mark("prep")
    group_results: list = []  # (items, replica_lists) per group
    try:
        # suspect_guard whenever the local read may be the ONLY
        # evidence (consistency=1 — including RF>1 with 0 remote acks
        # awaited): a quorum merge outvotes shadow-suspect staleness
        # by timestamp, an evidence-free merge cannot.
        local = col.tree.multi_get(
            keys, suspect_guard=consistency == 1
        )
        if rf > 1:
            # Full-entry round only: the digest prediction is a
            # per-key byte-compare trick and does not compose with
            # one-frame-per-peer batching (ARCHITECTURE.md).  One
            # frame per distinct replica set (vnodes: keys on
            # different arcs read different replica nodes).
            groups = _group_keyed_by_replica_set(
                my_shard, keyed, number_of_nodes
            )
            remotes = [
                my_shard.send_request_to_replicas(
                    ShardRequest.multi_get(
                        collection_name,
                        [key for _i, key in items],
                        deadline_ms=int(time.time() * 1000)
                        + timeout_ms,
                        trace_id=_trace_id_for_peers(ctx),
                        qos=peer_qos,
                    ),
                    consistency - 1,
                    number_of_nodes,
                    ShardResponse.MULTI_GET,
                    op_status=op_status,
                    key_hash=anchor,
                )
                for items, anchor in groups
            ]
            local_map, *per_group = await asyncio.wait_for(
                asyncio.gather(local, *remotes), timeout_ms / 1000
            )
            group_results = [
                (items, lists)
                for (items, _anchor), lists in zip(groups, per_group)
            ]
        else:
            local_map = await asyncio.wait_for(
                local, timeout_ms / 1000
            )
    except asyncio.TimeoutError:
        err = _quorum_error(my_shard, "multi_get", op_status)
        my_shard.metrics.record_error(classify_error(err))
        wire = err.to_wire()
        for i, _key in keyed:
            results[i] = [1, wire]
        return
    finally:
        if ctx is not None:
            ctx.mark("quorum" if rf > 1 else "local")
    if rf > 1:
        for items, replica_lists in group_results:
            aligned = [
                r
                for r in replica_lists
                if isinstance(r, (list, tuple))
                and len(r) == len(items)
            ]
            for j, (i, key) in enumerate(items):
                local_value = local_map.get(key)
                try:
                    win = _merge_quorum_get(
                        my_shard,
                        collection_name,
                        col,
                        key,
                        local_value,
                        [r[j] for r in aligned],
                        number_of_nodes,
                    )
                    results[i] = [0, win]
                except KeyNotFound as e:
                    results[i] = [1, e.to_wire()]
                except CorruptedFile as e:
                    # Suspect miss (quarantine pending repair):
                    # retryable per-sub-op error; the client re-runs
                    # it through the single-op replica walk.
                    my_shard.metrics.record_error(classify_error(e))
                    results[i] = [1, e.to_wire()]
        return
    for i, key in keyed:
        local_value = local_map.get(key)
        if local_value is None and col.tree.reads_suspect:
            e = CorruptedFile(
                "local miss is suspect: quarantined table pending "
                "repair"
            )
            my_shard.metrics.record_error(classify_error(e))
            results[i] = [1, e.to_wire()]
        elif (
            local_value is None
            or bytes(local_value[0]) == TOMBSTONE
        ):
            results[i] = [1, KeyNotFound(repr(key)).to_wire()]
        else:
            results[i] = [0, bytes(local_value[0])]


# ---------------------------------------------------------------------
# Atomic plane (ISSUE 19): epoch-fenced CAS + per-arc atomic batches.
#
# A conditional write DECIDES at exactly one replica — the key's arc
# owner (replica index 0 on the walk, or the first live stand-in when
# everything ahead is marked Dead) — under a per-(collection, arc)
# asyncio.Lock, so read-compare-decide sequences on an arc can never
# interleave.  The decider reads the key's current state at the op's
# consistency (R mirrors W, so quorum-consistency CAS observes every
# prior quorum-decided write even on a decider whose local tree is
# behind), compares the client's expectations, and on a match commits
# a fresh LWW timestamp that replicates as ORDINARY set/delete/
# multi_set peer frames — hinted handoff, read repair and
# anti-entropy converge replicas with no new peer verbs.  The
# membership-epoch fence applies exactly as it does to plain writes
# (re-checked under the lock: a migration may start while the op
# queues), and frames always serve on this interpreted path — the C
# planes punt the cas/atomic_batch verbs by construction (lint-pinned)
# so the fence and the lock cannot be bypassed.
#
# Caveats (documented in ARCHITECTURE.md): mixing raw LWW sets with
# CAS on the same key forfeits the CAS guarantees, and expect_value
# has the usual ABA limitation.
# ---------------------------------------------------------------------

# Ops per atomic_batch frame.  Small by design: the batch holds the
# arc lock across its quorum read + commit, so a huge batch would
# head-of-line-block every other conditional write on the arc.
ATOMIC_BATCH_MAX_OPS = 128

_NO_EXPECT = object()


def _atomic_decider_gate(
    my_shard: MyShard, key_hash: int, replica_index: int
) -> None:
    """Single-decider election for conditional writes.  The natural
    decider is replica index 0 on the key's walk; a later replica may
    stand in ONLY while every node ahead of it is marked Dead (the
    client walked here because the primary was unreachable).  Two
    LIVE deciders on one arc would each serialize CAS locally and
    could ack conflicting outcomes — the split brain the arc lock
    exists to prevent.  A freshly-restarted decider additionally sits
    out the boot barrier, so its comeback cannot race a stand-in that
    has not yet seen its Alive edge."""
    if replica_index > 0:
        alive = [
            n
            for n in my_shard.preceding_replica_nodes(key_hash)
            if n not in my_shard.dead_nodes
        ]
        if alive:
            raise KeyNotOwnedByShard(
                f"conditional write at replica_index {replica_index}"
                f" refused: preceding replica(s) {alive} are alive"
            )
    barrier_s = my_shard.atomic_barrier_remaining_s()
    if barrier_s > 0:
        raise Overloaded(
            "conditional-write decider barrier: "
            f"{int(barrier_s * 1000)}ms remaining after restart"
        )


def _cas_mismatch(
    request: dict, current, require: bool = True
) -> Optional[str]:
    """None when the map's expectations match the key's current
    state, else the conflict detail.  ``request`` is the client's cas
    request map OR one atomic_batch op map (same expectation fields
    by design); ``current`` is the decider's merged (value_bytes, ts)
    view — the value may be the tombstone — or None for
    never-written.  With ``require`` (the cas verb) at least one
    expectation field is demanded; batch ops may be unconditional
    (they still commit-or-refuse with the whole batch)."""
    live = current is not None and bytes(current[0]) != TOMBSTONE
    cur_ts = None if current is None else current[1]
    checked = False
    if request.get("expect_absent"):
        checked = True
        if live:
            return f"expected absent, but live at ts {cur_ts}"
    expect_ts = request.get("expect_ts")
    if isinstance(expect_ts, int):
        checked = True
        if cur_ts != expect_ts:
            return f"expected ts {expect_ts}, current ts {cur_ts}"
    expect_value = request.get("expect_value", _NO_EXPECT)
    if expect_value is not _NO_EXPECT:
        checked = True
        if not live:
            return "expected a live value, but key is absent"
        if bytes(current[0]) != _encode_field(expect_value):
            return "expected value does not match current value"
    if not checked and require:
        raise MissingField("expect_ts|expect_value|expect_absent")
    return None


async def _handle_cas(my_shard: MyShard, request: dict) -> bytes:
    ctx = trace_mod.current()
    collection_name = _extract(request, "collection")
    timeout_ms = request.get("timeout") or DEFAULT_SET_TIMEOUT_MS
    replica_index = request.get("replica_index") or 0
    col = my_shard.get_collection(collection_name)
    key = extract_key(my_shard, request, replica_index)
    _check_membership_epoch(my_shard, request)
    key_hash = hash_bytes(key)
    _atomic_decider_gate(my_shard, key_hash, replica_index)
    rf = col.replication_factor
    consistency = request.get("consistency")
    if not isinstance(consistency, int):
        consistency = rf
    consistency = min(consistency, rf)
    number_of_nodes = rf - replica_index - 1
    is_delete = bool(request.get("delete"))
    value = (
        TOMBSTONE
        if is_delete
        else _encode_field(_extract(request, "value"))
    )
    deadline = asyncio.get_event_loop().time() + timeout_ms / 1000
    op_status: dict = {}
    if ctx is not None:
        ctx.mark("prep")
    async with my_shard.atomic_lock(collection_name, key_hash):
        # Fence re-check under the lock: a migration (and its epoch
        # bump) may have landed while this op queued behind another
        # conditional write.
        _check_membership_epoch(my_shard, request)
        try:
            current = await _atomic_read_current(
                my_shard,
                collection_name,
                col,
                key,
                consistency,
                number_of_nodes,
                deadline,
                request,
                timeout_ms,
                op_status,
                ctx,
            )
        except asyncio.TimeoutError as e:
            raise _quorum_error(my_shard, "cas", op_status) from e
        if ctx is not None:
            ctx.mark("read")
        detail = _cas_mismatch(request, current)
        if detail is not None:
            my_shard.cas_conflicts += 1
            raise CasConflict(f"cas on {key!r}: {detail}")
        # Decide with a fresh LWW timestamp strictly above the
        # observed current, so the outcome replicates as an ordinary
        # WINNING set/delete everywhere.
        decided_ts = now_nanos()
        if current is not None and decided_ts <= current[1]:
            decided_ts = current[1] + 1
        await _replicate_decided(
            my_shard,
            collection_name,
            col,
            request,
            key,
            value,
            is_delete,
            decided_ts,
            consistency,
            number_of_nodes,
            deadline,
            timeout_ms,
            op_status,
            "cas",
            ctx,
        )
    my_shard.cas_served += 1
    return msgpack.packb({"ts": decided_ts}, use_bin_type=True)


def _live_arc_peers(
    my_shard: MyShard, number_of_nodes: int, key_hash: int
) -> int:
    """How many of the arc's walk-after-self replicas are NOT marked
    Dead right now — the response floor a decider's read must reach.
    Dead-marked peers fast-fail inside the fan-out (they cannot hold
    a write the failure detector hasn't already handed to hints), so
    they are excluded from the floor; every live-marked peer must
    actually answer or the conditional write refuses retryably."""
    if number_of_nodes <= 0:
        return 0
    peers = my_shard._replica_connections(
        number_of_nodes, key_hash
    )
    return sum(
        1
        for name, _c in peers
        if name not in my_shard.dead_nodes
    )


async def _atomic_read_current(
    my_shard: MyShard,
    collection_name: str,
    col,
    key: bytes,
    consistency: int,
    number_of_nodes: int,
    deadline: float,
    request: dict,
    timeout_ms: int,
    op_status: dict,
    ctx,
):
    """The decider's merged view of one key: local entry + a read of
    EVERY live replica on the arc.  A first-ack quorum read is not
    enough here: after a decider handover the newest committed write
    may live on exactly one surviving replica, and deciding against
    any view that might exclude it mints a NEWER timestamp on stale
    state — a silent lost update.  So the read demands an answer from
    every walk peer not marked Dead and raises TimeoutError (mapped
    to a retryable quorum refusal by the caller) when one is missing.
    Returns the max-timestamp (value_bytes, ts) — tombstones included
    — or None when no consulted replica has an entry."""
    local = col.tree.get_entry(
        key, suspect_guard=consistency == 1
    )
    budget = max(
        0.001, deadline - asyncio.get_event_loop().time()
    )
    live = _live_arc_peers(
        my_shard, number_of_nodes, hash_bytes(key)
    )
    if live > 0:
        remote = my_shard.send_request_to_replicas(
            ShardRequest.get(
                collection_name,
                key,
                deadline_ms=_wall_deadline_ms(request, timeout_ms),
                trace_id=_trace_id_for_peers(ctx),
                qos=_qos_for_peers(request),
            ),
            live,
            number_of_nodes,
            ShardResponse.GET,
            op_status=op_status,
            key_hash=hash_bytes(key),
        )
        local_value, values = await asyncio.wait_for(
            asyncio.gather(local, remote), budget
        )
        if len(values) < live:
            raise asyncio.TimeoutError(
                "atomic read: live replica did not answer"
            )
    else:
        local_value = await asyncio.wait_for(local, budget)
        values = []
    entries = [
        (bytes(v[0]), v[1]) for v in values if v is not None
    ]
    if local_value is not None:
        entries.append((bytes(local_value[0]), local_value[1]))
    if not entries:
        return None
    return max(entries, key=lambda e: e[1])


async def _replicate_decided(
    my_shard: MyShard,
    collection_name: str,
    col,
    request: dict,
    key: bytes,
    value: bytes,
    is_delete: bool,
    decided_ts: int,
    consistency: int,
    number_of_nodes: int,
    deadline: float,
    timeout_ms: int,
    op_status: dict,
    opname: str,
    ctx,
) -> None:
    """Commit + replicate one DECIDED conditional write exactly like
    an ordinary set/delete: the local LWW apply overlapped with plain
    SET/DELETE peer frames, so hinted handoff and anti-entropy
    converge replicas with no new peer verbs.  A quorum timeout HERE
    leaves the op ambiguous to the client (decided but unacked), the
    same contract as a timed-out plain set — clients resolve by
    re-reading.  Unlike a plain set, the remote ack count is
    ENFORCED: the fan-out resolves with whatever acks it got when
    replicas run out, and acking a conditional write held by the
    decider alone would let a later decider (after this node dies)
    rebuild the chain from a state that never saw it."""

    async def local_write():
        if not await col.tree.set_with_timestamp(
            key, value, decided_ts, stale_abort=True
        ):
            await my_shard.apply_if_newer(
                col.tree, key, value, decided_ts
            )

    budget = max(
        0.001, deadline - asyncio.get_event_loop().time()
    )
    if number_of_nodes > 0:
        peer_deadline = _wall_deadline_ms(request, timeout_ms)
        peer_qos = _qos_for_peers(request)
        remote_request = (
            ShardRequest.delete(
                collection_name, key, decided_ts,
                deadline_ms=peer_deadline,
                trace_id=_trace_id_for_peers(ctx),
                qos=peer_qos,
            )
            if is_delete
            else ShardRequest.set(
                collection_name, key, value, decided_ts,
                deadline_ms=peer_deadline,
                trace_id=_trace_id_for_peers(ctx),
                qos=peer_qos,
            )
        )
        expected = (
            ShardResponse.DELETE if is_delete else ShardResponse.SET
        )
        need_remote = min(consistency - 1, number_of_nodes)
        remote = my_shard.send_request_to_replicas(
            remote_request,
            need_remote,
            number_of_nodes,
            expected,
            op_status=op_status,
            key_hash=hash_bytes(key),
        )
        try:
            _local, acks = await asyncio.wait_for(
                asyncio.gather(local_write(), remote), budget
            )
            if len(acks) < need_remote:
                raise asyncio.TimeoutError(
                    f"{opname}: {len(acks)}/{need_remote} "
                    "replica acks"
                )
        except asyncio.TimeoutError as e:
            # POST-decide failure: always a plain Timeout, never the
            # richer _quorum_error kinds.  Clients key retry safety
            # off the kind — Overloaded/PeerDead/not-owned are only
            # ever raised BEFORE a decide (safe to replay), Timeout
            # after a conditional op means decided-but-unacked: the
            # client must surface ambiguity, not blindly replay
            # expectations its own (possibly applied) decide already
            # invalidated.
            raise Timeout(opname) from e
        finally:
            if ctx is not None:
                ctx.mark("quorum")
    else:
        try:
            await asyncio.wait_for(local_write(), budget)
        except asyncio.TimeoutError as e:
            raise Timeout(opname) from e
        finally:
            if ctx is not None:
                ctx.mark("local")


async def _handle_atomic_batch(
    my_shard: MyShard, request: dict
) -> bytes:
    ctx = trace_mod.current()
    collection_name = _extract(request, "collection")
    ops = _extract(request, "ops")
    if not isinstance(ops, (list, tuple)) or not ops:
        raise BadFieldType("ops")
    if len(ops) > ATOMIC_BATCH_MAX_OPS:
        raise BadFieldType(
            f"ops: atomic batch above {ATOMIC_BATCH_MAX_OPS}"
        )
    timeout_ms = request.get("timeout") or DEFAULT_SET_TIMEOUT_MS
    replica_index = request.get("replica_index") or 0
    col = my_shard.get_collection(collection_name)
    _check_membership_epoch(my_shard, request)
    rf = col.replication_factor
    consistency = request.get("consistency")
    if not isinstance(consistency, int):
        consistency = rf
    consistency = min(consistency, rf)
    number_of_nodes = rf - replica_index - 1

    parsed: list = []  # (key_bytes, value_bytes, op_map)
    for i, op in enumerate(ops):
        if not isinstance(op, dict):
            raise BadFieldType("ops")
        if i == 0:
            # Ownership is anchored on ops[0] — the key the client
            # routed the whole batch by.
            key = extract_key(my_shard, op, replica_index)
        else:
            # The other keys are validated by the arc-span check
            # below; an individual owns_key refusal here would turn
            # an unfixable key-choice error into a retryable
            # not-owned, and the client would resync forever.
            key = _encode_field(_extract(op, "key"))
        if op.get("delete"):
            value = TOMBSTONE
        elif "value" in op:
            value = _encode_field(op["value"])
        else:
            raise MissingField("value")
        parsed.append((key, value, op))
    # The commit unit is ONE ring arc: every key must resolve to the
    # same replica set (under vnodes, keys on different arcs fan to
    # different nodes — a spanning "atomic" batch would be two
    # independent commits wearing one name).  Refused as a client
    # error, not a conflict: no retry can fix the key choice.
    groups = _group_keyed_by_replica_set(
        my_shard,
        [(i, key) for i, (key, _v, _op) in enumerate(parsed)],
        number_of_nodes,
    )
    # Downstream-connection groups alone can collapse two distinct
    # arcs (walks (self, X) and (X, self) both fan to just X from
    # here) — those have DIFFERENT deciders, so also require every
    # key's walk prefix before this node to agree.
    walk_prefixes = {
        tuple(my_shard.preceding_replica_nodes(hash_bytes(key)))
        for key, _v, _op in parsed
    }
    if len(groups) > 1 or len(walk_prefixes) > 1:
        raise BadFieldType(
            "ops: atomic batch keys span multiple ring arcs"
        )
    anchor = groups[0][1]
    _atomic_decider_gate(my_shard, anchor, replica_index)
    deadline = asyncio.get_event_loop().time() + timeout_ms / 1000
    op_status: dict = {}
    if ctx is not None:
        ctx.mark("prep")
    keys = [key for key, _v, _op in parsed]
    async with my_shard.atomic_lock(collection_name, anchor):
        _check_membership_epoch(my_shard, request)
        local = col.tree.multi_get(
            keys, suspect_guard=consistency == 1
        )
        budget = max(
            0.001, deadline - asyncio.get_event_loop().time()
        )
        aligned: list = []
        live = _live_arc_peers(my_shard, number_of_nodes, anchor)
        try:
            if live > 0:
                # Same full-live-arc read discipline as single-key
                # CAS: every walk peer not marked Dead must answer
                # (with a well-formed row list), else the whole batch
                # refuses retryably — conditions evaluated against a
                # partial view could approve an op a missed replica
                # already superseded.
                remote = my_shard.send_request_to_replicas(
                    ShardRequest.multi_get(
                        collection_name,
                        keys,
                        deadline_ms=_wall_deadline_ms(
                            request, timeout_ms
                        ),
                        trace_id=_trace_id_for_peers(ctx),
                        qos=_qos_for_peers(request),
                    ),
                    live,
                    number_of_nodes,
                    ShardResponse.MULTI_GET,
                    op_status=op_status,
                    key_hash=anchor,
                )
                local_map, replica_lists = await asyncio.wait_for(
                    asyncio.gather(local, remote), budget
                )
                aligned = [
                    r
                    for r in replica_lists
                    if isinstance(r, (list, tuple))
                    and len(r) == len(keys)
                ]
                if len(aligned) < live:
                    raise asyncio.TimeoutError(
                        "atomic batch read: live replica did "
                        "not answer"
                    )
            else:
                local_map = await asyncio.wait_for(local, budget)
        except asyncio.TimeoutError as e:
            raise _quorum_error(
                my_shard, "atomic_batch", op_status
            ) from e
        if ctx is not None:
            ctx.mark("read")
        # Evaluate EVERY condition against the merged view before
        # touching anything: the batch commits or refuses whole.
        max_ts = 0
        for j, (key, _value, op) in enumerate(parsed):
            entries = []
            lv = local_map.get(key)
            if lv is not None:
                entries.append((bytes(lv[0]), lv[1]))
            for r in aligned:
                v = r[j]
                if v is not None:
                    entries.append((bytes(v[0]), v[1]))
            current = (
                max(entries, key=lambda e: e[1])
                if entries
                else None
            )
            if current is not None:
                max_ts = max(max_ts, current[1])
            detail = _cas_mismatch(op, current, require=False)
            if detail is not None:
                my_shard.batches_refused += 1
                raise CasConflict(
                    f"atomic_batch op {j} on {key!r}: {detail}"
                )
        decided_ts = max(now_nanos(), max_ts + 1)
        entries = [
            (key, value, decided_ts)
            for key, value, _op in parsed
        ]

        async def local_batch():
            # One memtable set_batch application, one WAL
            # append_batch group-commit ticket per chunk — the same
            # commit unit the plain multi_set path rides.
            rejected = await col.tree.set_batch_with_timestamp(
                entries, stale_abort=True
            )
            for k, v, ts in rejected:
                await my_shard.apply_if_newer(col.tree, k, v, ts)

        budget = max(
            0.001, deadline - asyncio.get_event_loop().time()
        )
        try:
            if number_of_nodes > 0:
                # Enforced ack floor, like _replicate_decided: the
                # fan-out resolves with whatever it got, and a batch
                # durable only on the decider is invisible to the
                # next decider's full-live-arc read once this node
                # dies.
                need_remote = min(consistency - 1, number_of_nodes)
                remote = my_shard.send_request_to_replicas(
                    ShardRequest.multi_set(
                        collection_name,
                        [[k, v, decided_ts] for k, v, _t in entries],
                        deadline_ms=_wall_deadline_ms(
                            request, timeout_ms
                        ),
                        trace_id=_trace_id_for_peers(ctx),
                        qos=_qos_for_peers(request),
                    ),
                    need_remote,
                    number_of_nodes,
                    ShardResponse.MULTI_SET,
                    op_status=op_status,
                    key_hash=anchor,
                )
                _local, acks = await asyncio.wait_for(
                    asyncio.gather(local_batch(), remote), budget
                )
                if len(acks) < need_remote:
                    raise asyncio.TimeoutError(
                        f"atomic_batch: {len(acks)}/{need_remote}"
                        " replica acks"
                    )
            else:
                await asyncio.wait_for(local_batch(), budget)
        except asyncio.TimeoutError as e:
            # POST-decide: plain Timeout only (decided but unacked)
            # — see _replicate_decided for the retry-safety contract.
            raise Timeout("atomic_batch") from e
        finally:
            if ctx is not None:
                ctx.mark(
                    "quorum" if number_of_nodes > 0 else "local"
                )
    my_shard.batches_committed += 1
    return msgpack.packb(
        {"ts": decided_ts, "applied": len(parsed)},
        use_bin_type=True,
    )


def _digest_reads_enabled() -> bool:
    import os

    return os.environ.get("DBEEL_NO_DIGEST_READS", "0") in ("", "0")


async def _digest_quorum_round(
    my_shard: MyShard,
    collection_name: str,
    col,
    key: bytes,
    local_value,
    consistency: int,
    number_of_nodes: int,
    timeout_s: float,
    op_status: Optional[dict] = None,
    deadline_ms: Optional[int] = None,
    trace_id: Optional[int] = None,
    qos: Optional[int] = None,
):
    """Digest-read round for an RF>1 get (beyond the reference, which
    ships RF full entries — db_server.rs:318-370): replicas answer
    (timestamp, murmur3_32(value)) digests; the coordinator predicts
    the exact response bytes from its LOCAL entry, so an agreeing
    replica costs a byte-compare — which the native fan-out engine
    (QuorumFan) performs in C — instead of a value payload + unpack.

    Returns True when the local entry is authoritative (every
    consulted replica agreed or was stale; stale ones got read
    repair spawned) — the caller answers from ``local_value``.
    Returns False when some replica holds a NEWER version (or a
    same-timestamp divergent value): the caller must run the
    full-entry round, which merges by max timestamp and read-repairs
    as before.  Raises Timeout like the full round would."""
    digest = pack_message(
        ShardRequest.get_digest(
            collection_name, key, deadline_ms=deadline_ms,
            trace_id=trace_id, qos=qos,
        )
    )
    framed = struct.pack("<I", len(digest)) + digest
    expected = pack_message(ShardResponse.get_digest(local_value))
    local_ts = None if local_value is None else local_value[1]
    if op_status is None:
        op_status = {}
    try:
        results = await asyncio.wait_for(
            my_shard.send_packed_to_replicas(
                framed,
                consistency - 1,
                number_of_nodes,
                expected,
                ShardResponse.GET_DIGEST,
                op_status=op_status,
                key_hash=hash_bytes(key),
            ),
            timeout_s,
        )
    except asyncio.TimeoutError as e:
        raise _quorum_error(my_shard, "get", op_status) from e
    newer = False
    stale = 0
    # Lazy, computed at most once: only needed when a ts-equal
    # digest arrives UNPACKED (traced frames piggyback, so their
    # agreement misses the byte-compare).
    local_hash = None
    for r in results:
        if r is None:
            continue  # byte-matched ack: replica agrees exactly
        if not r:  # []: replica has no entry
            if local_value is not None:
                stale += 1
            continue
        r_ts = r[0]
        if local_ts is None or r_ts > local_ts:
            newer = True  # replica holds a newer version
        elif r_ts < local_ts:
            stale += 1
        else:
            if len(r) > 1 and local_value is not None:
                if local_hash is None:
                    local_hash = murmur3_32(bytes(local_value[0]))
                if r[1] == local_hash:
                    # Same (ts, hash) but the bytes didn't compare
                    # equal — traced frames piggyback a replica
                    # span, so agreement arrives unpacked instead
                    # of as the predicted ack.
                    continue
            # Same timestamp, different value hash: divergence the
            # LWW model says cannot happen — resolve via the full
            # round rather than guessing.
            newer = True
    if newer:
        return False
    if (
        stale
        and local_value is not None
        and my_shard.allow_read_repair()
    ):
        my_shard.spawn(
            _read_repair(
                my_shard,
                collection_name,
                col,
                key,
                bytes(local_value[0]),
                local_value[1],
                number_of_nodes,
            )
        )
    return True


def _merge_quorum_get(
    my_shard: MyShard,
    collection_name: str,
    col,
    key: bytes,
    local_value,
    values,
    number_of_nodes: int,
) -> bytes:
    """The RF>1 get merge brain, shared by the Python punt path and
    the coordinator-assist path so the two can never diverge.
    Conflict resolution: max server timestamp wins
    (db_server.rs:353-363).  Read repair (improvement over the
    reference, which has none — SURVEY §5): any replica that answered
    with a missing or older entry gets the winning version
    re-propagated in the background — rate-capped through the
    shard's token bucket (beyond it the repair is skipped and
    counted; anti-entropy owns the tail); idempotent, since replicas
    keep the newest timestamp and duplicates collapse at compaction.
    Returns the winning value or raises KeyNotFound
    (tombstone/absence)."""
    entries = [(bytes(v[0]), v[1]) for v in values if v is not None]
    stale_acks = sum(1 for v in values if v is None)
    if local_value is not None:
        entries.append((bytes(local_value[0]), local_value[1]))
    else:
        stale_acks += 1
    if entries:
        win_value, win_ts = max(entries, key=lambda e: e[1])
        if (
            stale_acks or any(ts != win_ts for _v, ts in entries)
        ) and my_shard.allow_read_repair():
            my_shard.spawn(
                _read_repair(
                    my_shard,
                    collection_name,
                    col,
                    key,
                    win_value,
                    win_ts,
                    number_of_nodes,
                )
            )
        if win_value != TOMBSTONE:
            return win_value
    if not values and local_value is None and col.tree.reads_suspect:
        # Local-only evidence (consistency=1) on a tree with a
        # quarantine pending repair: the key may have lived in the
        # dropped table, so absence is unproven — answer with a
        # RETRYABLE error and let the client walk to a clean replica
        # instead of asserting KeyNotFound.
        raise CorruptedFile(
            "local miss is suspect: quarantined table pending repair"
        )
    raise KeyNotFound(repr(key))


async def _read_repair(
    my_shard: MyShard,
    collection_name: str,
    col,
    key: bytes,
    value: bytes,
    ts: int,
    number_of_nodes: int,
) -> None:
    from ..flow_events import FlowEvent

    # Spawned from inside a (possibly traced) get: the task copied
    # that op's context, and without this reset the repair's own
    # replica fan-out would absorb its SET acks into the GET's span
    # as phantom replicas.  Background work is never part of the
    # requesting op's latency.
    trace_mod.CURRENT.set(None)
    try:
        # Read-guarded local apply: win_ts came from layer-ordered
        # quorum reads and can be OLDER than a flushed version — a
        # blind insert would recreate the stale-shadow state
        # (PARITY.md deviation #9).  apply_if_newer is also the
        # correct read-repair semantic.
        await my_shard.apply_if_newer(col.tree, key, value, ts)
        if number_of_nodes > 0:
            await my_shard.send_request_to_replicas(
                ShardRequest.set(collection_name, key, value, ts),
                number_of_acks=0,
                number_of_nodes=number_of_nodes,
                expected_kind=ShardResponse.SET,
                key_hash=hash_bytes(key),
            )
        my_shard.flow.notify(FlowEvent.READ_REPAIR)
    except Exception as e:
        log.warning("read repair for %r failed: %s", key, e)


def _frame_response(buf: bytes) -> bytes:
    """Wire envelope: u32-LE length + payload (incl. type byte)."""
    return struct.pack("<I", len(buf)) + buf


def install_native_overload_responses(my_shard: MyShard) -> None:
    """Arm the native hard-overload/deadline answers (all-native
    serving path): the C client plane returns these COMPLETE wire
    frames for shed and dead-on-arrival data verbs, so a flood being
    shed never touches the interpreter it is flooding.  Packed here
    with the same encoder and message text as the interpreted shed
    path (_dispatch) and the dispatcher's deadline drop
    (handle_request), so the two paths answer byte-identically."""
    dp = my_shard.dataplane
    if dp is None:
        return

    def pack(msg: str) -> bytes:
        e = Overloaded(msg)
        return _frame_response(
            msgpack.packb(e.to_wire(), use_bin_type=True)
            + bytes([RESPONSE_ERR])
        )

    dp.set_overload_responses(
        pack(f"shard {my_shard.shard_name} shedding load"),
        pack("client deadline expired before dispatch"),
    )


def _get_timeout_ms(req: dict) -> int:
    """Per-op timeout field, defaulted/sanitized (wire input)."""
    t = req.get("timeout")
    if isinstance(t, int) and t > 0:
        return t
    return DEFAULT_GET_TIMEOUT_MS


KEEPALIVE_IDLE_TIMEOUT_S = 300.0  # reap idle keepalive connections
_REAP_PERIOD_S = 30.0


# Expected replica acks for the packed-fan-out byte compare (the
# native shard plane and the Python handler both produce exactly
# these canonical frames).
_ACK_SET = pack_message(["response", ShardResponse.SET])
_ACK_DELETE = pack_message(["response", ShardResponse.DELETE])


async def _serve_coord(my_shard: MyShard, coord: tuple):
    """Finish one RF>1 client op the native coordinator assist
    already started: the local half is done and ``coord`` carries the
    packed peer frame — fan it out, await the quorum acks (merging
    get results by max timestamp), and answer the client.  Mirrors
    handle_request's set/delete/get branches (timeout => Timeout
    error; results beyond the ack count drain in the background with
    hinted handoff; stale get replicas trigger read repair)."""
    started = time.monotonic()
    (
        op,
        peer_frame,
        keepalive,
        flush_tree,
        consistency,
        timeout_ms,
        col_name,
        local_entry,
        key,
        error_resp,
        defer,
        deadline_ms,
    ) = coord
    if flush_tree is not None:
        my_shard.spawn(flush_tree.flush())
    if error_resp is not None:
        # Entry applied but the WAL append failed: the C side built
        # the error response ("Internal", taxonomy class "other");
        # no fan-out, no re-run.  Count + capture it like the
        # interpreted path's errors — a bad disk under the coord
        # assist must be visible in trace_dump too.
        log.error(
            "native coord %s on %r: wal append failed", op, col_name
        )
        my_shard.metrics.record_error(ERROR_CLASS_OTHER)
        my_shard.metrics.record_request(
            op, started, error_kind=ERROR_CLASS_OTHER
        )
        return error_resp, keepalive
    try:
        col = my_shard.collections.get(col_name)
        if col is None:  # unreachable: registration keeps slots in sync
            raise MissingField(f"collection slot for {col_name!r}")
        rf = col.replication_factor
        consistency = (
            rf if consistency is None else min(consistency, rf)
        )
        if op == "get":
            buf = await _finish_coord_get(
                my_shard,
                col_name,
                col,
                peer_frame,
                local_entry,
                key,
                consistency,
                timeout_ms or DEFAULT_GET_TIMEOUT_MS,
                deadline_ms,
            )
        else:
            is_delete = op == "delete"
            op_status: dict = {}
            try:
                fan_out = my_shard.send_packed_to_replicas(
                    peer_frame,
                    consistency - 1,
                    rf - 1,
                    _ACK_DELETE if is_delete else _ACK_SET,
                    ShardResponse.DELETE
                    if is_delete
                    else ShardResponse.SET,
                    op_status=op_status,
                    key_hash=(
                        hash_bytes(key) if key else None
                    ),
                )
                if defer is not None:
                    # wal-sync: the coordinator's own replica-0 write
                    # only counts once its fdatasync completes — wait
                    # for it alongside the remote acks, inside the
                    # same timeout window (db_server.rs:230-257's
                    # try_join shape).
                    syncer, ticket = defer
                    fan_out = asyncio.gather(
                        fan_out, syncer.wait(ticket)
                    )
                await asyncio.wait_for(
                    fan_out,
                    (timeout_ms or DEFAULT_SET_TIMEOUT_MS) / 1000,
                )
            except asyncio.TimeoutError as e:
                raise _quorum_error(my_shard, op, op_status) from e
            buf = msgpack.packb("OK") + bytes([RESPONSE_BYTES])
    except Exception as e:  # defensive: never kill the connection task
        err_kind = classify_error(e)
        my_shard.metrics.record_error(err_kind)
        buf = _error_response(e)
        my_shard.metrics.record_request(
            op, started, error_kind=err_kind
        )
        _note_completion(my_shard, op, started, timeout_ms, None)
        return buf, keepalive
    my_shard.metrics.record_request(op, started)
    _note_completion(my_shard, op, started, timeout_ms, None)
    return buf, keepalive


async def _finish_coord_get(
    my_shard: MyShard,
    col_name: str,
    col,
    peer_frame: bytes,
    local_entry,
    key: bytes,
    consistency: int,
    timeout_ms: int,
    deadline_ms: Optional[int] = None,
) -> bytes:
    """Quorum-merge for a coordinator-assisted get: digest round
    first (replicas answer (ts, hash); agreement = C byte-compare in
    the fan-out engine), full-entry round only when a replica holds a
    newer version.  The full round combines replica results with the
    native local lookup by max server timestamp (db_server.rs:353-363),
    spawns read repair for stale replicas, and builds the client
    response.  `key` arrives from the C trailer — no peer-frame
    unpack on this path."""
    local_value = (
        None
        if local_entry is None or local_entry[0] == "miss"
        else local_entry
    )
    deadline = (
        asyncio.get_event_loop().time() + timeout_ms / 1000
    )
    op_status: dict = {}
    if _digest_reads_enabled():
        if await _digest_quorum_round(
            my_shard,
            col_name,
            col,
            key,
            local_value,
            consistency,
            col.replication_factor - 1,
            timeout_ms / 1000,
            op_status=op_status,
            deadline_ms=deadline_ms,
        ):
            if (
                local_value is None
                or bytes(local_value[0]) == TOMBSTONE
            ):
                raise KeyNotFound(repr(key))
            return bytes(local_value[0]) + bytes([RESPONSE_OK])
    remote = my_shard.send_packed_to_replicas(
        peer_frame,
        consistency - 1,
        col.replication_factor - 1,
        b"",  # no constant ack for gets: always unpack
        ShardResponse.GET,
        op_status=op_status,
        key_hash=hash_bytes(key) if key else None,
    )
    try:
        values = await asyncio.wait_for(
            remote,
            max(0.001, deadline - asyncio.get_event_loop().time()),
        )
    except asyncio.TimeoutError as e:
        raise _quorum_error(my_shard, "get", op_status) from e
    win_value = _merge_quorum_get(
        my_shard,
        col_name,
        col,
        key,
        local_value,
        values,
        col.replication_factor - 1,
    )
    return win_value + bytes([RESPONSE_OK])


async def _serve_frame(
    my_shard: MyShard,
    request_buf: bytes,
    req: Optional[dict] = None,
    ctx=None,
):
    """One request frame → (response bytes incl. trailing type byte,
    keepalive?).  ``req`` may carry the already-unpacked request map
    (the pipelined dispatcher parses frames once for batching);
    ``ctx`` an active trace span (sampled / client-stamped op, its
    t0 already set to the frame's arrival stamp) — installed as the
    task-tree current trace so the storage and fan-out layers can
    attribute their stages to it."""
    started = time.monotonic()
    op = "invalid"
    keepalive = False
    err_kind = None
    lane_cls = None
    token = (
        trace_mod.CURRENT.set(ctx) if ctx is not None else None
    )
    try:
        if req is None:
            try:
                req = msgpack.unpackb(request_buf, raw=False)
            except Exception as e:
                raise BadFieldType(f"document: {e}") from e
        if not isinstance(req, dict):
            raise BadFieldType("document")
        op = str(req.get("type", "invalid"))
        keepalive = bool(req.get("keepalive"))
        if op in _SHEDDABLE_OPS:
            # QoS lane accounting: this op occupies its class's
            # admission share until it completes; the lane's AIMD
            # window ticks on the release (end pairs with this begin
            # through the except-all below).
            lane_cls = qos_mod.request_class(req)
            my_shard.qos.begin(lane_cls)
        if ctx is not None:
            ctx.op = op
            col = req.get("collection")
            ctx.collection = col if isinstance(col, str) else None
            # Queue wait + unpack + the spawn hop to this task.
            ctx.mark("dispatch")
        payload = await handle_request(my_shard, req)
        if payload is None:
            buf = msgpack.packb("OK") + bytes([RESPONSE_BYTES])
        else:
            buf = payload + bytes([RESPONSE_OK])
    except Exception as e:  # defensive: never kill the connection task
        err_kind = classify_error(e)
        my_shard.metrics.record_error(err_kind)
        buf = _error_response(e)
    finally:
        if token is not None:
            trace_mod.CURRENT.reset(token)
        if lane_cls is not None:
            my_shard.qos.end(lane_cls)
    if ctx is not None:
        # Merge + response pack since the last stage mark; the span
        # then covers arrival → response bytes ready (the coalesced
        # transport write happens on the next loop tick).
        ctx.mark("respond")
        my_shard.trace_recorder.record_span(ctx, err_kind)
    my_shard.metrics.record_request(
        op, started, error_kind=err_kind, traced=ctx is not None
    )
    if isinstance(req, dict):
        _note_completion(
            my_shard,
            op,
            started,
            req.get("timeout"),
            req.get("deadline_ms"),
        )
        if op in ("get", "multi_get"):
            # Tenant byte quota, read side: point reads are billed by
            # their RESPONSE bytes (the request frame the dispatcher
            # billed carries only collection + keys — a tenant
            # streaming large documents out must pay for what it
            # reads, like scan chunks do).  Debt semantics: the real
            # size is only known now, the NEXT op pays.  Writes stay
            # billed by request bytes at dispatch.  Every tenant-
            # stamped frame serves on THIS interpreted path (the C
            # planes punt tenant frames; tenant gets skip the
            # coalesced batch), so this point covers them all.
            my_shard.qos.charge_bytes(
                qos_mod.request_tenant(req),
                req.get("collection"),
                len(buf),
            )
    return buf, keepalive


def _error_response(e: Exception) -> bytes:
    """The error wire envelope, shared by the slow path and the
    coordinator fast path so the two can never diverge.  Must be
    called from an except block (log.exception)."""
    if isinstance(e, DbeelError):
        if not isinstance(e, KeyNotFound):
            log.error("error handling request: %r", e)
        return msgpack.packb(e.to_wire(), use_bin_type=True) + bytes(
            [RESPONSE_ERR]
        )
    log.exception("unexpected error handling request")
    return msgpack.packb(
        ["Internal", str(e)], use_bin_type=True
    ) + bytes([RESPONSE_ERR])


class _DbProtocol(framed.FramedServerProtocol):
    """Raw-protocol serving path (latency pass, VERDICT round 1 #4):
    frame parsing happens in data_received with zero per-request
    timeout/stream machinery — the per-request `asyncio.wait_for` +
    two `readexactly` awaits of the stream version cost ~40µs/op on
    this class of host.  Idle keepalive connections are reaped by one
    per-shard timer instead of a timeout per request.  Wire format
    unchanged: u16-LE request frames; u32-LE response length +
    payload + trailing type byte (db_server.rs:395-428).  Framing and
    backpressure live in FramedServerProtocol, shared with the peer
    plane.

    Pipelined execution (ISSUE 2): up to ``window`` queued frames run
    CONCURRENTLY per connection — a head-of-line quorum fan-out or
    parked WAL ack no longer serializes the frames behind it — while
    responses are RELEASED strictly in arrival order through the
    parked queue (the same mechanism that already ordered wal-sync
    deferred acks), so the wire contract is unchanged: the N-th
    response always answers the N-th request.  Native-fast frames
    found behind a slow frame are answered synchronously at dispatch
    and take an in-order parked slot instead of waiting for the slow
    task.

    Overload control (ISSUE 5): the fixed 32-frame window became a
    per-connection AIMD window driven by the shard's load governor —
    multiplicative decrease toward --overload-window-min while the
    backlog signals read soft-overloaded (at most one halving per
    window of completions), additive recovery to
    --pipeline-window-max once they clear.  Past the governor's HARD
    limit, new data ops are shed at dispatch with the retryable
    ``Overloaded`` error (admin/observability frames always serve);
    frames whose client-supplied deadline already expired in the
    queue are dropped the same way instead of computing dead
    responses."""

    HEADER = 2
    MAX_FRAME = None  # u16 length is its own bound
    # Consecutive queued RF=1 gets coalesce into ONE internal
    # multi_get task (shared memtable/sstable probe setup) — the
    # drain-level mirror of the client's multi_get frames.
    GET_BATCH_MAX = 64

    __slots__ = (
        "last_active",
        "inflight",
        "_slot_free",
        "_get_batch",
        "_get_batch_col",
        "_sampled_next",
        "_ticked_next",
    )

    def __init__(self, my_shard: MyShard) -> None:
        super().__init__(my_shard)
        self.last_active = 0.0
        self.inflight: set = set()
        self._slot_free: "asyncio.Event | None" = None
        self._get_batch: list = []  # (park entry, request map, t0)
        self._get_batch_col: Optional[str] = None
        # Tracing plane: _try_fast drew the sampling tick for the
        # frame it just declined — _dispatch (which pops that same
        # frame first: the fast path is only consulted on an empty
        # queue) routes a fired sample through the interpreted path
        # with a span, and skips its own tick for a frame whose tick
        # was already drawn (_ticked_next) so no frame counts twice.
        self._sampled_next = False
        self._ticked_next = False
        # AIMD pipeline window (overload plane): starts at the max —
        # an idle shard gives new connections full pipelining; the
        # governor shrinks it the moment backlog builds.
        self.window = float(my_shard.config.pipeline_window_max)

    def _registry(self) -> set:
        return self.shard.db_connections

    def _on_connect(self) -> None:
        self.last_active = asyncio.get_event_loop().time()

    def _on_disconnect(self) -> None:
        # Client connections: nothing received is owed once the peer
        # hangs up — stop serving, drop the backlog, and cancel any
        # in-flight pipelined work (a quorum fan-out for a client
        # that left must not keep running detached).
        self.closing = True
        if self.task is not None:
            self.task.cancel()
        for t in list(self.inflight):
            t.cancel()
        if self._slot_free is not None:
            self._slot_free.set()

    def _on_data(self) -> None:
        self.last_active = asyncio.get_event_loop().time()
        self.shard.scheduler.fg_mark()

    def _try_fast(self, frame: bytes) -> int:
        rec = self.shard.trace_recorder
        if rec.sampling:
            # One sampling tick per client frame, drawn HERE for
            # frames the fast path sees.  On every FAST_MISS path
            # this exact frame is the next _dispatch pop (the fast
            # path is only consulted on an empty queue), so the
            # flags map one-to-one; a frame the fast path HANDLES
            # spends its tick (cleared below) — _dispatch ticks only
            # frames that queued without passing through here, so no
            # frame ever draws two ticks.
            self._ticked_next = True
            if rec.tick():
                # The 1-in-N trace sample: decline the fast path so
                # the interpreted dispatcher serves it with real
                # stage marks.
                self._sampled_next = True
                return framed.FAST_MISS
        verdict = self._try_fast_inner(frame)
        if verdict != framed.FAST_MISS:
            self._ticked_next = False
        return verdict

    def _try_fast_inner(self, frame: bytes) -> int:
        # A handled frame is answered synchronously right here — no
        # task hop, no interpreter dispatch.  Only consulted by
        # data_received when nothing is queued or in flight, so the
        # direct transport.write cannot overtake a parked response.
        dp = self.shard.dataplane
        if self.shard.governor.any_should_shed() and (
            dp is None or not dp.shed_armed
        ):
            # Hard overload without the native shed gate (no .so, or
            # a stale one): the native plane must not keep feeding
            # the backlogged memtable/WAL behind the governor's back
            # — queue the frame so _dispatch parses it and sheds
            # data ops (admin frames still serve there).  With the
            # gate armed, the governor's level is already mirrored
            # into C and try_handle answers data verbs with the
            # prebuilt retryable Overloaded response — the flood
            # being shed never reaches the interpreter.
            return framed.FAST_MISS
        if dp is None:
            return framed.FAST_MISS
        started = time.monotonic()
        fast = dp.try_handle(frame)
        if fast is None:
            return framed.FAST_MISS
        resp, keepalive, flush_tree, op, defer, extra = fast
        if extra is not None:
            self._note_native_extra(op, extra)
        if flush_tree is not None:
            self.shard.spawn(flush_tree.flush())
        if defer is not None:
            # wal-sync group commit: the OK leaves once a completed
            # fdatasync covers this append.
            syncer, ticket = defer
            entry = self.park_response(resp, keepalive, op, started)
            syncer.park(ticket, lambda e=entry: self.finish_park(e))
            if not keepalive:
                # Reference semantics: one request per non-keepalive
                # connection — stop applying any already-buffered
                # frames NOW (the parked ack still goes out; the
                # flush closes the transport after writing it).
                self.closing = True
                return framed.FAST_CLOSE
            return framed.FAST_HANDLED
        if self.parked:
            # Earlier responses on this connection still await their
            # sync: queue behind them to preserve order.
            self.park_response(resp, keepalive, op, started, done=True)
            if not keepalive:
                self.closing = True
                return framed.FAST_CLOSE
            return framed.FAST_HANDLED
        self.shard.metrics.record_request(op, started)
        if not keepalive:
            self.closing = True
            self._write_out(resp, close=True)
            return framed.FAST_CLOSE
        self._write_out(resp)
        return framed.FAST_HANDLED

    def _note_native_extra(self, op: str, extra: tuple) -> None:
        """Mirror the governor/metrics bookkeeping the Python path
        would have performed for a frame the C side answered: sheds
        and deadline drops count exactly like their interpreted twins
        (the stats schema cannot depend on which path answered), and
        multi frames record their batch-size histogram point."""
        shard = self.shard
        kind = extra[0]
        if kind == "multi":
            shard.metrics.record_batch_size(extra[1])
            return
        if kind == "shed":
            shard.governor.record_shed(op)
        else:  # "deadline": expired client budget dropped in C
            shard.governor.deadline_drops += 1
        shard.native_drops_by_op[op] = (
            shard.native_drops_by_op.get(op, 0) + 1
        )
        shard.metrics.record_error(ERROR_CLASS_OVERLOAD)
        # Flight recorder: native drops are error completions like
        # their interpreted twins (latency ~0 — the drop IS the
        # point; the ring records that it happened and why).
        shard.trace_recorder.note_op(op, 0, ERROR_CLASS_OVERLOAD)

    # -- pipelined drain --------------------------------------------

    async def _drain(self) -> None:
        try:
            while self.pending and not self.closing:
                # The window-full wait is bypassed only at STANDARD
                # hard (the classic global shed regime, where every
                # popped data frame is cheaply refused) — NOT when
                # merely the batch class reads hard: standard/
                # interactive frames would then pop past the AIMD
                # window and be ADMITTED, bypassing exactly the
                # backpressure the window exists for (review r14).
                # Batch frames behind a full window wait for a slot
                # and shed at dispatch like any popped frame.
                if len(self.inflight) >= max(
                    1, int(self.window)
                ) and not self.shard.governor.should_shed():
                    # Window full: stop popping (pending grows and
                    # the PENDING_HIGH read-pause backpressures the
                    # socket) until a task completes.  Don't sit on
                    # coalesced gets while waiting.  Under HARD
                    # overload the wait is skipped: queued data ops
                    # must shed NOW with a cheap retryable error, not
                    # rot behind a full window until the client's
                    # timeout turns them into opaque Timeouts.
                    self._flush_get_batch()
                    if self._slot_free is None:
                        self._slot_free = asyncio.Event()
                    self._slot_free.clear()
                    try:
                        # Bounded wait: a completion wakes us
                        # instantly; the timeout re-samples the
                        # governor so a backlog crossing the HARD
                        # limit starts shedding the queue even while
                        # every window slot is stuck on slow work.
                        # Keep the poll SHORT even at LEVEL_OK: the
                        # wal-sync plane parks acks behind fdatasync
                        # tickets, and a full window must re-check
                        # promptly or durable-ack pipelines stall a
                        # poll period per refill.
                        await asyncio.wait_for(
                            self._slot_free.wait(), 0.05
                        )
                    except asyncio.TimeoutError:
                        pass
                    continue
                frame, arrived = self.pending.popleft()
                if (
                    self.paused_reading
                    and len(self.pending) < self.PENDING_LOW
                    and not self.transport.is_closing()
                ):
                    self.paused_reading = False
                    self.transport.resume_reading()
                if not self._dispatch(frame, arrived):
                    return
        except asyncio.CancelledError:
            # Shard shutdown (or client disconnect) cancelled us:
            # suppress the finally-respawn, or the orphan drain would
            # outlive the cancellation snapshot and keep writing to
            # trees the shard is about to close.
            self.closing = True
            raise
        finally:
            # Coalesced gets still owe their responses — even on the
            # closing path (earlier in-order responses gate a parked
            # non-keepalive close).
            self._flush_get_batch()
            self.task = None
            # Frames may have arrived while we were finishing.
            if self.pending and not self.closing:
                self.task = self.shard.spawn(self._drain())

    def _dispatch(self, frame: bytes, arrived: float = 0.0) -> bool:
        """Start serving one queued frame without awaiting its result:
        natively-handled frames answer synchronously into an in-order
        parked slot; consecutive RF=1 gets coalesce into one internal
        multi_get task; everything else reserves its slot and runs as
        a windowed concurrent task.  Returns False to stop draining
        this connection.  ``arrived``: frame receipt stamp (queue-wait
        attribution for traced ops)."""
        gov = self.shard.governor
        # Any class at its hard limit (batch trips first): routing
        # gate — per-class decisions happen below once the frame's
        # class is known (interpreted path) or in C (native gate,
        # which holds the per-class levels).
        shedding = gov.any_should_shed()
        rec = self.shard.trace_recorder
        sampled = self._sampled_next
        ticked = self._ticked_next or sampled
        self._sampled_next = False
        self._ticked_next = False
        if not sampled and not ticked and rec.sampling and rec.tick():
            # Frames that queued behind others never consulted
            # _try_fast — the 1-in-N sample is drawn here instead
            # (frames _try_fast declined already drew theirs).
            sampled = True
        dp = self.shard.dataplane
        if sampled:
            # Sampled frame: the interpreted path end to end, so the
            # span gets real stage marks and the peer frames carry
            # the trace id.  1-in-N by construction — the slower path
            # for sampled ops IS the design.
            dp = None
        if shedding and (dp is None or not dp.shed_armed):
            # Hard overload without the native shed gate: only the
            # interpreted shed branch below may answer data ops.
            dp = None
        if dp is not None and (
            (
                self.writable.is_set()
                and len(self.parked) <= self.PENDING_HIGH
            )
            # Shedding with the gate armed bypasses the writability/
            # parked-depth bounds: every parseable data verb comes
            # back as the prebuilt tiny Overloaded frame, terminal
            # and parked in order like any response — the interpreted
            # branch would park the SAME bytes at ~30x the cost, and
            # memory stays bounded by the pending-queue watermark
            # either way.  Real (non-shed) serving keeps the bounds.
            or (shedding and dp.shed_armed)
        ):
            # Queued-frame native fast path: a cheap memtable get
            # behind a slow quorum op is answered NOW; the parked
            # slot keeps its response in arrival order.  Under hard
            # overload this is the native shed gate: data verbs come
            # back as prebuilt Overloaded responses.
            started = time.monotonic()
            fast = dp.try_handle(frame)
            if fast is not None:
                resp, keepalive, flush_tree, op, defer, extra = fast
                if extra is not None:
                    self._note_native_extra(op, extra)
                if flush_tree is not None:
                    self.shard.spawn(flush_tree.flush())
                if defer is not None:
                    syncer, ticket = defer
                    entry = self.park_response(
                        resp, keepalive, op, started
                    )
                    syncer.park(
                        ticket, lambda e=entry: self.finish_park(e)
                    )
                else:
                    self.park_response(
                        resp, keepalive, op, started, done=True
                    )
                if not keepalive:
                    self.closing = True
                    return False
                return True
        # Coordinator assist runs AT DISPATCH (synchronous C call):
        # the local write applies in frame-arrival order, so two
        # pipelined writes to one key keep their server-timestamp
        # order; only the fan-out/quorum wait runs concurrently.
        # Never while shedding — a frame the shed gate punted (admin,
        # exotic shape) must not sneak a data op past admission via
        # the assist; the interpreted branch below sheds it.
        coord = (
            dp.try_handle_coord(frame)
            if dp is not None and not shedding
            else None
        )
        req = None
        keepalive = True
        ctx = None
        if coord is not None:
            keepalive = bool(coord[2])
        else:
            try:
                req = msgpack.unpackb(frame, raw=False)
            except Exception:
                req = None  # _serve_frame re-raises the wire error
            keepalive = isinstance(req, dict) and bool(
                req.get("keepalive")
            )
            tid = (
                _client_trace_id(req)
                if isinstance(req, dict)
                else None
            )
            if tid is not None or sampled:
                # Span for this op: client-stamped ids force one;
                # server sampling assigns one.  t0 = frame arrival,
                # so queue wait is the first attributed stage.
                ctx = trace_mod.TraceCtx(
                    tid
                    if tid is not None
                    else trace_mod.new_trace_id(),
                    t0=arrived or time.monotonic(),
                    client_stamped=tid is not None,
                )
                ctx.mark("queue")
            refusal = None
            if (
                isinstance(req, dict)
                and req.get("type") in _SHEDDABLE_OPS
            ):
                # QoS admission (class-aware shed + tenant quota):
                # per-class hard limits and lane windows shed with
                # the retryable Overloaded; an exhausted tenant
                # bucket refuses with the retryable QuotaExceeded.
                # Cheap (dict lookups + int compares) and evaluated
                # for EVERY interpreted data op — a batch flood sheds
                # here while standard/interactive frames keep
                # serving.
                qp = self.shard.qos
                cls = qos_mod.request_class(req)
                if qp.should_shed(cls):
                    refusal = qp.shed_error(cls)
                    gov.record_shed(str(req.get("type")))
                    gov.python_sheds += 1
                else:
                    try:
                        ops_field = req.get("ops")
                        qp.charge_ops(
                            qos_mod.request_tenant(req),
                            req.get("collection"),
                            len(ops_field)
                            if isinstance(ops_field, (list, tuple))
                            else 1,
                        )
                        # Byte quota meters REQUEST bytes for WRITES
                        # (the frame carries the encoded key and
                        # value).  Reads are billed by their RESPONSE
                        # bytes in _serve_frame — charging their tiny
                        # request frame here too would double-bill
                        # them against the documented contract.
                        # Streamed chunk bytes are charged by the
                        # scan plane.
                        if req.get("type") not in (
                            "get",
                            "multi_get",
                        ):
                            qp.charge_bytes(
                                qos_mod.request_tenant(req),
                                req.get("collection"),
                                len(frame),
                            )
                    except DbeelError as e:  # QuotaExceeded
                        refusal = e
            if refusal is not None:
                # Hard-limit admission: answer a cheap retryable
                # error NOW instead of adding this op to the backlog
                # that made the shard overloaded (or letting a
                # tenant overdraft its bucket).  The error frame
                # takes an in-order parked slot like any response;
                # non-keepalive semantics are preserved.  With the
                # native shed gate armed only frames the C parser
                # punted land here — python_sheds measures exactly
                # that residue (the bench's zero-Python-dispatch
                # acceptance counter).
                op = str(req.get("type"))
                err = refusal
                err_kind = classify_error(err)
                self.shard.metrics.record_error(err_kind)
                # Flight recorder: sheds ARE the interesting tail —
                # always captured (full span when sampled).
                if ctx is not None:
                    ctx.op = op
                    ctx.mark("shed")
                    rec.record_span(ctx, err_kind)
                else:
                    rec.note_op(
                        op,
                        int(
                            (
                                time.monotonic()
                                - (arrived or time.monotonic())
                            )
                            * 1e6
                        ),
                        err_kind,
                    )
                self.park_response(
                    _frame_response(_error_response(err)),
                    keepalive,
                    op,
                    time.monotonic(),
                    done=True,
                )
                if not keepalive:
                    self.closing = True
                    return False
                return True
            if (
                keepalive
                and ctx is None
                and isinstance(req, dict)
                and self._batchable_get(req)
            ):
                # (Traced gets skip coalescing: the span belongs to
                # ONE frame, and sampling is rare enough that losing
                # one batch slot is noise.)
                if (
                    self._get_batch
                    and self._get_batch_col != req.get("collection")
                ):
                    self._flush_get_batch()
                self._get_batch_col = req.get("collection")
                self._get_batch.append(
                    (
                        self.park_response(None, True),
                        req,
                        time.monotonic(),
                    )
                )
                if len(self._get_batch) >= self.GET_BATCH_MAX:
                    self._flush_get_batch()
                return True
        entry = self.park_response(None, True)
        self.shard.metrics.record_pipeline_depth(
            len(self.inflight) + 1
        )
        task = self.shard.spawn(
            self._serve_pipelined(frame, coord, entry, req, ctx)
        )
        self.inflight.add(task)
        task.add_done_callback(self._pipelined_done)
        if not keepalive:
            # Reference semantics: one request per non-keepalive
            # connection — frames already buffered behind it are
            # DROPPED, never executed (the previous sequential drain
            # guaranteed this; the concurrent drain must too).  The
            # in-order parked release still closes the transport
            # right after this frame's own response.
            self.closing = True
            return False
        return True

    def _batchable_get(self, req: dict) -> bool:
        """Eligible for drain-level get coalescing: a keepalive get
        on an RF=1 collection (quorum gets keep their per-frame
        fan-out brain).  Dead-on-arrival gets (expired client
        deadline) are NOT batchable — they fall through to
        handle_request, whose dispatch check drops them with the
        counted retryable error; the batch path used to serve them,
        silently diverging from both the native plane and the
        unbatched path."""
        if req.get("type") != "get" or not req.get("keepalive"):
            return False
        if "tenant" in req or "qos" in req:
            # QoS-stamped gets keep their own frame task so the lane
            # inflight gauge and tenant byte accounting stay exact
            # (the coalesced batch path has no per-frame class walk).
            return False
        deadline_ms = req.get("deadline_ms")
        if (
            isinstance(deadline_ms, int)
            and deadline_ms > 0
            and time.time() * 1000.0 > deadline_ms
        ):
            return False
        col = self.shard.collections.get(req.get("collection"))
        return col is not None and col.replication_factor == 1

    def _flush_get_batch(self) -> None:
        if not self._get_batch:
            return
        items, self._get_batch = self._get_batch, []
        col_name, self._get_batch_col = self._get_batch_col, None
        self.shard.metrics.record_pipeline_depth(
            len(self.inflight) + 1
        )
        task = self.shard.spawn(
            self._serve_get_batch(col_name, items)
        )
        self.inflight.add(task)
        task.add_done_callback(self._pipelined_done)

    async def _serve_get_batch(
        self, col_name: str, items: list
    ) -> None:
        """Serve a run of coalesced pipelined gets with ONE
        LSMTree.multi_get (shared probe setup); each frame still gets
        its own in-order response and its own error surface
        (ownership, absence)."""
        my_shard = self.shard
        my_shard.metrics.record_batch_size(len(items))
        keyed: list = []
        try:
            col = my_shard.get_collection(col_name)
        except DbeelError as e:
            kind = classify_error(e)
            for entry, _req, started in items:
                my_shard.metrics.record_error(kind)
                my_shard.metrics.record_request(
                    "get", started, error_kind=kind
                )
                self.finish_park(
                    entry, _frame_response(_error_response(e))
                )
            return
        # Conservative shared bound: the smallest per-op timeout in
        # the batch — a frame must never wait LONGER because it
        # happened to coalesce with others.
        timeout_ms = min(
            _get_timeout_ms(req) for _entry, req, _started in items
        )
        for entry, req, started in items:
            try:
                key = extract_key(
                    my_shard, req, req.get("replica_index") or 0
                )
                keyed.append((entry, key, started))
            except DbeelError as e:
                kind = classify_error(e)
                my_shard.metrics.record_error(kind)
                my_shard.metrics.record_request(
                    "get", started, error_kind=kind
                )
                self.finish_park(
                    entry, _frame_response(_error_response(e))
                )
        if not keyed:
            return
        err: Optional[DbeelError] = None
        found: dict = {}
        try:
            found = await asyncio.wait_for(
                col.tree.multi_get(
                    [k for _e, k, _s in keyed], suspect_guard=True
                ),
                timeout_ms / 1000,
            )
        except asyncio.TimeoutError:
            err = Timeout("get")
        except Exception as e:  # defensive: entries must resolve
            err = DbeelError(f"Internal: {e}")
        for entry, key, started in keyed:
            hit = found.get(key)
            kind = None
            if err is not None:
                kind = classify_error(err)
                my_shard.metrics.record_error(kind)
                buf = _error_response(err)
            elif hit is None and col.tree.reads_suspect:
                # Quarantine pending repair: a miss is unproven —
                # answer retryably so the client walks replicas.
                bad = CorruptedFile(
                    "local miss is suspect: quarantined table "
                    "pending repair"
                )
                kind = classify_error(bad)
                my_shard.metrics.record_error(kind)
                buf = _error_response(bad)
            elif hit is None or bytes(hit[0]) == TOMBSTONE:
                buf = _error_response(KeyNotFound(repr(key)))
            else:
                buf = bytes(hit[0]) + bytes([RESPONSE_OK])
            my_shard.metrics.record_request(
                "get", started, error_kind=kind
            )
            self.finish_park(entry, _frame_response(buf))

    def _pipelined_done(self, task) -> None:
        self.inflight.discard(task)
        # One completed pipelined unit = one AIMD sample: shrink
        # while the governor reads backlog, recover toward the max
        # once it clears.
        cfg = self.shard.config
        self.aimd_tick(
            float(max(1, cfg.overload_window_min)),
            float(cfg.pipeline_window_max),
        )
        if self._slot_free is not None:
            self._slot_free.set()

    async def _serve_pipelined(
        self,
        frame: bytes,
        coord,
        entry,
        req: Optional[dict] = None,
        ctx=None,
    ) -> None:
        if coord is not None:
            buf, keepalive = await _serve_coord(self.shard, coord)
        else:
            buf, keepalive = await _serve_frame(
                self.shard, frame, req, ctx
            )
        if not keepalive:
            # Reference behavior: one request per connection unless
            # the client opted into keepalive — stop consuming
            # buffered frames now; the in-order parked release
            # closes the transport right after this response.
            self.closing = True
        entry[2] = keepalive
        self.finish_park(entry, _frame_response(buf))

    async def _serve_one(self, frame: bytes) -> bool:
        raise NotImplementedError  # _drain dispatches directly


async def reap_idle_db_connections(my_shard: MyShard) -> None:
    """Single per-shard reaper replacing per-request read timeouts:
    pooled clients that never close() can't pin fds forever."""
    while True:
        await asyncio.sleep(_REAP_PERIOD_S)
        now = asyncio.get_event_loop().time()
        for conn in list(my_shard.db_connections):
            if (
                now - conn.last_active > KEEPALIVE_IDLE_TIMEOUT_S
                and conn.task is None
                and not conn.inflight
                and conn.transport is not None
            ):
                conn.transport.close()


async def bind_db_server(my_shard: MyShard) -> asyncio.Server:
    port = my_shard.config.db_port(my_shard.id)
    server = await asyncio.get_event_loop().create_server(
        lambda: _DbProtocol(my_shard),
        my_shard.config.ip,
        port,
    )
    log.info("listening for clients on %s:%d", my_shard.config.ip, port)
    return server


async def run_db_server(
    my_shard: MyShard, server: Optional[asyncio.Server] = None
) -> None:
    if server is None:
        server = await bind_db_server(my_shard)
    async with server:
        await server.serve_forever()
