"""Compaction coalescer — many shards' merges, one device launch.

The BASELINE.json north star asks that "local_shard's compaction task
scheduler learns to coalesce per-shard compaction jobs into one TPU
launch".  Shards submit their staged merge columns here; jobs arriving
within a small window (or up to ``max_batch``) are padded to a common
(K, P) shape and dispatched as ONE ``vmap``-batched bitonic-merge kernel
call (ops/bitonic.py: merge_runs_prefix_batch_kernel).  Each shard gets
back its own permutation.

The packing itself lives in ``pack_jobs`` — the vmap-ready launch shape
ARCHITECTURE.md describes, computed independently of the device so the
CPU path executes the SAME batched shape today (dryrun-parity tested
against the ops/device_compaction.py twins) and a future device wake
changes only where the kernel runs.  The first successful batched
launch on a real accelerator persists its working config to
``DEVICE_LAST_GOOD.json`` (the device-capture discipline: wakes are
rare, every one must leave an artifact).

One coalescer is shared per process (all shards of a node run on one
loop), matching the reference's one-TPU-per-host deployment picture.
"""

from __future__ import annotations

import asyncio
import logging
from typing import List, Optional, Tuple

import numpy as np

from ..ops import bitonic
from ..storage import columnar

log = logging.getLogger(__name__)


class PackedBatch:
    """One vmap-ready coalesced launch: every job's staged prefixes
    padded to the common (jobs, K, P) stack the batch kernel compiles
    for.  ``pad_frac`` measures the padding waste — the operator's
    answer to "is the window coalescing similar-shaped jobs"."""

    __slots__ = (
        "k", "p", "out_rows", "prefixes", "counts", "bases",
        "real_rows", "pad_frac",
    )

    def __init__(self, k, p, out_rows, prefixes, counts, bases,
                 real_rows, pad_frac) -> None:
        self.k = k
        self.p = p
        self.out_rows = out_rows
        self.prefixes = prefixes
        self.counts = counts
        self.bases = bases
        self.real_rows = real_rows
        self.pad_frac = pad_frac


def pack_jobs(jobs: List[Tuple]) -> PackedBatch:
    """Pack per-shard compaction jobs into ONE vmap-batched launch
    shape: K = max run count (next pow2), P = max run length (next
    pow2), every job's 8-byte key prefixes staged into a common
    (jobs, K, P) stack.  Pure host-side packing — the caller decides
    whether the batched kernel runs on the device or the CPU twin."""
    k = max(bitonic._pow2(max(1, len(rc))) for _, rc, *_ in jobs)
    p = max(
        bitonic._pow2(max(8, max(rc) if rc else 8))
        for _, rc, *_ in jobs
    )
    out_rows = 0
    staged = []
    real_rows = 0
    for cols, rc, *_ in jobs:
        prefixes, counts, bases, rows = bitonic.stage_prefixes(
            cols, rc, k=k, p=p
        )
        staged.append((prefixes, counts, bases))
        out_rows = max(out_rows, rows)
        # Actual staged rows, NOT stage_prefixes' 64Ki-bucketed
        # out_rows — pad_frac must measure real padding waste.
        real_rows += int(sum(rc))
    batch_prefixes = np.stack([s[0] for s in staged])
    batch_counts = np.stack([s[1] for s in staged])
    bases = [s[2] for s in staged]
    padded = len(jobs) * k * p
    pad_frac = round(1.0 - real_rows / padded, 4) if padded else 0.0
    return PackedBatch(
        int(k), int(p), int(out_rows), batch_prefixes, batch_counts,
        bases, real_rows, pad_frac,
    )


class CompactionCoalescer:
    def __init__(
        self, window_s: float = 0.01, max_batch: int = 16
    ) -> None:
        self.window_s = window_s
        self.max_batch = max_batch
        self._pending: List[Tuple] = []
        self._flush_task: Optional[asyncio.Task] = None
        self.launches = 0  # batched kernel launches (observability)
        self.jobs_coalesced = 0
        # Last launch's vmap shape + padding waste (observability:
        # whether the window actually coalesces, and how much of the
        # compiled (jobs, K, P) stack was real data).
        self.last_batch_jobs = 0
        self.last_batch_k = 0
        self.last_batch_p = 0
        self.last_pad_frac = 0.0

    async def submit(
        self, cols: columnar.MergeColumns, run_counts: List[int]
    ) -> np.ndarray:
        """Returns the merged permutation for this job (8B-prefix order;
        ties resolved by the caller via
        columnar.fixup_and_dedup_prefix)."""
        if len(cols) == 0:
            return np.zeros(0, np.int64)
        loop = asyncio.get_event_loop()
        fut: asyncio.Future = loop.create_future()
        self._pending.append((cols, run_counts, fut))
        if len(self._pending) >= self.max_batch:
            self._trigger()
        elif self._flush_task is None:
            self._flush_task = asyncio.ensure_future(
                self._flush_after_window()
            )
        return await fut

    def _trigger(self) -> None:
        if self._flush_task is not None:
            self._flush_task.cancel()
            self._flush_task = None
        asyncio.ensure_future(self._flush())

    async def _flush_after_window(self) -> None:
        try:
            await asyncio.sleep(self.window_s)
        except asyncio.CancelledError:
            return
        self._flush_task = None
        await self._flush()

    async def _flush(self) -> None:
        jobs, self._pending = self._pending, []
        if not jobs:
            return
        try:
            batch = pack_jobs(jobs)

            def run() -> np.ndarray:
                return np.asarray(
                    bitonic.merge_runs_prefix_batch_kernel(
                        batch.prefixes, batch.counts, batch.out_rows
                    )
                )

            packed = await asyncio.get_event_loop().run_in_executor(
                None, run
            )
            self.launches += 1
            self.jobs_coalesced += len(jobs)
            self.last_batch_jobs = len(jobs)
            self.last_batch_k = batch.k
            self.last_batch_p = batch.p
            self.last_pad_frac = batch.pad_frac
            _persist_wake(len(jobs), batch.k, batch.p)

            shift = np.uint32(batch.p.bit_length() - 1)
            mask = np.uint32(batch.p - 1)
            for j, (cols, _rc, fut) in enumerate(jobs):
                n = len(cols)
                row = packed[j, :n]
                run_ids = (row >> shift).astype(np.int64)
                pos = (row & mask).astype(np.int64)
                perm = batch.bases[j][run_ids] + pos
                if not fut.done():
                    fut.set_result(perm)
        except Exception as e:
            log.exception("coalesced merge launch failed")
            for _, _, fut in jobs:
                if not fut.done():
                    fut.set_exception(e)


_default: Optional[CompactionCoalescer] = None
_wake_persisted = False


def _persist_wake(jobs: int, k: int, p: int) -> None:
    """First successful batched launch of the process on a REAL
    accelerator: persist the working coalescer config under
    DEVICE_LAST_GOOD.json (same artifact every other device plane
    feeds), so the next tunnel-down round can cite a known-good
    vmap-batch shape instead of guessing.  CPU-twin launches (today's
    normal mode) skip silently — the artifact records device wakes
    only."""
    global _wake_persisted
    if _wake_persisted:
        return
    try:
        import jax

        platform = jax.devices()[0].platform
    except Exception:
        return
    if platform == "cpu":
        return
    _wake_persisted = True
    try:
        import fcntl
        import json
        import os
        import time

        from ..ops.query_kernels import _last_good_path

        path = _last_good_path()
        with open(path + ".lock", "w") as lock_f:
            fcntl.flock(lock_f, fcntl.LOCK_EX)
            try:
                with open(path) as f:
                    data = json.load(f)
                if not isinstance(data, dict):
                    data = {}
            except Exception:
                data = {}
            data["coalesced_compaction"] = {
                "timestamp_utc": time.strftime(
                    "%Y-%m-%dT%H:%M:%SZ", time.gmtime()
                ),
                "platform": platform,
                "batch_jobs": int(jobs),
                "k": int(k),
                "p": int(p),
                "jax_platforms_env": os.environ.get(
                    "JAX_PLATFORMS", ""
                ),
                "kernel": "merge_runs_prefix_batch_kernel/vmap",
            }
            tmp = path + ".tmp"
            with open(tmp, "w") as f:
                json.dump(data, f, indent=2, sort_keys=True)
            os.replace(tmp, path)
    except Exception as e:  # best-effort artifact, never a failure
        log.warning("DEVICE_LAST_GOOD persist failed: %s", e)


def default_coalescer() -> CompactionCoalescer:
    global _default
    if _default is None:
        _default = CompactionCoalescer()
    return _default


def stats() -> "dict | None":
    """Process-wide coalescer counters for ``get_stats`` (None until
    the first device merge constructs the singleton)."""
    if _default is None:
        return None
    return {
        "launches": _default.launches,
        "jobs_coalesced": _default.jobs_coalesced,
        "last_batch_jobs": _default.last_batch_jobs,
        "last_batch_k": _default.last_batch_k,
        "last_batch_p": _default.last_batch_p,
        "last_pad_frac": _default.last_pad_frac,
    }


class CoalescedDeviceMergeStrategy:
    """CompactionStrategy whose sort rides the shared coalescer.
    Exposes ``merge_async`` (the LSM tree prefers it when present) so
    concurrent shard compactions rendezvous in one launch."""

    name = "coalesced"
    # Intra-merge latency-class hook (see CompactionStrategy.throttle;
    # this class is duck-typed, not a subclass, so it needs its own).
    throttle = None
    # GC-grace cutoff (see CompactionStrategy.tombstone_drop_before) —
    # same duck-typing story: LSMTree.compact() stamps it, but a
    # directly-constructed strategy must default to "keep tombstones".
    tombstone_drop_before = None

    def __init__(
        self, coalescer: Optional[CompactionCoalescer] = None
    ) -> None:
        self.coalescer = coalescer or default_coalescer()

    # Sync fallback (e.g. recovery paths before a loop exists).
    def merge(self, *args, **kwargs):
        from ..ops.device_compaction import DeviceMergeStrategy

        s = DeviceMergeStrategy()
        s.throttle = self.throttle
        s.tombstone_drop_before = self.tombstone_drop_before
        return s.merge(*args, **kwargs)

    async def merge_async(
        self,
        sources,
        dir_path,
        output_index,
        cache,
        keep_tombstones,
        bloom_min_size,
    ):
        from ..ops.device_compaction import DeviceMergeStrategy
        from ..storage.compaction import write_output_columnar

        loop = asyncio.get_event_loop()

        # Big merges: the partitioned native pipeline (off-loop) beats
        # any coalesced single-shot launch; the coalescer exists for
        # many small concurrent per-shard merges.
        total = sum(getattr(s, "data_size", 0) for s in sources)
        if total >= DeviceMergeStrategy.PIPELINE_MIN_BYTES:
            from ..ops.pipeline import pipeline_merge

            result = await loop.run_in_executor(
                None,
                lambda: pipeline_merge(
                    sources,
                    dir_path,
                    output_index,
                    keep_tombstones,
                    bloom_min_size,
                    throttle=self.throttle,
                    tombstone_drop_before=self.tombstone_drop_before,
                ),
            )
            if result is not None:
                return result

        cols = await loop.run_in_executor(
            None, columnar.load_columns, sources
        )
        run_counts = (
            np.bincount(cols.src).tolist() if len(cols) else []
        )
        try:
            perm = await self.coalescer.submit(cols, run_counts)
        except Exception as e:
            log.warning(
                "coalesced device launch failed (%s); host merge", e
            )
            perm = await loop.run_in_executor(
                None, columnar.sort_columns_numpy, cols
            )
            perm = columnar.fixup_long_key_ties(cols, perm)

        def finish():
            from ..storage.compaction import drop_tombstones_mask

            p, keep = columnar.fixup_and_dedup_prefix(
                cols, perm, words=2
            )
            if not keep_tombstones:
                keep = keep & ~drop_tombstones_mask(
                    cols.is_tombstone[p],
                    cols.timestamp[p],
                    self.tombstone_drop_before,
                )
            order = p[keep]
            return write_output_columnar(
                cols, order, dir_path, output_index, cache,
                bloom_min_size, throttle=self.throttle,
                index_fields=self.index_fields,
            )

        return await loop.run_in_executor(None, finish)
