"""MyShard — the per-core cluster state hub.

Role parity with /root/reference/src/shards.rs: one instance per shard
holding config, the consistent hash ring (rotated so this shard sees
itself as origin), known nodes, collections (one LSM tree per
(collection, shard)), the page cache, and gossip dedup counts; plus the
ownership math, replica fan-out with early-ack, gossip send, membership
handling and hash-range migration planning.
"""

from __future__ import annotations

import asyncio
import bisect
import json
import logging
import os
import re
import secrets
import socket
import threading
import time
from dataclasses import dataclass
from typing import Dict, List, Optional, Tuple, Union

import msgpack

from .. import flow_events
from ..config import Config
from ..errors import (
    CollectionAlreadyExists,
    CollectionNotFound,
    ConnectionError_,
    CorruptedFile,
    DbeelError,
    Overloaded,
    Timeout,
)
from ..flow_events import FlowEvent
from ..storage import DEFAULT_TREE_CAPACITY
from ..storage.compaction import get_strategy
from ..storage.lsm_tree import LSMTree, TOMBSTONE
from ..storage.page_cache import PageCache, PartitionPageCache
from ..storage.secondary_index import index_stats, sanitize_index_fields
from ..utils.event import LocalEvent
from ..utils.murmur import hash_bytes, hash_string
from ..cluster import messages as msgs
from ..cluster.local_comm import LocalShardConnection
from ..cluster.messages import (
    ClusterMetadata,
    GossipEvent,
    NodeMetadata,
    ShardEvent,
    ShardRequest,
    ShardResponse,
)
from ..cluster.remote_comm import RemoteShardConnection
from . import trace as trace_mod

log = logging.getLogger(__name__)

# Grace period before migrating to a newly-announced node
# (shards.rs:64-65, NEW_NODE_MIGARTION_DELAY = 500ms).
NEW_NODE_MIGRATION_DELAY_S = 0.5


def _coalescer_stats():
    """Device-compaction coalescer counters for get_stats.  Peeks
    sys.modules instead of importing: the coalescer pulls in the
    jax kernel stack (~2 s cold), and get_stats runs on the serving
    loop — a server that never device-merged reports None for free."""
    import sys

    mod = sys.modules.get("dbeel_tpu.server.coalescer")
    return mod.stats() if mod is not None else None


def _compaction_stats_block():
    """Single-pass compaction counters (ISSUE 15) for get_stats —
    storage/compaction.py is always imported by the time a shard
    serves (the tree construction pulls it), so this is a straight
    read of the process-wide accounting object."""
    from ..storage.compaction import compaction_stats

    return compaction_stats.stats()


def is_between(item: int, start: int, end: int) -> bool:
    """Half-open wrap-around ring range [start, end)
    (shards.rs:103-109)."""
    if end < start:
        return item >= start or item < end
    return start <= item < end


def vnode_tokens(shard_name: str, vnodes: int) -> List[int]:
    """Ring tokens for one shard under the virtual-node ring
    (ISSUE 18).  Token 0 is the legacy ``hash_string(shard_name)`` so
    a vnode node keeps its old primary position and a --vnodes 1 ring
    is bit-identical to the reference's; token k >= 1 salts the shard
    name, so any two cluster members derive the same token list from
    the (name, vnodes) pair alone."""
    tokens = [hash_string(shard_name)]
    for k in range(1, max(1, vnodes)):
        tokens.append(hash_string(f"{shard_name}#v{k}"))
    return tokens


ShardConnection = Union[LocalShardConnection, RemoteShardConnection]


@dataclass
class Shard:
    """Ring entry (shards.rs:80-92)."""

    node_name: str
    name: str
    connection: ShardConnection
    hash: int = -1

    def __post_init__(self):
        if self.hash < 0:
            self.hash = hash_string(self.name)

    @property
    def is_local(self) -> bool:
        return isinstance(self.connection, LocalShardConnection)


@dataclass
class Collection:
    tree: LSMTree
    replication_factor: int
    # Per-collection tenant-quota overrides (ISSUE 15 satellite):
    # DDL-carried {"ops_per_sec": int, "bytes_per_sec": int} rates
    # that beat the --tenant-* flag defaults for THIS collection
    # (None / missing key = use the flag default; 0 disables).
    # Round-tripped through the collection metadata file.
    quotas: "Optional[dict]" = None
    # Secondary-index DDL (ISSUE 17): value fields whose per-SSTable
    # index runs the flush/compaction writers maintain inline and the
    # scan planner consults.  Round-tripped through the metadata file
    # and the create_collection gossip/peer frames like quotas.
    index_fields: "Optional[list]" = None


def _sanitize_quotas(quotas) -> "Optional[dict]":
    """Normalize a DDL-carried quota override map: only the two known
    rate keys survive, as non-negative ints.  Anything malformed (the
    map crosses the wire from clients and gossip) degrades to None —
    the flag defaults — rather than poisoning admission."""
    if not isinstance(quotas, dict):
        return None
    out = {}
    for k in ("ops_per_sec", "bytes_per_sec"):
        v = quotas.get(k)
        if isinstance(v, int) and not isinstance(v, bool) and v >= 0:
            out[k] = v
    return out or None


class MigrationAction:
    SEND = "send"
    DELETE = "delete"


@dataclass
class RangeAndAction:
    start: int
    end: int
    action: str  # MigrationAction
    connection: Optional[ShardConnection] = None


class MyShard:
    def __init__(
        self,
        config: Config,
        shard_id: int,
        shards: List[Shard],
        cache: PageCache,
        local_connection: LocalShardConnection,
    ) -> None:
        self.config = config
        self.id = shard_id
        self.shard_name = f"{config.name}-{shard_id}"
        self.hash = hash_string(self.shard_name)
        self.shards: List[Shard] = list(shards)
        self.nodes: Dict[str, NodeMetadata] = {}
        self.gossip_requests: Dict[Tuple[str, str], int] = {}
        self.collections: Dict[str, Collection] = {}
        self.collections_change_event = LocalEvent()
        # Hinted handoff (improvement over the reference, which has
        # none — SURVEY §5): (collection, key, ts) of mutations whose
        # replica fan-out skipped or failed a node, keyed by that
        # node, replayed on its next Alive (and by the periodic drain
        # loop).  WAL-backed per shard — hints survive a restart.
        from .hints import HintLog

        self.hint_log = HintLog(
            os.path.join(config.dir, f"hints-{shard_id}.log")
            if config.dir and config.hint_ttl_ms > 0
            else None,
            max_per_node=config.hint_max_per_node,
            ttl_s=config.hint_ttl_ms / 1000.0,
        )
        # Ring entries of nodes the failure detector removed: a
        # write's NATURAL replica set does not shrink because a node
        # is down — departed nodes that would have been in the
        # distinct-node walk get hints instead of frames.  Entries
        # leave on the node's next Alive, or when the hint-drain
        # sweep closes their TTL window (a node gone longer than
        # --hint-ttl gets anti-entropy backfill, not hints — and a
        # permanently decommissioned node stops costing a hint per
        # write).
        self.departed_shards: Dict[str, List[Shard]] = {}
        self.departed_at: Dict[str, float] = {}
        # Hash-sorted live+departed ring + its hash list, rebuilt
        # lazily on ring or departed-set changes: _hint_departed runs
        # on EVERY fan-out while a node is down and must not pay a
        # sort per request.
        self._merged_walk_cache: Optional[tuple] = None
        # Failure-aware request plane: nodes the failure detector (or
        # Dead gossip) declared dead.  Fan-outs treat these peers as
        # immediately failed instead of stalling into connect/read
        # timeouts; cleared on Alive.
        self.dead_nodes: set = set()
        # In-flight replica fan-out futures by target node: a death
        # mark cancels them on the spot, so a client op blocked on a
        # black-holed peer unblocks the moment detection fires (the
        # blind window is bounded by the detector, not the timeouts).
        self._inflight_by_node: Dict[str, set] = {}
        # peers.json write serialization (ADVICE r5 low #1): a
        # monotonic snapshot version + lock so an older snapshot can
        # never os.replace a newer one when two executor writes race.
        self._peers_version = 0
        self._peers_written_version = 0
        self._peers_write_lock = threading.Lock()
        self.cache = cache
        # Shares discipline (glommio task-queue parity): serving marks
        # foreground activity; compaction/migration/hint-replay units
        # run under scheduler.bg_slice().
        from .scheduler import ShareScheduler

        self.scheduler = ShareScheduler(
            config.foreground_tasks_shares,
            config.background_tasks_shares,
        )
        from .metrics import ShardMetrics

        self.metrics = ShardMetrics()
        # Tracing plane (PR 9): the per-shard flight recorder.  Full
        # spans for sampled / client-stamped ops, minimal records for
        # every slow or errored op; queried via the admin trace_dump
        # verb.  The metrics hub holds a reference so its completion
        # points feed the slow/error capture.
        from .trace import FlightRecorder

        self.trace_recorder = FlightRecorder(
            sample_every=config.trace_sample,
            slow_op_us=config.slow_op_us,
            capacity=config.trace_ring,
        )
        self.metrics.recorder = self.trace_recorder
        self.metrics.slow_op_us = self.trace_recorder.slow_op_us
        # Overload-control plane (PR 5): one governor per shard folds
        # the backlog signals (admitted work, memtable fill, flush/
        # compaction debt) into an OK/soft/hard level.  Soft delays
        # background units (installed as the scheduler's gate) and
        # shrinks the AIMD connection windows; hard sheds new public
        # data ops with the retryable Overloaded error.
        from .governor import LoadGovernor

        self.governor = LoadGovernor(self, config)
        self.scheduler.overload_gate = self.governor.bg_gate
        # Multi-tenant QoS plane (ISSUE 14): class lanes (weighted
        # admission shares, per-class AIMD windows over the
        # governor's per-class levels) + per-tenant token-bucket
        # quotas enforced at dispatch.
        from .qos import QosPlane

        self.qos = QosPlane(self, config)
        # Streaming scan/range query plane (PR 12): chunked, cursor-
        # resumable scans merged across every ring arc's replicas,
        # admitted chunk-by-chunk through the governor.
        from .scan import ScanPlane

        self.scan_plane = ScanPlane(self, config)
        # Watch/CDC streaming plane (ISSUE 20): bounded per-shard
        # change ring fed at the WAL group-commit release point +
        # resumable coordinator fan-out with durable-state catch-up.
        from .watch import WatchPlane

        self.watch_plane = WatchPlane(self, config)
        # Continuous telemetry plane (PR 11): per-shard time-series
        # ring + health watchdog.  Constructed unconditionally so the
        # get_stats schema never depends on the knob; sampling only
        # arms (riding the governor heartbeat) when
        # --telemetry-interval > 0.
        from .telemetry import ShardTelemetry

        self.telemetry = ShardTelemetry(config)
        # Cluster health view: node name -> freshest per-node health
        # digest (gossip piggybacks + periodic `health` events).
        # Served by the always-on cluster_stats admin verb.
        self.cluster_view: Dict[str, dict] = {}
        # This node's own folded digest (set by the node-managing
        # shard's announce; piggybacked on outgoing gossip frames).
        self.last_node_digest: Optional[dict] = None
        # Snapshot stamps (offline rate derivation from dump pairs):
        # wall/monotonic start anchors + a per-shard monotonic
        # get_stats sequence.
        self.started_at_ms = int(time.time() * 1000)
        self._started_mono = time.monotonic()
        self.stats_seq = 0
        # Anti-entropy transfer counters (observability + the
        # sub-range proportionality test: one diverged key must move
        # ~range/buckets entries, not the whole range).
        self.ae_entries_pushed = 0
        self.ae_entries_fetched = 0
        # Convergence-plane counters (get_stats.convergence).
        self.ae_rounds = 0
        # Local applies performed by convergence machinery (RANGE_PUSH
        # receipts — hint replay and AE pushes land here — plus
        # RANGE_PULL applies): every healed key on THIS shard counts
        # exactly once.
        self.keys_healed = 0
        self.read_repairs = 0
        self.read_repairs_skipped = 0
        # Read-repair token bucket (opportunistic rate cap).
        self._rr_tokens = float(config.read_repair_max_per_sec)
        self._rr_refill_at: Optional[float] = None
        # Native serving data plane (SURVEY §7: compiled hot path,
        # Python keeps the cluster/replication brain).  None when the
        # native library is unavailable — everything then runs the
        # Python path.
        from .dataplane import create_dataplane

        self.dataplane = create_dataplane()
        # Native drops by verb (hard-overload sheds + expired-client-
        # deadline drops the C plane answered): the Python-side half
        # of the native_served_frac accounting — the C counters are
        # totals, the verb split only exists at the mirror point.
        self.native_drops_by_op: Dict[str, int] = {}
        if self.dataplane is not None:
            # Arm the native shed/deadline answers with wire frames
            # byte-identical to the interpreted path's (all-native
            # serving path): the governor mirrors its level in, and
            # at LEVEL_HARD data verbs are answered entirely in C.
            from .db_server import install_native_overload_responses

            install_native_overload_responses(self)
            if config.trace_sample > 0:
                # Native-plane timing (tracing plane): arm the coarse
                # per-verb stage counters (parse/work/reply monotonic
                # deltas) so natively-served ops stay visible to
                # latency accounting.  Off by default — the clock
                # reads cost ~0 but the acceptance bar is "within
                # noise", so unsampled deployments pay literally
                # nothing.
                self.dataplane.set_trace(True)
        # Native quorum fan-out engine (VERDICT r3 #2): the packed
        # peer frame goes out on persistent raw sockets and acks are
        # byte-compared in C; Python keeps quorum counting/merge/
        # repair.  None when the native library lacks it — the
        # asyncio fan-out below is always the fallback.
        from ..cluster.native_fanout import create_quorum_fanout

        self.quorum_fanout = create_quorum_fanout(self)
        self.local_connection = local_connection
        self.stop_event = local_connection.stop_event
        # Live public-API connections (protocol objects) for the
        # per-shard idle reaper.
        self.db_connections: set = set()
        self.remote_connections: set = set()  # peer-plane protocols
        self.flow = flow_events.FlowEventNotifier()
        self._background_tasks: set = set()
        # Set by crash-simulating harnesses: suppresses graceful-stop
        # side effects (death gossip) so a "crash" really is silent.
        self.crashed = False
        # Durability plane (PR 3): WAL EIO/ENOSPC flips the shard into
        # explicit read-only degraded mode — writes answer
        # ShardDegraded (clients walk to healthy replicas), reads keep
        # serving.  Sticky until restart: a disk that errored once is
        # not trusted again on a timer.
        self.degraded = False
        self.degraded_reason: Optional[str] = None
        # Collections with a replica repair pull in flight (quarantine
        # recovery) — dedup so a burst of checksum failures on one
        # table spawns one repair; a quarantine arriving MID-repair
        # marks a rerun instead of being dropped.
        self._repairs_running: set = set()
        self._repairs_rerun: set = set()
        # Background scrub counters (tasks.run_scrub_loop).
        self.scrub_bytes_verified = 0
        self.scrub_cycles = 0
        # Per-boot nonce salted into the gossip source: a restarted
        # node's announcements are a FRESH epidemic, so the seen-count
        # dedup can never suppress a rejoin (the reference's
        # name-keyed dedup silently eats re-announcements from nodes
        # that crash and come back).
        self.boot_id = secrets.token_hex(4)
        # Elastic membership plane (ISSUE 18): this shard's virtual-
        # node ring tokens, the per-node ownership epoch every arc is
        # fenced under (any ring change bumps it; a migration plan
        # stamped with an older epoch aborts between batches, and a
        # write stamped with an older epoch is refused retryably while
        # a migration is live), the in-flight migration task set the
        # fence cancels, and the get_stats.membership counters.
        self.tokens = vnode_tokens(self.shard_name, config.vnodes)
        self.membership_epoch = 1
        self._migration_tasks: set = set()
        self.migrations_started = 0
        self.migrations_resumed = 0
        self.migrations_cancelled = 0
        self.keys_migrated = 0
        self.bytes_migrated = 0
        self.fence_refusals = 0
        # Atomic plane (ISSUE 19): per-arc serialization locks for
        # conditional writes (cas / atomic_batch), keyed by
        # (collection, replica-set tuple) — arcs are finite, so the
        # dict is bounded; plain LWW sets never take them.  The boot
        # barrier refuses to DECIDE conditional writes for a short
        # window after restart (split-decider race: a fallback decider
        # may still be serving this arc until the Alive edge
        # propagates).  Counters feed get_stats.atomic.
        self._atomic_locks: dict = {}
        self._atomic_barrier_until = (
            time.monotonic() + config.cas_boot_barrier_ms / 1000.0
        )
        self.cas_served = 0
        self.cas_conflicts = 0
        self.batches_committed = 0
        self.batches_refused = 0
        if config.vnodes > 1:
            self._expand_vnode_ring()
        self.sort_consistent_hash_ring()

    # ------------------------------------------------------------------
    # Ring (shards.rs:657-670)
    # ------------------------------------------------------------------

    def _expand_vnode_ring(self) -> None:
        """Expand THIS node's ring entries to --vnodes tokens each
        (__init__ receives one entry per local shard).  The extra
        entries share the physical shard's name and connection —
        identity on the ring is by NAME; the hash is just a token.
        Remote nodes' entries are expanded by add_shards_of_nodes from
        their gossiped token lists instead."""
        expanded: List[Shard] = []
        for s in self.shards:
            if s.node_name != self.config.name:
                expanded.append(s)
                continue
            for tok in vnode_tokens(s.name, self.config.vnodes):
                expanded.append(
                    Shard(s.node_name, s.name, s.connection, hash=tok)
                )
        self.shards = expanded

    def sort_consistent_hash_ring(self) -> None:
        """Ascending by hash, rotated so hashes >= self.hash come first —
        shards[0] is this shard, shards[-1] its ring predecessor."""
        threshold = self.hash
        self.shards.sort(
            key=lambda s: (s.hash < threshold, s.hash)
        )
        self._hash_sorted = sorted(self.shards, key=lambda s: s.hash)
        self._sorted_hashes = [s.hash for s in self._hash_sorted]
        # getattr: sort runs once from __init__ before the cache
        # attribute exists.
        if getattr(self, "_merged_walk_cache", None) is not None:
            self._merged_walk_cache = None
        self._refresh_dataplane_ownership()

    def _refresh_dataplane_ownership(self) -> None:
        """Push the replica-0 ownership range down to the native fast
        path.  owns_key(h, 0) == "I am the first shard with hash >= h
        on the wrapping ring", i.e. the cyclic range
        (predecessor_hash, my_hash]."""
        dp = getattr(self, "dataplane", None)
        if dp is None:
            return
        if len(getattr(self, "tokens", ()) or ()) > 1:
            # Vnodes: this shard's ownership is a UNION of arcs the
            # native single-range check can't express — keyed-op
            # ownership gates run in Python (the C plane punts with
            # own_mode 0).
            dp.set_ownership(0)
            return
        if getattr(self, "_migration_tasks", None):
            # Epoch fence engaged: a live migration means stale-epoch
            # writes must be refused at the Python dispatcher — the C
            # plane doesn't read the epoch field, so it punts keyed
            # ops while the fence is up (restored when the last
            # migration task drains).
            dp.set_ownership(0)
            return
        ring = self._sorted_hashes
        if len(ring) < 2:
            dp.set_ownership(1)
            return
        if len(set(ring)) != len(ring):
            # Hash collisions on the ring: bisect tie-breaks get
            # subtle — serve ownership checks from Python only.
            dp.set_ownership(0)
            return
        idx = ring.index(self.hash)
        prev_hash = ring[idx - 1]  # cyclic: idx 0 -> last entry
        dp.set_ownership(2, prev_hash, self.hash)

    def add_shards_of_nodes(self, nodes: List[NodeMetadata]) -> None:
        for node in nodes:
            # Vnode dialect: a peer that gossips token lists gets one
            # ring entry per token, all sharing the shard's pooled
            # connection; a legacy (or --vnodes 1) peer omits them and
            # keeps the single derived token — mixed clusters agree
            # on ownership because every member walks the same union
            # of advertised tokens.
            tokens_by_sid = {}
            if node.tokens is not None and len(node.tokens) == len(
                node.ids
            ):
                tokens_by_sid = dict(zip(node.ids, node.tokens))
            for sid in node.ids:
                address = f"{node.ip}:{node.remote_shard_base_port + sid}"
                name = f"{node.name}-{sid}"
                # Ring entries are long-lived: pool their request
                # streams (replication fan-out latency).
                connection = RemoteShardConnection.from_config(
                    address, self.config, pooled=True
                )
                for tok in tokens_by_sid.get(sid) or [
                    hash_string(name)
                ]:
                    self.shards.append(
                        Shard(
                            node_name=node.name,
                            name=name,
                            connection=connection,
                            hash=int(tok),
                        )
                    )
        self.sort_consistent_hash_ring()

    def owns_key(self, key_hash: int, replica_index: int = 0) -> bool:
        """Am I the replica_index-th distinct-node owner of this hash?

        Deliberate deviation: the reference's owns_key
        (shards.rs:586-618) walks the rotated ring BACKWARD collecting
        distinct nodes, which disagrees with the client's FORWARD
        replica walk (dbeel_client/src/lib.rs:343-395) whenever a node
        has multiple shards interleaved on the ring — correctly-routed
        replica requests then bounce with KeyNotOwnedByShard and the
        client resyncs forever (latent upstream: its tests run one
        shard per node).  We define ownership as the exact mirror of
        the client walk: start at the first shard with hash >= key_hash
        and take the replica_index-th shard on a distinct-node walk.
        Property-tested in tests/test_ring_properties.py."""
        ring = self._hash_sorted
        if len(ring) < 2:
            return True
        import bisect

        start = bisect.bisect_left(
            self._sorted_hashes, key_hash
        ) % len(ring)
        nodes: set = set()
        found = 0
        for off in range(len(ring)):
            s = ring[(start + off) % len(ring)]
            if s.node_name in nodes:
                continue
            if found == replica_index:
                # Identity by NAME, not token: under vnodes this
                # shard appears once per token and any of its entries
                # selected by the walk means ownership.
                return s.name == self.shard_name
            found += 1
            nodes.add(s.node_name)
        return False

    # ------------------------------------------------------------------
    # Atomic plane (ISSUE 19): decider election + per-arc locks
    # ------------------------------------------------------------------

    def preceding_replica_nodes(self, key_hash: int) -> List[str]:
        """Distinct-node walk order BEFORE this node for a key's hash
        (the exact mirror of owns_key's forward walk).  The CAS
        fallback-decider gate: a coordinator may DECIDE a conditional
        write at replica_index>0 only when every node ahead of it on
        the key's walk is marked Dead — otherwise two live deciders
        could serialize the same key independently (split brain)."""
        ring = self._hash_sorted
        if len(ring) < 2:
            return []
        start = bisect.bisect_left(
            self._sorted_hashes, key_hash
        ) % len(ring)
        seen: set = set()
        preceding: List[str] = []
        for off in range(len(ring)):
            s = ring[(start + off) % len(ring)]
            if s.node_name in seen:
                continue
            if s.name == self.shard_name:
                return preceding
            seen.add(s.node_name)
            preceding.append(s.node_name)
        return preceding

    def atomic_barrier_remaining_s(self) -> float:
        """Seconds left in the post-boot conditional-write refusal
        window (0 when expired or disabled)."""
        return max(
            0.0, self._atomic_barrier_until - time.monotonic()
        )

    def atomic_lock(self, collection_name: str, key_hash: int):
        """The per-arc serialization lock for conditional writes:
        every cas/atomic_batch whose key(s) land on the same
        (collection, replica-set) arc decides under ONE asyncio.Lock,
        so read-compare-decide sequences on a key can never
        interleave on this decider.  Keyed by the DISTINCT-NODE
        replica set (not the raw token) so two tokens of one arc
        share a lock."""
        names = tuple(
            n
            for n, _c in self._replica_connections(
                len(self.nodes) or 1, key_hash
            )
        )
        lock_key = (collection_name, names)
        lock = self._atomic_locks.get(lock_key)
        if lock is None:
            lock = self._atomic_locks[lock_key] = asyncio.Lock()
        return lock

    def _atomic_stats(self) -> dict:
        """get_stats.atomic: conditional-write counters.  The numeric
        leaves flatten into the telemetry ring (cas_conflicts_per_s
        and the cas_conflict_storm watchdog read them)."""
        return {
            "cas_served": self.cas_served,
            "cas_conflicts": self.cas_conflicts,
            "batches_committed": self.batches_committed,
            "batches_refused": self.batches_refused,
            "barrier_remaining_ms": int(
                self.atomic_barrier_remaining_s() * 1000
            ),
        }

    @staticmethod
    def get_last_owning_shard(
        shards: List[Shard], start_shard_hash: int, replication_factor: int
    ) -> Optional[Shard]:
        """shards.rs:1074-1101: walk the ring from the first shard with
        hash >= start, collecting distinct nodes; the RF-th is the last
        owner of this range."""
        if not shards:
            return None
        start = next(
            (
                i
                for i, s in enumerate(shards)
                if s.hash >= start_shard_hash
            ),
            0,
        )
        nodes = set()
        found = 0
        i = 0
        index = start % len(shards)
        while i == 0 or index != start:
            shard = shards[index]
            if shard.node_name not in nodes:
                found += 1
                if found == replication_factor:
                    return shard
                nodes.add(shard.node_name)
            i += 1
            index = (start + i) % len(shards)
        return None

    def is_owning_shard(
        self, start_shard_index: int, replication_factor: int
    ) -> bool:
        """shards.rs:1103-1129: is this shard among the RF distinct-node
        owners of the range starting at ring position start_shard_index?"""
        shards = self.shards
        nodes = set()
        found = 0
        i = 0
        index = start_shard_index % len(shards)
        while i == 0 or index != start_shard_index:
            shard = shards[index]
            if shard.node_name not in nodes:
                if shard.name == self.shard_name:
                    return True
                found += 1
                if found == replication_factor:
                    break
                nodes.add(shard.node_name)
            i += 1
            index = (start_shard_index + i) % len(shards)
        return False

    # ------------------------------------------------------------------
    # Node metadata
    # ------------------------------------------------------------------

    def persist_peers(self) -> None:
        """Write the known OTHER nodes to ``{dir}/peers.json`` (wire
        form, tmp+rename) — the system.peers pattern the reference
        lacks: its ring lives only in memory, so a node restarted
        after every OTHER node forgot it (failure detection removed
        it) and whose configured seeds are dead or itself stays
        PARTITIONED ALONE FOREVER — found by chaos_soak.py
        --scale-churn, where the self-seeded restart of the seed node
        split the cluster and 145 acked writes became unreadable
        through it.  Discovery (run.py discover_nodes) merges these
        persisted peers with the configured seeds, so a restart can
        always re-announce to someone who remembers the rest.

        Only the node-managing view (shard 0) writes; every
        membership change (discovery, Alive-add, death) refreshes."""
        if self.id != 0 or not self.config.dir:
            return
        # Snapshot on the loop, write OFF-loop (this fires inside the
        # gossip Alive / dead-node handlers; a slow disk must not
        # stall every shard's request handling — same discipline as
        # the off-loop WAL disposal).
        wire = [n.to_wire() for n in self.nodes.values()]
        # Version assignment happens on the loop thread (serialized);
        # the write-side lock + version check order the executor
        # writes so an older snapshot can never replace a newer one.
        self._peers_version += 1
        version = self._peers_version
        try:
            asyncio.get_running_loop().run_in_executor(
                None, self._persist_peers_write, wire, version
            )
        except RuntimeError:
            # No loop (direct construction in tests).
            self._persist_peers_write(wire, version)

    def _persist_peers_write(self, wire: list, version: int) -> None:
        """Executor-side peers.json write, serialized by version: a
        snapshot older than what's already on disk is a no-op
        (ADVICE r5 low #1 — two racing pool threads used to be able
        to os.replace a newer peers.json with a stale one)."""
        try:
            with self._peers_write_lock:
                if version <= self._peers_written_version:
                    return  # a newer snapshot already landed
                dir_path = self.config.dir
                os.makedirs(dir_path, exist_ok=True)
                path = os.path.join(dir_path, "peers.json")
                # Unique tmp per write: two queued executor writes
                # must not interleave in one tmp file.
                tmp = f"{path}.tmp{os.getpid()}-{version}"
                with open(tmp, "w") as f:
                    json.dump(wire, f)
                os.replace(tmp, path)
                self._peers_written_version = version
        except OSError:
            log.warning("peers.json write failed", exc_info=True)

    def get_node_metadata(self) -> NodeMetadata:
        # All shards of THIS node — local queues in single-process mode,
        # same-node remote entries in the per-core process launcher.
        # Under vnodes a shard appears once per ring token: dedup to
        # physical shard ids and advertise the per-shard token lists
        # (an optional trailing wire slot old peers ignore).
        mine: Dict[int, set] = {}
        for s in self.shards:
            if s.node_name == self.config.name:
                sid = int(s.name.rsplit("-", 1)[1])
                mine.setdefault(sid, set()).add(s.hash)
        ids = sorted(mine)
        return NodeMetadata(
            name=self.config.name,
            ip=self.config.ip,
            remote_shard_base_port=self.config.remote_shard_port,
            ids=ids,
            gossip_port=self.config.gossip_port,
            db_port=self.config.port,
            tokens=[sorted(mine[sid]) for sid in ids],
        )

    def get_nodes(self) -> List[NodeMetadata]:
        nodes = list(self.nodes.values())
        nodes.append(self.get_node_metadata())
        return nodes

    def get_cluster_metadata(self) -> ClusterMetadata:
        return ClusterMetadata(
            nodes=self.get_nodes(),
            collections=[
                (name, c.replication_factor)
                for name, c in self.collections.items()
            ],
            # Clients stamp this epoch on writes; a migration-time
            # fence refuses older stamps retryably (the refused
            # client resyncs metadata — picking up the new epoch AND
            # the new ring — and retries).
            epoch=self.membership_epoch,
        )

    # ------------------------------------------------------------------
    # Collections (shards.rs:259-381)
    # ------------------------------------------------------------------

    def _collection_metadata_path(self, name: str) -> str:
        return os.path.join(self.config.dir, f"{name}.metadata")

    def _collection_dir(self, name: str) -> str:
        return os.path.join(self.config.dir, f"{name}-{self.id}")

    def get_collection(self, name: str) -> Collection:
        col = self.collections.get(name)
        if col is None:
            raise CollectionNotFound(name)
        return col

    def _create_lsm_tree(
        self, name: str, index_fields: "Optional[list]" = None
    ) -> LSMTree:
        capacity = self.config.memtable_capacity or DEFAULT_TREE_CAPACITY
        strategy = get_strategy(self.config.compaction_backend)
        # Intra-merge latency class: the merge worker thread yields CPU
        # to serving between bounded quanta (scheduler.BgThrottle).
        strategy.throttle = self.scheduler.thread_throttle()
        tree = LSMTree.open_or_create(
            self._collection_dir(name),
            cache=PartitionPageCache(name, self.cache),
            capacity=capacity,
            wal_sync=self.config.wal_sync,
            wal_sync_delay_us=self.config.wal_sync_delay_us,
            bloom_min_size=self.config.sstable_bloom_min_size,
            strategy=strategy,
            memtable_kind=self.config.memtable_kind,
            gc_grace_s=self.config.gc_grace_s(),
            index_fields=index_fields,
        )
        # Durability-plane escalation hooks: disk errors degrade the
        # whole shard; a corruption quarantine pulls the lost range
        # back from replicas.
        tree.on_disk_error = self._on_tree_disk_error
        tree.on_quarantine = (
            lambda _tree, n=name: self._on_tree_quarantine(n)
        )
        # Watch/CDC plane (ISSUE 20): every acked mutation — client
        # writes, replica applies, decided CAS outcomes, RANGE_PUSH
        # and hint replays — releases through the tree's commit
        # chokepoints, so this one hook is the complete change feed.
        tree.on_commit = (
            lambda key, value, ts, n=name: self.watch_plane.publish(
                n, key, value, ts
            )
        )
        if self.degraded:
            tree.read_only = True
        return tree

    # -- degraded mode + quarantine repair (durability plane) ----------

    def _on_tree_disk_error(self, exc: BaseException) -> None:
        self.enter_degraded(exc)

    def enter_degraded(self, reason) -> None:
        """Flip this shard read-only after a disk failure: every
        tree rejects writes with ShardDegraded (a retryable class —
        coordinators keep quorum via the other replicas, smart clients
        walk), the native write fast path is suspended so the guard
        cannot be bypassed in C, and reads keep serving.  Sticky until
        operator restart."""
        for col in self.collections.values():
            col.tree.read_only = True
        if self.degraded:
            return
        self.degraded = True
        self.degraded_reason = str(reason)
        log.error(
            "shard %s entering DEGRADED read-only mode: %s",
            self.shard_name,
            reason,
        )
        if self.dataplane is not None:
            # The C client/replica planes answer writes without Python
            # in the loop: unhook them (listener first, or the next
            # write-state notify would re-register) so every request
            # funnels through the guarded Python path.
            for name, col in list(self.collections.items()):
                col.tree.write_state_listener = None
                try:
                    self.dataplane.unregister(name)
                except Exception:
                    log.exception(
                        "dataplane unregister(%s) failed", name
                    )
        self.flow.notify(FlowEvent.SHARD_DEGRADED)

    def _on_tree_quarantine(self, name: str) -> None:
        """A table was quarantined: suspend the collection's native
        fast path (a C miss during the suspect window would read as a
        confident absence) and spawn one replica repair pull."""
        col = self.collections.get(name)
        if col is not None and self.dataplane is not None:
            col.tree.write_state_listener = None
            try:
                self.dataplane.unregister(name)
            except Exception:
                log.exception("dataplane unregister(%s) failed", name)
        if name in self._repairs_running:
            # A repair is mid-pull: its `covered` snapshot doesn't
            # include THIS quarantine — request a follow-up run, or
            # the new quarantine would stay suspect forever.
            self._repairs_rerun.add(name)
            return
        self._repairs_running.add(name)

        async def run(n=name):
            from .tasks import repair_collection

            try:
                while True:
                    self._repairs_rerun.discard(n)
                    await repair_collection(self, n)
                    if n not in self._repairs_rerun:
                        break
            except Exception:
                log.exception("replica repair for %s failed", n)
            finally:
                self._repairs_running.discard(n)
                self._resume_dataplane(n)

        self.spawn(run())

    def _resume_dataplane(self, name: str) -> None:
        col = self.collections.get(name)
        if (
            col is None
            or self.dataplane is None
            or self.degraded
            or col.tree.reads_suspect
        ):
            return
        try:
            self.dataplane.register_tree(
                name,
                col.tree,
                client_plane=col.replication_factor == 1,
            )
        except Exception:
            log.exception("dataplane re-register(%s) failed", name)

    async def rearm(self) -> None:
        """Operator-initiated exit from sticky degraded mode (the
        ROADMAP "re-arm after disk replacement" item): re-run the
        free-space and WAL-append pre-checks on every collection's
        store, clear read-only, and re-register the native write
        plane — no restart.  Raises ShardDegraded (shard STAYS
        degraded) when any pre-check still fails."""
        if not self.degraded and not any(
            col.tree.read_only for col in self.collections.values()
        ):
            return  # already armed: idempotent no-op
        # Probe every tree BEFORE clearing anything: a node with one
        # replaced disk and one still-dead disk must stay degraded.
        for name, col in list(self.collections.items()):
            await col.tree.rearm_precheck()
        for col in self.collections.values():
            col.tree.read_only = False
        self.degraded = False
        self.degraded_reason = None
        log.info("shard %s re-armed: degraded mode cleared",
                 self.shard_name)
        for name, col in list(self.collections.items()):
            # Retry any flush the degraded window refused (frees the
            # memtable) and re-register the native write plane.
            self.spawn(col.tree.flush())
            self._resume_dataplane(name)
        self.flow.notify(FlowEvent.SHARD_REARMED)

    def allow_read_repair(self) -> bool:
        """Token-bucket admission for quorum read-repair pushes:
        beyond the configured rate the repair is skipped (counted;
        anti-entropy owns the tail) so a stale-replica hot spot
        cannot turn every read into a write storm."""
        rate = self.config.read_repair_max_per_sec
        if rate <= 0:
            self.read_repairs += 1
            return True
        now = asyncio.get_event_loop().time()
        if self._rr_refill_at is None:
            self._rr_refill_at = now
        self._rr_tokens = min(
            float(rate),
            self._rr_tokens + (now - self._rr_refill_at) * rate,
        )
        self._rr_refill_at = now
        if self._rr_tokens >= 1.0:
            self._rr_tokens -= 1.0
            self.read_repairs += 1
            return True
        self.read_repairs_skipped += 1
        return False

    def get_stats(self) -> dict:
        """Per-shard observability snapshot (no reference analog —
        SURVEY.md §5 marks tracing/metrics as a gap to improve on)."""
        collections = {}
        for name, col in self.collections.items():
            tree = col.tree
            collections[name] = {
                "memtable_entries": tree.memtable_entries,
                "sstables": tree.sstable_indices_and_sizes(),
                "replication_factor": col.replication_factor,
            }
        from ..storage.wal import group_commit_stats, hub_fsync_errors

        durability = {
            "checksum_failures": 0,
            "quarantined_tables": 0,
            "repairs_completed": 0,
        }
        repairs_pending = 0
        for col in self.collections.values():
            for k in durability:
                durability[k] += col.tree.durability.get(k, 0)
            repairs_pending += col.tree._quarantine_pending
        from ..storage import native as native_mod

        durability.update(
            repairs_pending=repairs_pending,
            scrub_bytes_verified=self.scrub_bytes_verified,
            scrub_cycles=self.scrub_cycles,
            degraded_mode=int(self.degraded),
            degraded_reason=self.degraded_reason,
            # Silent O_DIRECT → buffered degradations in the C
            # streamers (process-wide; unaligned buffers or a
            # filesystem refusing O_DIRECT).  Previously invisible —
            # the only symptom was a throughput cliff.
            odirect_fallbacks=native_mod.odirect_fallbacks(),
        )

        # Overload-control block (PR 5): governor level/signals, shed
        # and deadline-drop counters, AIMD window shape, and the
        # slow-peer outbound-queue sheds summed over ring peers.
        overload = self.governor.stats()
        # One term per physical connection: vnode entries share their
        # shard's connection and must not multiply the sums.
        peer_conns = {
            id(s.connection): s.connection for s in self.shards
        }.values()
        overload["peer_queue_sheds"] = sum(
            getattr(c, "shed_count", 0) for c in peer_conns
        )
        overload["peer_pipelined_ops"] = sum(
            getattr(c, "pipelined_ops", 0) for c in peer_conns
        )
        windows = [
            conn.window
            for conn in self.db_connections
            if getattr(conn, "window", None) is not None
        ]
        overload["window_cur"] = (
            round(sum(windows) / len(windows), 2) if windows else None
        )

        self.stats_seq += 1
        return {
            "shard": self.shard_name,
            # Snapshot stamps (telemetry plane): wall time, process
            # uptime and a monotonic per-shard sequence, so offline
            # tooling can derive rates from any dump PAIR without
            # guessing wall-clock or ordering.
            "ts_ms": int(time.time() * 1000),
            "uptime_s": round(
                time.monotonic() - self._started_mono, 1
            ),
            "stats_seq": self.stats_seq,
            "started_at_ms": self.started_at_ms,
            "durability": durability,
            "overload": overload,
            "nodes_known": len(self.nodes),
            "ring_size": len(self.shards),
            "dead_nodes": sorted(self.dead_nodes),
            # Elastic membership plane (ISSUE 18): ownership epoch
            # (stamped per owned arc below — every arc shares the
            # node epoch by construction, any ring change bumps all),
            # migration lifecycle counters and the fence refusals.
            "membership": self._membership_stats(),
            # Atomic plane (ISSUE 19): conditional-write counters —
            # cas decides/conflicts, batch commits/refusals, and the
            # post-boot decider barrier's remaining window.
            "atomic": self._atomic_stats(),
            "hints_queued": self.hint_log.queued_by_node(),
            # Replica-convergence plane (PR 4): hinted handoff,
            # quorum read-repair, background anti-entropy.
            "convergence": {
                "hints_queued": self.hint_log.queued_total(),
                "hints_recorded": self.hint_log.recorded,
                "hints_replayed": self.hint_log.replayed,
                "hints_expired": self.hint_log.expired,
                "hints_dropped_capacity": (
                    self.hint_log.dropped_capacity
                ),
                "read_repairs": self.read_repairs,
                "read_repairs_skipped": self.read_repairs_skipped,
                "anti_entropy_rounds": self.ae_rounds,
                "keys_healed": self.keys_healed,
            },
            "wal_fsync_errors": hub_fsync_errors(),
            # Group-commit shape: durable acks released per completed
            # fdatasync (process-wide; the batching win of pipelined
            # connections + multi-ops, observable in production).
            "wal_group_commit": group_commit_stats(),
            "cache": {
                "pages": len(self.cache),
                "hits": self.cache.hits,
                "misses": self.cache.misses,
            },
            "scheduler": self.scheduler.stats(),
            "anti_entropy": {
                "entries_pushed": self.ae_entries_pushed,
                "entries_fetched": self.ae_entries_fetched,
            },
            "metrics": self.metrics.snapshot(),
            # Tracing plane (PR 9): flight-recorder counters + the
            # native plane's coarse per-verb stage attribution, so
            # C-served ops are no longer invisible to latency
            # accounting.  Ring CONTENTS come back via trace_dump.
            "trace": {
                "sample_every": self.trace_recorder.sample_every,
                "slow_op_us": self.trace_recorder.slow_op_us,
                "capacity": self.trace_recorder.capacity,
                **self.trace_recorder.stats(),
                "native": (
                    self.dataplane.trace_stats()
                    if self.dataplane is not None
                    else None
                ),
            },
            # Streaming scan plane (PR 12): chunk/byte/cursor/shed
            # counters + the active-chunks gauge.
            "scan": self.scan_plane.stats(),
            "watch": self.watch_plane.stats(),
            # Multi-tenant QoS plane (ISSUE 14): per-class admitted/
            # shed/window/level lanes + per-tenant token balances and
            # throttle counters — reachable through BOTH clients like
            # every other block.
            "qos": self.qos.stats(),
            # Single-pass compaction plane (ISSUE 15): bytes
            # read/written per background pass, inline vs post-hoc
            # sidecar counts, and the read-amplification ratio the
            # tentpole claims (~1.0 single-pass, ~2.0 when outputs
            # are re-read for their sidecar).  Process-wide, like the
            # device-coalescer counters.
            "compaction": _compaction_stats_block(),
            # Secondary-index plane (ISSUE 17): runs built/merged at
            # flush/compaction time, planner hit/miss counters, and
            # quarantines.  The maintenance-cost ratio lives under
            # "compaction" (index_maintenance_amplification) next to
            # the read-amplification claim it rides on.
            "index": index_stats.stats(),
            "device_coalescer": _coalescer_stats(),
            "dataplane": (
                self.dataplane.stats()
                if self.dataplane is not None
                else None
            ),
            # All-native serving path: the measurable claim — what
            # fraction of client data frames were answered without
            # entering the Python dispatcher, by verb group.
            "native_path": self._native_path_stats(),
            "quorum_fanout": (
                self.quorum_fanout.stats()
                if self.quorum_fanout is not None
                else None
            ),
            # Continuous telemetry plane (PR 11): ring/rate summary +
            # the watchdog's machine-readable health verdict.  Ring
            # CONTENTS come back via the telemetry_dump verb; the
            # cluster-wide rollup via cluster_stats.
            "telemetry": self.telemetry.stats_block(),
            "health": self.telemetry.health_block(),
            "collections": collections,
        }

    def _membership_stats(self) -> dict:
        """get_stats.membership: the elastic-membership block.  The
        numeric leaves flatten into the telemetry ring (rates like
        keys_migrated_per_s and the migration_stall watchdog read
        them); arc_epochs is a list (dropped by flatten_stats) —
        observability for humans and the churn soak, not a trend."""
        max_rf = max(
            (
                c.replication_factor
                for c in self.collections.values()
            ),
            default=1,
        )
        try:
            arc_epochs = [
                [start, end, self.membership_epoch]
                for start, end, _peers in self.replica_arcs(max_rf)
            ]
        except Exception:  # pragma: no cover - stats must not raise
            arc_epochs = []
        return {
            "epoch": self.membership_epoch,
            "vnodes": self.config.vnodes,
            "tokens_self": len(self.tokens),
            "ring_tokens": len(self.shards),
            "arcs_owned": len(arc_epochs),
            "arc_epochs": arc_epochs,
            "migrations_started": self.migrations_started,
            "migrations_resumed": self.migrations_resumed,
            "migrations_cancelled": self.migrations_cancelled,
            "migrations_active": len(self._migration_tasks),
            "keys_migrated": self.keys_migrated,
            "bytes_migrated": self.bytes_migrated,
            "fence_refusals": self.fence_refusals,
        }

    def absorb_health_digest(self, digest) -> None:
        """Fold one per-node health digest (gossip piggyback, the
        periodic ``health`` event, or our own announce) into the
        cluster view — freshest (ts_ms, seq) wins, so re-propagated
        epidemic copies can never roll a node's entry backward."""
        if not isinstance(digest, dict):
            return
        node = digest.get("node")
        if not isinstance(node, str) or not node:
            return
        cur = self.cluster_view.get(node)
        if cur is not None:
            if cur.get("boot") and cur.get("boot") == digest.get(
                "boot"
            ):
                # Same incarnation: order by announce seq — a wall
                # clock stepping backwards on the sender must not pin
                # its stale digest cluster-wide until time catches up.
                if (cur.get("seq") or 0) >= (digest.get("seq") or 0):
                    return
            elif (cur.get("ts_ms") or 0, cur.get("seq") or 0) >= (
                (digest.get("ts_ms") or 0, digest.get("seq") or 0)
            ):
                # Cross-boot (restart): wall clock is the only shared
                # ordering left.
                return
        self.cluster_view[node] = digest
        if node == self.config.name:
            # Our own node's folded digest arriving via the local
            # gossip broadcast: sibling shards adopt it, so THEIR
            # cluster_stats (and their outgoing gossip piggybacks)
            # report the whole node, not just themselves.
            self.last_node_digest = digest

    def cluster_stats(self) -> dict:
        """The always-served ``cluster_stats`` admin verb: this
        node's view of every node's health digest (gossip-aggregated)
        — one call to ANY node answers for the whole cluster.  Nodes
        known to the ring but not yet heard from are listed under
        ``missing`` (telemetry off, old version, or just booted)."""
        view = dict(self.cluster_view)
        own = self.last_node_digest
        if own is not None:
            if (self.config.name not in view) or (
                (own.get("ts_ms") or 0)
                > (view[self.config.name].get("ts_ms") or 0)
            ):
                view[self.config.name] = own
        elif self.config.name not in view:
            # Telemetry disabled, or the first announce hasn't
            # reached this shard yet: answer with THIS shard's
            # on-demand digest so the caller always sees at least the
            # node it asked.  Never shadows an absorbed NODE digest —
            # an on-demand single-shard view would under-report the
            # node's other shards with an always-fresher ts_ms.
            view[self.config.name] = self.telemetry.merge_digests(
                self.config.name,
                [self.telemetry.shard_digest(self)],
                boot=self.boot_id,
            )
        known = {self.config.name} | set(self.nodes)
        return {
            "source": self.shard_name,
            "ts_ms": int(time.time() * 1000),
            "nodes": view,
            "nodes_known": len(known),
            "nodes_reporting": len(view),
            "missing": sorted(known - set(view)),
            "dead_nodes": sorted(self.dead_nodes),
        }

    def _native_path_stats(self) -> Optional[dict]:
        """Frames answered entirely in C vs everything this shard
        served, by verb group (set+delete share one C counter).
        Numerators: the C fast-path counters plus the native
        shed/deadline drops mirrored per verb; denominators: the
        request histograms, which count every client frame exactly
        once whichever path answered it.  RF>1 coordinator-assist ops
        are NOT in the numerator — their fan-out await runs in
        Python, so counting them would overstate the claim."""
        if self.dataplane is None:
            return None
        dp = self.dataplane.stats()
        drops = self.native_drops_by_op
        req = self.metrics.requests

        def total(*ops: str) -> int:
            return sum(req[o].count for o in ops if o in req)

        served = {
            "write": dp.get("fast_sets", 0)
            + drops.get("set", 0)
            + drops.get("delete", 0),
            "get": dp.get("fast_gets", 0)
            + dp.get("fast_table_gets", 0)
            + drops.get("get", 0),
            "multi_set": dp.get("fast_multi_sets", 0)
            + drops.get("multi_set", 0),
            "multi_get": dp.get("fast_multi_gets", 0)
            + drops.get("multi_get", 0),
        }
        totals = {
            "write": total("set", "delete"),
            "get": total("get"),
            "multi_set": total("multi_set"),
            "multi_get": total("multi_get"),
        }
        sum_served = sum(served.values())
        sum_total = sum(totals.values())
        return {
            "served": served,
            "totals": totals,
            "by_verb": {
                verb: (
                    round(min(1.0, served[verb] / totals[verb]), 4)
                    if totals[verb]
                    else None
                )
                for verb in served
            },
            "native_served_frac": (
                round(min(1.0, sum_served / sum_total), 4)
                if sum_total
                else None
            ),
            "native_sheds": dp.get("native_sheds", 0),
            "native_deadline_drops": dp.get(
                "native_deadline_drops", 0
            ),
            "python_sheds": self.governor.python_sheds,
            "crc_failures": dp.get("crc_failures", 0),
        }

    async def create_collection(
        self,
        name: str,
        replication_factor: int,
        quotas: "Optional[dict]" = None,
        index: "Optional[list]" = None,
    ) -> None:
        if name in self.collections:
            raise CollectionAlreadyExists(name)
        quotas = _sanitize_quotas(quotas)
        index = sanitize_index_fields(index)
        # Audited sync I/O: DDL is rare (operator-rate, gossiped once)
        # and the metadata file is tens of bytes — an executor hop
        # would cost more than the write.  The fsync CAN stall the
        # loop ~ms-scale on a slow disk; acceptable on this path.
        os.makedirs(self.config.dir, exist_ok=True)  # lint: allow(async-blocking)
        tree = self._create_lsm_tree(name, index_fields=index)
        path = self._collection_metadata_path(name)
        if not os.path.exists(path):
            meta = {"replication_factor": replication_factor}
            if quotas:
                # Per-collection quota overrides ride the same
                # metadata file, so a restart rediscovers them.
                meta["quotas"] = quotas
            if index:
                # Indexed-field DDL persists the same way (ISSUE 17)
                # so a restart keeps maintaining the same runs.
                meta["index"] = index
            # lint: allow(async-blocking)
            with open(path, "wb") as f:
                f.write(msgpack.packb(meta))
                f.flush()
                os.fsync(f.fileno())  # lint: allow(async-blocking)
        self.collections[name] = Collection(
            tree, replication_factor, quotas, index
        )
        if self.dataplane is not None:
            # RF=1: full client-plane fast path.  RF>1: replica plane
            # + coordinator assist; the client plane punts so Python
            # keeps the replication/consistency brain.  (register_tree
            # itself refuses replica-plane registration on a stale
            # .so without the client_ok ABI.)
            self.dataplane.register_tree(
                name, tree, client_plane=replication_factor == 1
            )
        self.collections_change_event.notify()
        self.flow.notify(FlowEvent.COLLECTION_CREATED)

    async def drop_collection(self, name: str) -> None:
        try:
            # Audited sync I/O: one unlink on the operator-rate DDL
            # path (see create_collection).
            os.unlink(self._collection_metadata_path(name))  # lint: allow(async-blocking)
        except OSError:
            pass
        col = self.collections.pop(name, None)
        if col is None:
            raise CollectionNotFound(name)
        if self.dataplane is not None:
            self.dataplane.unregister(name)
        await col.tree.purge()
        self.collections_change_event.notify()
        self.flow.notify(FlowEvent.COLLECTION_DROPPED)

    def get_collections_from_disk(
        self,
    ) -> List[Tuple[str, int, Optional[dict], Optional[list]]]:
        """Disk discovery by '<name>-<id>' directory scan
        (shards.rs:265-311); the third element is the DDL-carried
        per-collection quota override map (or None), the fourth the
        secondary-index field list (or None)."""
        if not os.path.isdir(self.config.dir):
            return []
        pattern = re.compile(rf"^(.*?)\-{self.id}$")
        out = []
        for entry in os.listdir(self.config.dir):
            m = pattern.match(entry)
            if not m or not os.path.isdir(
                os.path.join(self.config.dir, entry)
            ):
                continue
            name = m.group(1)
            meta_path = self._collection_metadata_path(name)
            try:
                with open(meta_path, "rb") as f:
                    meta = msgpack.unpackb(f.read(), raw=False)
                out.append(
                    (
                        name,
                        meta["replication_factor"],
                        meta.get("quotas"),
                        meta.get("index"),
                    )
                )
            except FileNotFoundError:
                log.error(
                    "collection %r has no metadata file on disk", name
                )
        return out

    # ------------------------------------------------------------------
    # Local shard comm (shards.rs:398-460)
    # ------------------------------------------------------------------

    def sibling_connections(self) -> List[ShardConnection]:
        """Other shards of this node: asyncio queues when co-located in
        one process, loopback TCP in the per-core process launcher.
        One connection per PHYSICAL shard (vnode entries share it)."""
        seen: set = set()
        out: List[ShardConnection] = []
        for s in self.shards:
            if (
                s.node_name == self.config.name
                and s.name != self.shard_name
                and s.name not in seen
            ):
                seen.add(s.name)
                out.append(s.connection)
        return out

    async def _send_sibling_message(self, conn, message: list) -> None:
        if isinstance(conn, LocalShardConnection):
            await conn.send_message(self.id, message)
        else:
            await conn.send_event(message)

    async def _send_sibling_request(self, conn, request: list):
        if isinstance(conn, LocalShardConnection):
            return await conn.send_request(self.id, request)
        # Loopback TCP sibling (per-core process mode): tolerate the
        # startup bind race with brief retries before surfacing.
        last: Optional[Exception] = None
        for attempt in range(3):
            try:
                return await conn.send_request(request)
            except DbeelError as e:
                last = e
                await asyncio.sleep(0.2 * (attempt + 1))
        assert last is not None
        raise last

    async def broadcast_message_to_local_shards(self, message: list):
        # Per-sibling failures must not abort the whole broadcast (in
        # per-core process mode a sibling may still be binding).
        results = await asyncio.gather(
            *[
                self._send_sibling_message(c, message)
                for c in self.sibling_connections()
            ],
            return_exceptions=True,
        )
        for r in results:
            if isinstance(r, Exception):
                log.warning("sibling broadcast failed: %s", r)

    async def send_request_to_local_shards(
        self, request: list, expected_kind: str
    ) -> List:
        results = await asyncio.gather(
            *[
                self._send_sibling_request(c, request)
                for c in self.sibling_connections()
            ]
        )
        return [
            msgs.response_to_result(r, expected_kind) for r in results
        ]

    # ------------------------------------------------------------------
    # Replica fan-out (shards.rs:463-543)
    # ------------------------------------------------------------------

    # Hints per RANGE_PUSH frame during a drain (one bg_slice each).
    HINT_REPLAY_PAGE = 256

    def _record_hint(self, node_name: str, request: list) -> None:
        """Queue the (collection, key, ts) of a failed replica
        mutation for replay when the node returns.  Values are NOT
        stored: replay pushes this shard's CURRENT newest entry, so
        repeated overwrites dedup to one hint (newest ts kept) and
        one transfer."""
        if self.config.hint_ttl_ms <= 0:
            return
        kind = request[1] if len(request) > 1 else None
        changed = False
        if kind in (ShardRequest.SET, ShardRequest.DELETE):
            changed = self.hint_log.record(
                node_name,
                request[2],
                bytes(request[3]),
                int(request[5] if kind == ShardRequest.SET else request[4]),
            )
        elif kind == ShardRequest.MULTI_SET:
            col = request[2]
            for key, _value, ts in request[3]:
                changed |= self.hint_log.record(
                    node_name, col, bytes(key), int(ts)
                )
        else:
            return
        if changed:
            self.flow.notify(FlowEvent.HINT_RECORDED)

    def _node_shard_for_key(
        self, key_hash: int, node_name: str
    ) -> Optional[Shard]:
        """The shard of ``node_name`` that serves ``key_hash`` — the
        first shard of that node on the distinct-node replica walk
        (the same walk the client and owns_key use), i.e. the first
        of its shards at/after the hash on the sorted ring."""
        ring = self._hash_sorted
        if not ring:
            return None
        import bisect

        start = bisect.bisect_left(
            self._sorted_hashes, key_hash
        ) % len(ring)
        for off in range(len(ring)):
            s = ring[(start + off) % len(ring)]
            if s.node_name == node_name:
                return s
        return None

    async def replay_hints(self, node_name: str) -> None:
        """Drain this shard's queued hints for ``node_name``: page
        them out oldest-first, resolve each key to its CURRENT local
        newest entry, and push per-target-shard RANGE_PUSH batches
        (applied strictly-newer on the peer).  Bounded rate: each
        page runs under a bg_slice and the configured keys/sec
        ceiling paces consecutive pages."""
        if not self.hint_log.has(node_name):
            return
        rate = max(1, self.config.hint_drain_keys_per_sec)
        replayed = 0
        failed = False
        while not failed:
            page = self.hint_log.take_page(
                node_name, self.HINT_REPLAY_PAGE
            )
            if not page:
                break
            # Resolve hints to current entries, grouped by the target
            # node's serving shard for each key (multi-shard nodes:
            # the replica walk picks a specific shard per key).
            # Each batch keeps its source hints so a failed push can
            # requeue exactly what it owed.
            batches: Dict[str, list] = {}  # -> [shard, col, entries, hints]
            async with self.scheduler.bg_slice():
                for hint in page:
                    col_name, key, _ts, _created = hint
                    col = self.collections.get(col_name)
                    if col is None:
                        continue  # collection dropped: hint is moot
                    try:
                        entry = await col.tree.get_entry(bytes(key))
                    except DbeelError:
                        # Suspect local read (quarantine pending):
                        # keep the hint for a later drain.
                        self.hint_log.requeue(node_name, [hint])
                        failed = True
                        continue
                    if entry is None:
                        # Nothing to push (tombstone GC'd before the
                        # drain): anti-entropy owns the remainder.
                        self.hint_log.expired += 1
                        continue
                    shard = self._node_shard_for_key(
                        hash_bytes(bytes(key)), node_name
                    )
                    if shard is None:
                        failed = True  # node left the ring again
                        self.hint_log.requeue(node_name, [hint])
                        continue
                    value, local_ts = entry
                    batch = batches.setdefault(
                        f"{shard.name}/{col_name}",
                        [shard, col_name, [], []],
                    )
                    batch[2].append(
                        [bytes(key), bytes(value), int(local_ts)]
                    )
                    batch[3].append(hint)
            for shard, col_name, entries, hints in batches.values():
                if failed:
                    self.hint_log.requeue(node_name, hints)
                    continue
                try:
                    msgs.response_to_result(
                        await shard.connection.send_request(
                            ShardRequest.range_push(col_name, entries)
                        ),
                        ShardResponse.RANGE_PUSH,
                    )
                    replayed += len(entries)
                except (DbeelError, OSError) as e:
                    log.warning(
                        "hint replay to %s stopped after %d: %s",
                        node_name,
                        replayed,
                        e,
                    )
                    failed = True
                    # Untried/failed hints go back on the queue (node
                    # raced back down etc.) — never dropped.
                    self.hint_log.requeue(node_name, hints)
            if failed:
                break
            # Bounded drain rate.
            await asyncio.sleep(len(page) / rate)
        if replayed or not failed:
            # A COMPLETE drain persists the drop marker even when it
            # replayed nothing (everything TTL-expired / resolved to
            # absent entries) — without it, a restart resurrects the
            # dead records from the log.  Partial (failed) drains
            # skip the marker: its watermark would erase the
            # requeued survivors across a restart.
            self.hint_log.mark_drained(
                node_name, replayed, drop_marker=not failed
            )
        if replayed:
            log.info(
                "replayed %d hints to %s", replayed, node_name
            )
        self.flow.notify(FlowEvent.HINTS_REPLAYED)

    async def send_request_to_replicas(
        self,
        request: list,
        number_of_acks: int,
        number_of_nodes: int,
        expected_kind: str,
        op_status: Optional[dict] = None,
        key_hash: Optional[int] = None,
    ) -> List:
        """Send to the first ``number_of_nodes`` distinct-node remote
        shards on the ring (anchored at ``key_hash`` when given — see
        ``_replica_connections``); return after ``number_of_acks``
        successes, drain the rest in the background.  Failed mutations
        become hints for the unreachable node.  ``op_status`` (when
        given) collects failure context for the caller's error frame:
        ``peer_dead`` / ``peer_unreachable`` flags."""
        self._hint_departed(number_of_nodes, lambda: request)
        return await self._fan_out_to_replicas(
            lambda c: c.send_request(request),
            lambda resp: msgs.response_to_result(
                resp, expected_kind
            ),
            lambda: request,
            number_of_acks,
            number_of_nodes,
            connections=self._replica_connections(
                number_of_nodes, key_hash
            ),
            op_status=op_status,
        )

    async def send_packed_to_replicas(
        self,
        framed: bytes,
        number_of_acks: int,
        number_of_nodes: int,
        expected_ack: bytes,
        expected_kind: str,
        op_status: Optional[dict] = None,
        key_hash: Optional[int] = None,
    ) -> List:
        """send_request_to_replicas for a PRE-PACKED peer frame (the
        native coordinator's output): the frame bytes go out verbatim
        on each replica stream, and each raw response payload is
        byte-compared against ``expected_ack`` — msgpack unpacking
        happens only on mismatch (error responses) or when a failed
        replica's hint needs the request as a list.  When the native
        fan-out engine has live streams to every replica, the whole
        mechanism (socket writes, response reads, ack compare) runs
        in C (shards.rs:463-543 parity); the asyncio fan-out below is
        the always-available fallback."""
        hint_request_fn = lambda: msgs.unpack_message(framed[4:])  # noqa: E731
        self._hint_departed(number_of_nodes, hint_request_fn)
        connections = self._replica_connections(
            number_of_nodes, key_hash
        )
        if op_status is not None:
            # The walk targets, for PeerDead-vs-Timeout attribution
            # at the op deadline (db_server._quorum_error) — recorded
            # here so the native fan-out path carries them too.
            op_status["targets"] = [n for n, _c in connections]
        qf = self.quorum_fanout
        if (
            qf is not None
            # Traced ops keep the asyncio fan-out: the span needs
            # per-replica RTTs and the piggybacked replica stage
            # summaries, which the C engine's byte-compare path
            # doesn't surface.  Sampling is 1-in-N — the slow path
            # for sampled ops is the design, not a regression.
            and trace_mod.current() is None
            and all(
                not isinstance(c, LocalShardConnection)
                for _n, c in connections
            )
        ):
            fut = qf.try_submit(
                framed,
                connections,
                number_of_acks,
                expected_ack,
                expected_kind,
                hint_request_fn,
            )
            if fut is not None:
                return await fut

        def interpret(payload):
            # Traced fan-outs absorb the replica's piggybacked span
            # before interpretation and hand back an already-unpacked
            # list — accept both forms.
            if isinstance(payload, (bytes, bytearray)):
                if payload == expected_ack:
                    return None
                payload = msgs.unpack_message(payload)
            return msgs.response_to_result(payload, expected_kind)

        return await self._fan_out_to_replicas(
            lambda c: c.send_packed(framed),
            interpret,
            hint_request_fn,
            number_of_acks,
            number_of_nodes,
            connections=connections,
            op_status=op_status,
        )

    def _hint_departed(
        self, number_of_nodes: int, hint_request_fn
    ) -> None:
        """Record hints for departed (detector-removed) nodes that
        would sit in this mutation's replica set had they been alive.
        The live fan-out walks the SHRUNK ring (availability: the
        next distinct node genuinely owns the slot now), but the
        down node's copy must not silently stay stale until
        anti-entropy — the write's natural owner gets a hint, and the
        Alive-edge drain replays it the moment the node returns.

        Walk budget: ``number_of_nodes`` live slots PLUS one slot per
        departed node — a departed node occupies a replica slot
        without consuming the live budget, so a coordinator serving
        at replica_index>0 BECAUSE the primary is down (its remaining
        live fan-out may be zero nodes) still hints that primary.
        Slightly over-hints when a departed node sits just past the
        natural set (harmless: replay is an idempotent strictly-newer
        push, and cap+TTL bound it).  The walk is anchored at each
        KEY's hash (bisect into the merged ring), not at this
        coordinator's rotation front — under vnodes a departed node's
        many arcs each resolve to their true per-arc replica slots."""
        if (
            not self.departed_shards
            or self.config.hint_ttl_ms <= 0
        ):
            return
        request = hint_request_fn()
        kind = request[1] if len(request) > 1 else None
        if kind in (ShardRequest.SET, ShardRequest.DELETE):
            keys = [bytes(request[3])]
        elif kind == ShardRequest.MULTI_SET:
            keys = [bytes(k) for k, _v, _t in request[3]]
        else:
            return  # reads never hint
        # The merged ring: live + departed token entries, hash-sorted
        # with a parallel hash list for per-key bisect — the replica
        # walk of the UNSHRUNK ring, anchored at each key's own hash
        # (under vnodes a departed node owns many small arcs, and the
        # coordinator's rotation order says nothing about which arc a
        # key lands in).  Cached: rebuilt only when the ring or the
        # departed set changes.
        merged = self._merged_walk_cache
        if merged is None:
            entries = list(self.shards)
            for shards in self.departed_shards.values():
                entries.extend(shards)
            entries.sort(key=lambda s: (s.hash, s.name))
            merged = (entries, [s.hash for s in entries])
            self._merged_walk_cache = merged
        entries, hashes = merged
        if not entries:
            return
        budget = number_of_nodes + len(self.departed_shards)
        targets: set = set()
        for key in keys:
            start = bisect.bisect_left(
                hashes, hash_bytes(key)
            ) % len(entries)
            nodes: set = set()
            for off in range(len(entries)):
                if len(nodes) >= budget:
                    break
                s = entries[(start + off) % len(entries)]
                if (
                    s.node_name == self.config.name
                    or s.node_name in nodes
                ):
                    continue
                nodes.add(s.node_name)
                if s.node_name in self.departed_shards:
                    targets.add(s.node_name)
        # Deliberately NOT op_status["peer_dead"]: the live fan-out
        # may satisfy the quorum fine — a later deadline expiry on a
        # merely-slow LIVE peer must report Timeout, not PeerDead
        # (the flag is set only where a requested target actually
        # failed).  MULTI_SET hints the whole batch to every departed
        # target its keys touch (harmless over-hint: replay is an
        # idempotent strictly-newer push).
        for name in sorted(targets):
            self._record_hint(name, request)

    def _replica_connections(
        self,
        number_of_nodes: int,
        key_hash: Optional[int] = None,
    ) -> List[tuple]:
        """First ``number_of_nodes`` distinct-OTHER-node shards on the
        ring (the replica walk, shards.rs:463-497).  With ``key_hash``
        the walk is anchored at the key's own ring position (bisect
        into the hash-sorted ring) — required under vnodes, where a
        key may route to this shard via a secondary token and the
        rotation front (anchored at the PRIMARY token) would pick the
        wrong replica set.  Without it, the legacy rotation-front walk
        (identical to the anchored walk when every shard has one
        token and the key landed on this shard's own arc).

        The anchored walk collects the key's full distinct-node order
        and rotates PAST this node before truncating: a coordinator
        serving at replica_index>0 must fan to the replicas AFTER it
        in ring order (the earlier ones already failed the client),
        exactly what the rotation-front walk did for one token."""
        nodes: set = set()
        connections: List[tuple] = []
        if key_hash is None:
            for s in self.shards:
                # Replicas live on OTHER nodes (same-node shards may
                # be remote connections under the per-core process
                # launcher).
                if (
                    s.node_name == self.config.name
                    or s.node_name in nodes
                ):
                    continue
                nodes.add(s.node_name)
                connections.append((s.node_name, s.connection))
                if len(connections) >= number_of_nodes:
                    break
            return connections
        ring = self._hash_sorted
        if not ring:
            return connections
        start = bisect.bisect_left(
            self._sorted_hashes, key_hash
        ) % len(ring)
        ordered: List[tuple] = []  # full distinct-node walk order
        self_idx = None
        for off in range(len(ring)):
            s = ring[(start + off) % len(ring)]
            if s.node_name in nodes:
                continue
            nodes.add(s.node_name)
            if s.node_name == self.config.name:
                self_idx = len(ordered)
            ordered.append((s.node_name, s.connection))
        if self_idx is not None:
            ordered = (
                ordered[self_idx + 1:] + ordered[:self_idx]
            )
        return [
            (n, c)
            for n, c in ordered[:number_of_nodes]
            if n != self.config.name
        ]

    def _register_inflight(self, name: str, fut) -> None:
        self._inflight_by_node.setdefault(name, set()).add(fut)

    def _unregister_inflight(self, name: str, fut) -> None:
        futs = self._inflight_by_node.get(name)
        if futs is not None:
            futs.discard(fut)
            if not futs:
                self._inflight_by_node.pop(name, None)

    async def _fan_out_to_replicas(
        self,
        send_fn,
        interpret_fn,
        hint_request_fn,
        number_of_acks: int,
        number_of_nodes: int,
        connections: Optional[List[tuple]] = None,
        op_status: Optional[dict] = None,
    ) -> List:
        if connections is None:
            connections = self._replica_connections(number_of_nodes)
        if op_status is not None:
            op_status.setdefault(
                "targets", [name for name, _c in connections]
            )
        # Tracing plane: captured HERE (the caller's context) — the
        # fan-out body runs as a spawned task and must attribute its
        # per-replica RTTs / piggybacked spans to the op that asked.
        trace_ctx = trace_mod.current()

        result_future: asyncio.Future = (
            asyncio.get_event_loop().create_future()
        )

        async def fan_out():
            # A peer already marked Dead is failed on the spot: hint
            # and skip the dial — never a connect/read-timeout stall
            # (the detector-bounded blind window, failure_detector.rs
            # parity).  Normally ring removal keeps dead peers out of
            # the walk; this guard covers the race where the
            # connection list was snapshotted before the death mark.
            live = []
            for name, c in connections:
                if name in self.dead_nodes:
                    if op_status is not None:
                        op_status["peer_dead"] = True
                    log.warning(
                        "replica %s marked Dead: fast-fail", name
                    )
                    self._record_hint(name, hint_request_fn())
                else:
                    live.append((name, c))
            fut_node = {}
            fut_sent = {}
            for name, c in live:
                fut = asyncio.ensure_future(send_fn(c))
                fut_node[fut] = name
                fut_sent[fut] = time.monotonic()
                self._register_inflight(name, fut)
            pending = set(fut_node)

            def settle(fut) -> bool:
                """Interpret one finished future; True on ack."""
                name = fut_node[fut]
                self._unregister_inflight(name, fut)
                try:
                    payload = fut.result()
                    if trace_ctx is not None:
                        # Per-replica attribution: send→settle RTT
                        # plus the stage summary the replica
                        # piggybacked (stripped before interpret so
                        # the quorum brain sees the base frame).
                        payload = trace_ctx.absorb_peer(
                            name,
                            int(
                                (time.monotonic() - fut_sent[fut])
                                * 1e6
                            ),
                            payload,
                        )
                    results.append(interpret_fn(payload))
                    return True
                except asyncio.CancelledError:
                    # Cancelled by a mid-flight death mark
                    # (handle_dead_node): treat like unreachable.
                    if op_status is not None:
                        op_status["peer_dead"] = True
                    log.error(
                        "replica %s died mid-request: cancelled", name
                    )
                    self._record_hint(name, hint_request_fn())
                except (Timeout, ConnectionError_, Overloaded) as e:
                    # Unreachable replica — or one that SHED the
                    # request (its governor past the hard limit, its
                    # deadline check found the work already dead, or
                    # OUR capped outbound queue to it refused the
                    # send): either way the mutation did not land
                    # there, so it hands off to the hint path and the
                    # drain/anti-entropy converge it later.
                    if op_status is not None:
                        if isinstance(e, Overloaded):
                            op_status["peer_overloaded"] = True
                        else:
                            op_status["peer_unreachable"] = True
                    log.error("unreachable replica: %s", e)
                    self._record_hint(name, hint_request_fn())
                except DbeelError as e:
                    # Application-level error from a LIVE replica
                    # (e.g. CollectionNotFound during gossip
                    # propagation) — not a handoff case.
                    log.error(
                        "failed response from replica: %s", e
                    )
                except Exception as e:
                    # Anything else (garbled pooled-stream payload
                    # blowing up interpret_fn, etc.): log and keep
                    # settling — one bad response must not abort the
                    # drain and strand the other stragglers unhinted.
                    log.error("replica response failed: %s", e)
                return False

            results: List = []
            acks = 0
            try:
                # Like the reference (shards.rs:500-528): gather up to
                # number_of_acks successes; when replicas run out early,
                # return what we have rather than erroring.
                while pending and acks < number_of_acks:
                    done, pending = await asyncio.wait(
                        pending, return_when=asyncio.FIRST_COMPLETED
                    )
                    for fut in done:
                        if settle(fut):
                            acks += 1
            finally:
                if not result_future.done():
                    result_future.set_result(results)
            # Drain stragglers in the background (shards.rs:530-539).
            for fut in pending:
                try:
                    await asyncio.wait({fut})
                except asyncio.CancelledError:
                    # The fan-out TASK itself is being cancelled
                    # (shard shutdown): stop draining.
                    raise
                settle(fut)

        self.spawn(fan_out())
        return await result_future

    def spawn(self, coro) -> asyncio.Task:
        """Detached background task tied to this shard."""
        task = asyncio.ensure_future(coro)
        self._background_tasks.add(task)
        task.add_done_callback(self._background_tasks.discard)
        return task

    # ------------------------------------------------------------------
    # Message dispatch (shards.rs:695-790)
    # ------------------------------------------------------------------

    async def handle_shard_message(
        self, message: list
    ) -> Optional[list]:
        tag = message[0]
        if tag == "event":
            await self.handle_shard_event(message)
            return None
        if tag == "request":
            try:
                return await self.handle_shard_request(message)
            except DbeelError as e:
                return ShardResponse.error(e)
        return None

    async def handle_shard_event(self, event: list) -> None:
        kind = event[1]
        if kind == ShardEvent.GOSSIP:
            await self.handle_gossip_event(event[2])
        elif kind == ShardEvent.SET:
            _, _, collection, key, value, ts = event
            await self.handle_shard_set_message(
                collection, bytes(key), bytes(value), ts
            )

    async def handle_shard_set_message(
        self, collection: str, key: bytes, value: bytes, ts: int
    ) -> None:
        col = self.get_collection(collection)
        if ts <= col.tree.max_flushed_ts or not (
            await col.tree.set_with_timestamp(
                key, value, ts, stale_abort=True
            )
        ):
            # A delayed/replayed write (hint replay, late replica
            # frame, migration stream) no newer than the flushed
            # layers: blind memtable insert would put the OLDER ts in
            # a NEWER layer and first-match point reads would serve
            # it — apply read-guarded instead.  stale_abort closes
            # the race where a capacity wait spans a flush swap that
            # advances the watermark past ts.
            await self.apply_if_newer(col.tree, key, value, ts)
        self.flow.notify(FlowEvent.ITEM_SET_FROM_SHARD_MESSAGE)

    # Position of the OPTIONAL trailing wall-clock deadline (ms) a
    # coordinator appends to data-op peer frames (deadline
    # propagation, PR 5).  Old-dialect frames simply lack the element.
    _PEER_DEADLINE_INDEX = {
        ShardRequest.SET: 6,
        ShardRequest.DELETE: 5,
        ShardRequest.GET: 4,
        ShardRequest.GET_DIGEST: 4,
        ShardRequest.MULTI_SET: 4,
        ShardRequest.MULTI_GET: 4,
    }

    # Position of the OPTIONAL trailing trace id (tracing plane,
    # PR 9): always exactly one slot past the deadline (a sampled
    # frame with no real budget carries a 0 deadline placeholder, so
    # the trace slot never shifts).  The wire-parity lint pins each
    # entry to deadline_index + 1 and checks the C parser's
    # trace-dialect (`want + 2`) handling in lockstep.
    _PEER_TRACE_INDEX = {
        ShardRequest.SET: 7,
        ShardRequest.DELETE: 6,
        ShardRequest.GET: 5,
        ShardRequest.GET_DIGEST: 5,
        ShardRequest.MULTI_SET: 5,
        ShardRequest.MULTI_GET: 5,
    }

    # Position of the OPTIONAL trailing QoS class id (QoS plane,
    # ISSUE 14): always exactly one slot past the trace id (frames
    # with a qos element carry 0 placeholders for an absent deadline
    # and trace, so the slot never shifts).  The wire-parity lint
    # pins each entry to trace_index + 1 and checks the C parser's
    # qos-dialect (`want + 3`) recognition in lockstep.  Old-dialect
    # frames simply lack the element (class = standard).
    _PEER_QOS_INDEX = {
        ShardRequest.SET: 8,
        ShardRequest.DELETE: 7,
        ShardRequest.GET: 6,
        ShardRequest.GET_DIGEST: 6,
        ShardRequest.MULTI_SET: 6,
        ShardRequest.MULTI_GET: 6,
    }

    # Fixed arity of the SCAN peer frame (scan plane, PR 12; spec
    # element appended by the query compute plane, PR 13; qos class
    # appended by the QoS plane, ISSUE 14):
    # ["request","scan",collection,start,end,start_after,prefix,
    #  limit,max_bytes,with_values,spec,qos].  No trailing deadline/
    # trace dialects — scan pages ride pooled round trips like the
    # RANGE_* family (the chunk-level deadline lives on the CLIENT
    # frame); old-arity frames (no spec and/or no qos) are accepted.
    # Lint-pinned against the encoder and both C sources
    # (analysis/wire_parity.py; native kScanPeerArity).
    _SCAN_PEER_ARITY = 12

    # WATCH_FEED peer frame arity (watch/CDC plane, ISSUE 20):
    # [request, watch_feed, collection, boot_epoch, after_seq,
    #  ranges, limit, max_bytes, spec, qos].  Feed pages ride pooled
    # round trips like SCAN.  Lint-pinned against the encoder
    # (analysis/wire_parity.py); the C planes have no watch tokens —
    # an old .so falls through to this interpreted branch.
    _WATCH_PEER_ARITY = 10

    @classmethod
    def peer_qos_class(cls, request) -> int:
        """QoS class a coordinator stamped on this data-op peer frame
        (QoS plane, ISSUE 14); STANDARD when absent (old dialect) or
        malformed — an unknown stamp degrades to the default lane."""
        from . import qos as qos_mod

        if (
            not isinstance(request, (list, tuple))
            or len(request) < 2
            or request[0] != "request"
        ):
            return qos_mod.QOS_STANDARD
        idx = cls._PEER_QOS_INDEX.get(request[1])
        if idx is None or len(request) <= idx:
            return qos_mod.QOS_STANDARD
        return qos_mod.class_of(request[idx])

    @classmethod
    def peer_trace_id(cls, request) -> Optional[int]:
        """Trace id a coordinator stamped on this peer frame, or None.
        A replica serving a traced frame piggybacks its own stage
        summary (a few u32 micros) on the response so the
        coordinator's span decomposes end to end."""
        if (
            not isinstance(request, (list, tuple))
            or len(request) < 2
            or request[0] != "request"
        ):
            return None
        idx = cls._PEER_TRACE_INDEX.get(request[1])
        if idx is None or len(request) <= idx:
            return None
        tid = request[idx]
        if isinstance(tid, int) and tid > 0:
            return tid
        return None

    def _peer_deadline_expired(self, request: list) -> bool:
        """True when the frame carries a propagated deadline that has
        already passed: the coordinator's client gave up — computing
        the response would burn replica CPU on a dead answer.  Wall
        clock, like the LWW timestamps (same loose-sync caveat)."""
        idx = self._PEER_DEADLINE_INDEX.get(request[1])
        if idx is None or len(request) <= idx:
            return False
        deadline_ms = request[idx]
        if not isinstance(deadline_ms, int) or deadline_ms <= 0:
            return False
        import time as _time

        if _time.time() * 1000.0 <= deadline_ms:
            return False
        self.governor.replica_deadline_drops += 1
        return True

    async def handle_shard_request(self, request: list) -> list:
        kind = request[1]
        if kind in self._PEER_DEADLINE_INDEX:
            # QoS plane: account the propagated class so a bulk
            # load's replica-side writes are visible in the batch
            # lane cluster-wide.  Accounting only — the peer plane
            # never sheds (replica work keeps quorums alive).
            self.qos.note_peer(self.peer_qos_class(request))
        if kind in self._PEER_DEADLINE_INDEX and (
            self._peer_deadline_expired(request)
        ):
            # Deadline propagation: drop dead work instead of
            # computing it.  The error is retryable; for mutations the
            # coordinator's fan-out records a hint, so convergence
            # still owns the write (settle() treats Overloaded like an
            # unreachable replica).
            raise Overloaded(
                "deadline expired before the replica served it"
            )
        if kind == ShardRequest.PING:
            return ShardResponse.pong()
        if kind == ShardRequest.TELEMETRY_DIGEST:
            # Telemetry plane: intra-node aggregation — the managing
            # shard folds sibling digests into the per-node digest it
            # gossips.  Cheap (ring reads only), never sheds.
            return ShardResponse.telemetry_digest(
                self.telemetry.shard_digest(self)
            )
        if kind == ShardRequest.REARM:
            await self.rearm()
            return ShardResponse.empty(ShardResponse.REARM)
        if kind == ShardRequest.GET_METADATA:
            return ShardResponse.get_metadata(self.get_nodes())
        if kind == ShardRequest.GET_COLLECTIONS:
            # Tail dialect mirrors the CREATE_COLLECTION frame: quotas
            # at slot 2 (None placeholder when only an index is set),
            # index field list at slot 3.  Short entries stay short so
            # pre-ISSUE-15/17 peers parse them unchanged.
            entries = []
            for n, c in self.collections.items():
                e = [n, c.replication_factor]
                if c.quotas or c.index_fields:
                    e.append(c.quotas if c.quotas else None)
                if c.index_fields:
                    e.append(c.index_fields)
                entries.append(tuple(e))
            return ShardResponse.get_collections(entries)
        if kind == ShardRequest.CREATE_COLLECTION:
            # Optional 5th element: per-collection quota overrides
            # (old-arity frames from pre-ISSUE-15 peers are accepted);
            # optional 6th: secondary-index field list (ISSUE 17).
            await self.create_collection(
                request[2],
                request[3],
                request[4] if len(request) > 4 else None,
                request[5] if len(request) > 5 else None,
            )
            return ShardResponse.empty(ShardResponse.CREATE_COLLECTION)
        if kind == ShardRequest.DROP_COLLECTION:
            await self.drop_collection(request[2])
            return ShardResponse.empty(ShardResponse.DROP_COLLECTION)
        if kind == ShardRequest.SET:
            await self.handle_shard_set_message(
                request[2], bytes(request[3]), bytes(request[4]), request[5]
            )
            return ShardResponse.empty(ShardResponse.SET)
        if kind == ShardRequest.DELETE:
            col = self.collections.get(request[2])
            if col is not None:
                ts = request[4]
                if ts <= col.tree.max_flushed_ts or not (
                    await col.tree.set_with_timestamp(
                        bytes(request[3]), TOMBSTONE, ts,
                        stale_abort=True,
                    )
                ):
                    await self.apply_if_newer(
                        col.tree, bytes(request[3]), TOMBSTONE, ts
                    )
            return ShardResponse.empty(ShardResponse.DELETE)
        if kind == ShardRequest.MULTI_SET:
            # Batched replica mutations (one peer frame per client
            # batch): apply under the same watermark discipline as
            # single SETs — fresh entries batch-insert (one WAL
            # append_batch / one sync ticket), stale or race-rejected
            # ones fall back to the read-guarded apply.
            col = self.get_collection(request[2])
            entries = [
                (bytes(k), bytes(v), int(ts))
                for k, v, ts in request[3]
            ]
            wm = col.tree.max_flushed_ts
            fresh = [e for e in entries if e[2] > wm]
            stale = [e for e in entries if e[2] <= wm]
            if fresh:
                stale.extend(
                    await col.tree.set_batch_with_timestamp(
                        fresh, stale_abort=True
                    )
                )
            for k, v, ts in stale:
                await self.apply_if_newer(col.tree, k, v, ts)
            if entries:
                self.flow.notify(FlowEvent.ITEM_SET_FROM_SHARD_MESSAGE)
            return ShardResponse.empty(ShardResponse.MULTI_SET)
        if kind == ShardRequest.MULTI_GET:
            col = self.collections.get(request[2])
            keys = [bytes(k) for k in request[3]]
            if col is None:
                return ShardResponse.multi_get([None] * len(keys))
            found = await col.tree.multi_get(keys)
            if col.tree.reads_suspect and any(
                found.get(k) is None for k in keys
            ):
                self._raise_suspect_miss()
            return ShardResponse.multi_get(
                [found.get(k) for k in keys]
            )
        if kind == ShardRequest.GET:
            col = self.collections.get(request[2])
            entry = None
            if col is not None:
                entry = await col.tree.get_entry(bytes(request[3]))
                if entry is None and col.tree.reads_suspect:
                    self._raise_suspect_miss()
            return ShardResponse.get(entry)
        if kind == ShardRequest.GET_DIGEST:
            # Digest read (quorum-get fast path): answer (ts, value
            # hash) only — canonical bytes, so an agreeing replica's
            # response byte-matches the coordinator's prediction and
            # never needs unpacking (fan-out engine compares in C).
            col = self.collections.get(request[2])
            entry = None
            if col is not None:
                entry = await col.tree.get_entry(bytes(request[3]))
                if entry is None and col.tree.reads_suspect:
                    self._raise_suspect_miss()
            return ShardResponse.get_digest(entry)
        if kind == ShardRequest.RANGE_DIGEST:
            col = self.collections.get(request[2])
            # Clamp both sides: nb sizes two local allocations, so an
            # unbounded peer-supplied count would be an OOM lever on
            # the network-facing port.
            nb = int(request[5]) if len(request) > 5 else 1
            nb = max(1, min(nb, 65536))
            counts, digests = [0] * nb, [0] * nb
            if col is not None:
                # Peer-side anti-entropy scans are background work too:
                # they must defer to this shard's own serving traffic.
                async with self.scheduler.bg_slice():
                    counts, digests = await self.compute_range_digests(
                        col.tree, request[3], request[4], nb
                    )
            return ShardResponse.range_digest(counts, digests)
        if kind == ShardRequest.RANGE_PULL:
            col = self.collections.get(request[2])
            entries: list = []
            if col is not None:
                buckets = None
                nb = 0
                if len(request) > 8 and request[7] is not None:
                    buckets = {int(b) for b in request[7]}
                    nb = int(request[8])
                async with self.scheduler.bg_slice():
                    entries = await self.collect_range_page(
                        col.tree,
                        request[3],
                        request[4],
                        bytes(request[5])
                        if request[5] is not None
                        else None,
                        int(request[6]),
                        buckets,
                        nb,
                    )
            return ShardResponse.range_pull(entries)
        if kind == ShardRequest.SCAN:
            # Streaming scan page (scan plane, PR 12): one ordered,
            # byte-bounded page of this shard's entries in the arc —
            # served by the vectorized ScanStage (per-entry fallback),
            # tombstones included so the coordinator merge can
            # suppress stale live values.  Deliberately NOT under
            # scheduler.bg_slice: the chunk was already admitted and
            # paced by the COORDINATOR's governor (shed at hard, park
            # at soft, byte-budgeted slices), and the unit payback would
            # throttle the scan against its own chunk frames' fg
            # marks (measured: 4x idle per page).  Page cost is
            # bounded by the byte clamp + cooperative yields inside
            # scan_page.  Clamps mirror RANGE_PULL's: peer-supplied
            # sizes must not become allocation levers.
            col = self.collections.get(request[2])
            entries: list = []
            more = False
            if col is None:
                return ShardResponse.scan(entries, more)
            start_after = (
                bytes(request[5])
                if request[5] is not None
                else None
            )
            prefix = bytes(request[6]) if request[6] else None
            limit = max(1, min(int(request[7]), 65536))
            max_bytes = max(
                4096, min(int(request[8]), 16 << 20)
            )
            spec = request[10] if len(request) > 10 else None
            # QoS plane: scan pages account in the stamped lane
            # (batch by default — old-arity frames lack the element).
            from . import qos as qos_mod

            self.qos.note_peer(
                qos_mod.class_of(request[11])
                if len(request) > 11
                else qos_mod.QOS_BATCH
            )
            if spec is not None:
                # Query compute plane (PR 13): predicate/aggregate
                # pushdown over the staged columns.  The peer spec
                # is re-validated HERE — it crossed a network — and
                # a malformed one raises the clean BadFieldType the
                # coordinator relays, never a shard death.
                from .. import query as Q

                where, agg, mode = Q.unpack_peer_spec(spec)
                (
                    entries,
                    more,
                    cover,
                    scanned_rows,
                    scanned_bytes,
                    partial,
                    eval_path,
                ) = await col.tree.scan_filter_page(
                    int(request[3]),
                    int(request[4]),
                    start_after,
                    prefix,
                    limit,
                    max_bytes,
                    bool(request[9]),
                    where,
                    agg,
                    mode,
                )
                if eval_path == "device":
                    self.scan_plane.device_evals += 1
                elif eval_path == "indexed":
                    self.scan_plane.indexed_evals += 1
                elif eval_path in ("numpy", "golden"):
                    self.scan_plane.fallback_evals += 1
                if partial is not None:
                    self.scan_plane.agg_partials += 1
                return ShardResponse.scan(
                    entries,
                    more,
                    cover,
                    scanned_rows,
                    scanned_bytes,
                    partial,
                )
            entries, more = await col.tree.scan_page(
                int(request[3]),
                int(request[4]),
                start_after,
                prefix,
                limit,
                max_bytes,
                bool(request[9]),
            )
            return ShardResponse.scan(entries, more)
        if kind == ShardRequest.WATCH_FEED:
            # Watch/CDC plane (ISSUE 20): one change-ring page —
            # events strictly after the coordinator's (boot, seq)
            # position, filtered to the collection / hash ranges /
            # optional spec.  Served off the in-memory ring with an
            # O(1) empty fast path (no storage I/O, no bg_slice);
            # clamps mirror SCAN's so peer-supplied sizes never
            # become allocation levers.  An unknown collection is
            # answered as an empty at-tail page (status 0): watch
            # interest can reach a replica before the collection's
            # create gossip does.
            from . import qos as qos_mod

            self.qos.note_peer(
                qos_mod.class_of(request[9])
                if len(request) > 9
                else qos_mod.QOS_BATCH
            )
            # Watched collections must not serve writes natively:
            # sticky-suspend this replica's fast path the moment
            # feed interest lands (see WatchPlane.suspend_native).
            self.watch_plane.suspend_native(request[2])
            ranges = (
                [[int(r[0]), int(r[1])] for r in request[5]]
                if request[5]
                else None
            )
            limit = max(1, min(int(request[6]), 65536))
            max_bytes = max(
                4096, min(int(request[7]), 16 << 20)
            )
            spec = request[8] if len(request) > 8 else None
            events, boot_epoch, tail_seq, status = (
                self.watch_plane.feed_page(
                    request[2],
                    int(request[3]),
                    int(request[4]),
                    ranges,
                    limit,
                    max_bytes,
                    bytes(spec) if spec is not None else None,
                )
            )
            return ShardResponse.watch_feed(
                events, boot_epoch, tail_seq, status
            )
        if kind == ShardRequest.RANGE_PUSH:
            col = self.collections.get(request[2])
            if col is None:
                raise CollectionNotFound(request[2])
            pushed_any = False
            async with self.scheduler.bg_slice():
                for key, value, ts in request[3]:
                    if await self.apply_if_newer(
                        col.tree, bytes(key), bytes(value), int(ts)
                    ):
                        # Convergence accounting: hint replays and AE
                        # pushes land here — every key this shard was
                        # missing (or held stale) counts once.
                        self.keys_healed += 1
                        pushed_any = True
            if pushed_any:
                # The items WERE set from a shard message: fire the
                # same milestone the Set-frame path fires, so tests
                # waiting on replicated writes stay event-driven.
                self.flow.notify(FlowEvent.ITEM_SET_FROM_SHARD_MESSAGE)
            return ShardResponse.empty(ShardResponse.RANGE_PUSH)
        raise DbeelError(f"unknown shard request {kind!r}")

    @staticmethod
    def _raise_suspect_miss() -> None:
        """A replica-plane miss on a tree with a quarantine pending
        repair is unproven (the key may have lived in the dropped
        table): answer the coordinator with a retryable error frame
        instead of a confident absence it would merge as truth."""
        from ..errors import CorruptedFile

        raise CorruptedFile(
            "replica miss is suspect: quarantined table pending repair"
        )

    # ------------------------------------------------------------------
    # Anti-entropy primitives (no reference analog — SURVEY §5 lists
    # anti-entropy as a gap in the reference's replication design,
    # alongside hinted handoff and read repair, both also added here)
    # ------------------------------------------------------------------

    @staticmethod
    def _merge_adjacent_arcs(
        arcs: List[list],
    ) -> List[Tuple[int, int, List[Shard]]]:
        """Merge ring-adjacent arcs with identical shard-name sets
        (arcs arrive in sorted-ring order, so arc i's end is arc
        i+1's start; the (last, first) pair wraps)."""
        merged: List[list] = []
        for arc in arcs:
            if (
                merged
                and merged[-1][1] == arc[0]
                and {s.name for s in merged[-1][2]}
                == {s.name for s in arc[2]}
            ):
                merged[-1][1] = arc[1]
            else:
                merged.append(arc)
        if (
            len(merged) > 1
            and merged[-1][1] == merged[0][0]
            and {s.name for s in merged[-1][2]}
            == {s.name for s in merged[0][2]}
        ):
            merged[0][0] = merged[-1][0]
            merged.pop()
        return [(s, e, p) for s, e, p in merged]

    def all_arcs(
        self, rf: int
    ) -> List[Tuple[int, int, List[Shard]]]:
        """EVERY ring arc with its full rf-distinct-node replica
        shard set, as (start, end, selected_shards) — the whole-ring
        generalization of ``replica_arcs`` the streaming scan plane
        merges across: for every arc, ``selected_shards`` are the
        shards (possibly including THIS one) the distinct-node walk
        from the arc's owning ring point selects.  Bounds are
        +1-shifted half-open [start, end); start == end means the
        whole ring.  Adjacent arcs with identical shard sets merge."""
        ring = self._hash_sorted
        n = len(ring)
        if n == 0:
            return []
        shifted = (ring[0].hash + 1) & 0xFFFFFFFF
        if n == 1:
            return [(shifted, shifted, [ring[0]])]
        arcs: List[list] = []
        for i in range(n):
            # Arc (ring[i-1].hash, ring[i].hash]: the walk starts at
            # ring[i] (first shard at/after every hash in the arc).
            nodes: set = set()
            selected: List[Shard] = []
            for off in range(n):
                s = ring[(i + off) % n]
                if s.node_name in nodes:
                    continue
                nodes.add(s.node_name)
                selected.append(s)
                if len(nodes) >= rf:
                    break
            arcs.append(
                [
                    (ring[i - 1].hash + 1) & 0xFFFFFFFF,
                    (ring[i].hash + 1) & 0xFFFFFFFF,
                    selected,
                ]
            )
        return self._merge_adjacent_arcs(arcs)

    def replica_arcs(
        self, rf: int
    ) -> List[Tuple[int, int, List[Shard]]]:
        """The EXACT owned-range union for this shard under the
        distinct-node replica walk, as (start, end, peer_shards)
        arcs: for every ring arc, the walk from the arc's owning
        ring point selects one shard per distinct node until ``rf``
        nodes; arcs where THIS shard is selected are owned, and
        ``peer_shards`` are the other selected shards (the replicas
        that must agree with us over that arc).

        Bounds come back +1-shifted into the half-open [start, end)
        form the anti-entropy filters take; start == end means the
        whole ring.  Adjacent arcs with identical peer sets merge,
        so the common single-shard-per-node ring costs ~rf arcs.

        Replaces the (rf-th-distinct-predecessor, self] arc, which
        under interleaved multi-shard nodes over-approximates the
        union (ROADMAP open item) — importing ranges this shard can
        never serve and missing none, but paying transfer for them.
        Shared by quarantine repair, the background anti-entropy
        loop, and (via ``all_arcs``) the scan plane's merge, so their
        notion of "what a shard stores" can never diverge.
        Property-tested against owns_key in tests/test_convergence.py."""
        ring = self._hash_sorted
        n = len(ring)
        shifted_self = (self.hash + 1) & 0xFFFFFFFF
        if n < 2:
            return [(shifted_self, shifted_self, [])]
        arcs: List[list] = []
        for start, end, selected in self.all_arcs(rf):
            if not any(s.name == self.shard_name for s in selected):
                continue
            peers = [
                s
                for s in selected
                if s.name != self.shard_name
                and s.node_name != self.config.name
            ]
            arcs.append([start, end, peers])
        return self._merge_adjacent_arcs(arcs)

    @staticmethod
    async def apply_if_newer(
        tree, key: bytes, value: bytes, ts: int
    ) -> bool:
        """Write (key, value, ts) only if strictly newer than the local
        newest for that key (checks sstables too, not just the
        memtable).  The anti-entropy apply primitive: a replayed old
        entry must never shadow a newer value that was already flushed
        out of the memtable."""
        while True:
            local = await tree.get_entry(key)
            if local is not None and local[1] >= ts:
                return False
            # Close the probe/write race: a concurrent client write
            # may have landed during get_entry's awaits (and even been
            # swapped to the flushing memtable).  Re-probe the
            # memtables with NO awaits between this check and
            # set_with_timestamp's synchronous memtable insert.
            watermark = tree.max_flushed_ts
            newest = tree.newest_memtable_ts(key)
            if newest is not None and newest >= ts:
                return False
            if await tree.set_with_timestamp(
                key, value, ts, stale_abort_from=watermark
            ):
                return True
            # A capacity wait inside the insert spanned a flush swap
            # that advanced the watermark past ts (the last
            # stale-shadow window, ADVICE r5 low #2): the probe above
            # is stale — re-probe against the newly flushed layers
            # and retry.  Terminates: each extra round requires a NEW
            # swap during the insert.

    @staticmethod
    def _in_ae_range(h: int, start: int, end: int) -> bool:
        """Anti-entropy range membership.  ``start``/``end`` are the
        primary ownership range (prev, self] pre-shifted by +1 into
        half-open [start, end) form; start == end means the shard's
        single ring point covers the whole ring."""
        from .migration import _between

        return start == end or _between(h, start, end)

    @staticmethod
    def _ae_bucket_of(h: int, start: int, end: int, nbuckets: int) -> int:
        """Sub-range bucket (0..nbuckets-1) of an in-range hash: the
        wrap range [start, end) is split into nbuckets equal slices.
        Both digest sides and the pull filter use THIS function, so
        bucket membership can never disagree across peers."""
        width = (end - start) & 0xFFFFFFFF
        if width == 0:
            width = 1 << 32  # single ring point: the whole ring
        d = (h - start) & 0xFFFFFFFF
        return min(nbuckets - 1, (d * nbuckets) // width)

    @staticmethod
    async def compute_range_digests(
        tree, start: int, end: int, nbuckets: int = 1
    ) -> Tuple[list, list]:
        """Order-independent 64-bit digests over (key, newest-ts) pairs
        in the anti-entropy range, one per hash sub-range bucket (a
        flat merkle layer: ONE scan fills all buckets).  Tombstones
        count (their deletions must converge too).

        Big trees take the vectorized path (storage/range_digest.py):
        bulk column reads + native murmur batches on an executor
        thread, ~20× cheaper than this method's per-entry fallback
        and golden-tested equal."""
        from ..storage import range_digest as rd

        total = tree.memtable_entries + tree.sstable_entry_count()
        if total >= rd.MIN_VECTORIZED_ENTRIES:
            snap = tree.scan_snapshot()
            try:
                res = await asyncio.get_event_loop().run_in_executor(
                    None,
                    rd.vectorized_range_digests,
                    snap.memtable_items,
                    snap.tables,
                    start,
                    end,
                    nbuckets,
                )
            except CorruptedFile as e:
                # Bulk-scan corruption: quarantine the source table
                # (the .path-attribution pattern of the compaction
                # merge) so repair starts NOW, then re-raise — the
                # AE loop skips this arc for the round.
                tree.quarantine_by_exception(e, snap.tables)
                raise
            finally:
                snap.release()
            if res is not None:
                return res

        from ..utils.murmur import murmur3_32

        newest: Dict[bytes, Tuple[int, int]] = {}  # key -> (ts, hash)
        # One hash per entry: range membership is checked in the loop
        # body (the filter lambda would hash a second time) and the
        # hash is carried into aggregation for the bucket derivation.
        async for key, _value, ts in tree.iter_filter(None):
            h = hash_bytes(key)
            if not MyShard._in_ae_range(h, start, end):
                continue
            prev = newest.get(key)
            if prev is None or ts > prev[0]:
                newest[key] = (ts, h)
        counts = [0] * nbuckets
        digests = [0] * nbuckets
        for key, (ts, h) in newest.items():
            b = MyShard._ae_bucket_of(h, start, end, nbuckets)
            blob = key + ts.to_bytes(8, "little", signed=True)
            counts[b] += 1
            digests[b] ^= murmur3_32(blob, 0x0A57E4A1) | (
                murmur3_32(blob, 0x51C6E57A) << 32
            )
        return counts, digests

    @staticmethod
    async def collect_range_entries(
        tree,
        start: int,
        end: int,
        start_after: Optional[bytes] = None,
        buckets: Optional[set] = None,
        nbuckets: int = 0,
    ) -> list:
        """ALL (key, value, newest-ts) triples in the anti-entropy
        range with key > start_after, ascending by key; with
        ``buckets``, only entries in those hash sub-range buckets.
        The push side calls this once and pages from the result; the
        stateless RANGE_PULL server pays one scan per page (keys <=
        start_after are filtered during the scan, so later pages dedup
        less)."""
        newest: Dict[bytes, Tuple[bytes, int]] = {}
        async for key, value, ts in tree.iter_filter(None):
            if start_after is not None and key <= start_after:
                continue
            h = hash_bytes(key)  # once per entry: range AND bucket
            if not MyShard._in_ae_range(h, start, end):
                continue
            if buckets is not None and (
                MyShard._ae_bucket_of(h, start, end, nbuckets)
                not in buckets
            ):
                continue
            prev = newest.get(key)
            if prev is None or ts > prev[1]:
                newest[key] = (value, ts)
        return [
            [k, v, ts] for k, (v, ts) in sorted(newest.items())
        ]

    @staticmethod
    async def collect_range_page(
        tree,
        start: int,
        end: int,
        start_after: Optional[bytes],
        limit: int,
        buckets: Optional[set] = None,
        nbuckets: int = 0,
    ) -> list:
        """Up to ``limit`` entries with key > start_after (the
        stateless remote paging entry point)."""
        entries = await MyShard.collect_range_entries(
            tree, start, end, start_after, buckets, nbuckets
        )
        return entries[:limit]

    # ------------------------------------------------------------------
    # Gossip (shards.rs:791-827, 1131-1200)
    # ------------------------------------------------------------------

    async def gossip(self, event: list) -> None:
        await self.broadcast_message_to_local_shards(
            ShardEvent.gossip(event)
        )
        buf = msgs.serialize_gossip_message(
            f"{self.config.name}#{self.boot_id}",
            event,
            # Telemetry plane: every outgoing gossip frame carries
            # this node's freshest health digest — membership/DDL
            # traffic keeps remote cluster_stats views warm for free.
            self.last_node_digest,
        )
        await self.gossip_buffer(buf)

    async def gossip_buffer(self, buf: bytes) -> None:
        """Fire-and-forget UDP to gossip_fanout random nodes."""
        import random

        nodes = list(self.nodes.values())
        random.shuffle(nodes)
        targets = nodes[: self.config.gossip_fanout]
        for node in targets:
            await self._gossip_send(buf, node)

    async def _gossip_send(self, buf: bytes, node) -> None:
        loop = asyncio.get_event_loop()
        try:
            sock = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
            sock.setblocking(False)
            if hasattr(loop, "sock_sendto"):
                await loop.sock_sendto(
                    sock, buf, (node.ip, node.gossip_port)
                )
            else:
                # py3.10: loop.sock_sendto doesn't exist.  A UDP
                # sendto on a non-blocking socket never blocks —
                # it either queues the datagram or drops it
                # (EAGAIN), and gossip is fire-and-forget.
                sock.sendto(buf, (node.ip, node.gossip_port))
            sock.close()
        except OSError as e:
            log.error("gossip send to %s failed: %s", node.name, e)

    async def gossip_to_node(self, event: list, node) -> None:
        """Unicast a gossip frame straight at one node, bypassing the
        random epidemic fanout.  The one caller that needs this is the
        DEAD path: ``handle_dead_node`` pops the victim from
        ``self.nodes`` BEFORE the event is gossiped, so the normal
        fanout can never select the accused — a falsely-removed (but
        alive) node would otherwise never hear its own death
        certificate, never fire the self-defense ALIVE re-announce,
        and the asymmetric membership split would be permanent."""
        buf = msgs.serialize_gossip_message(
            f"{self.config.name}#{self.boot_id}",
            event,
            self.last_node_digest,
        )
        await self._gossip_send(buf, node)

    async def handle_gossip_event(self, event: list) -> bool:
        """Returns True when the event should continue propagating
        (shards.rs:1131-1200 returns !another_gossip_sent)."""
        kind = event[0]
        another_gossip_sent = False
        if kind == GossipEvent.ALIVE:
            node = NodeMetadata.from_wire(event[1])
            if node.name != self.config.name:
                self.dead_nodes.discard(node.name)
                self.departed_shards.pop(node.name, None)
                self.departed_at.pop(node.name, None)
                newly_added = node.name not in self.nodes
                if newly_added:
                    self.nodes[node.name] = node
                    self.add_shards_of_nodes([node])
                    self.persist_peers()
                    # Membership changed: bump the epoch and cancel
                    # any in-flight migration (it re-plans below from
                    # the NEW ring).
                    self._fence_membership_change()
                # State transition resets the opposite epidemic
                # counters (sources are name#boot_id salted).
                self._reset_gossip_counters(
                    node.name, GossipEvent.DEAD
                )
                if self.hint_log.has(node.name):
                    self.spawn(self.replay_hints(node.name))
                self.flow.notify(FlowEvent.ALIVE_NODE_GOSSIP)
                if newly_added:
                    # Migrate ONLY on the add transition (shards.rs:
                    # 1139-1152 — the ring didn't change on a duplicate
                    # Alive, so re-streaming every owned range per
                    # gossip re-receipt is pure waste and hides real
                    # repair mechanisms behind accidental migrations).
                    self.migrate_data_on_node_addition(
                        [
                            s
                            for s in self.shards
                            if s.node_name == node.name
                        ]
                    )
        elif kind == GossipEvent.DEAD:
            node_name = event[1]
            if node_name == self.config.name:
                # Self-defense: we're alive — re-announce (1165-1172).
                await self.gossip(
                    GossipEvent.alive(self.get_node_metadata())
                )
                another_gossip_sent = True
            else:
                # Grab the victim's address BEFORE removal: every
                # processor forwards the accusation straight to the
                # accused so a false positive can self-defend (the
                # epidemic fanout only targets ``self.nodes``, which
                # no longer contains it).
                victim = self.nodes.get(node_name)
                await self.handle_dead_node(node_name)
                if victim is not None:
                    self.spawn(self.gossip_to_node(event, victim))
        elif kind == GossipEvent.CREATE_COLLECTION:
            try:
                await self.create_collection(
                    event[1],
                    event[2],
                    event[3] if len(event) > 3 else None,
                    event[4] if len(event) > 4 else None,
                )
            except CollectionAlreadyExists:
                pass
        elif kind == GossipEvent.DROP_COLLECTION:
            try:
                await self.drop_collection(event[1])
            except CollectionNotFound:
                pass
        elif kind == GossipEvent.HEALTH:
            # Telemetry plane: absorb the node's periodic health
            # digest into this shard's cluster view (freshest wins)
            # and keep propagating — the epidemic is what makes
            # cluster_stats answer from ANY node.
            if len(event) > 3:
                self.absorb_health_digest(event[3])
        return not another_gossip_sent

    def _reset_gossip_counters(self, node_name: str, kind: str) -> None:
        """Drop dedup counters of ``kind`` for every boot of a node
        (gossip sources are '<name>#<boot_id>')."""
        dead_keys = [
            key
            for key in self.gossip_requests
            if key[1] == kind
            and key[0].split("#", 1)[0] == node_name
        ]
        for key in dead_keys:
            del self.gossip_requests[key]

    async def handle_dead_node(self, node_name: str) -> None:
        if self.nodes.pop(node_name, None) is None:
            return
        # Failure-aware request plane: mark first, THEN cancel any
        # replica request already in flight to the dead peer — a
        # client op blocked on a black-holed socket unblocks now (and
        # the mutation is hinted), instead of riding the 15 s read
        # timeout.  The mark makes new fan-outs fast-fail during the
        # removal race, and handle_request uses it to answer PeerDead
        # instead of a bare quorum Timeout.
        self.dead_nodes.add(node_name)
        for fut in list(self._inflight_by_node.get(node_name, ())):
            fut.cancel()
        if self.quorum_fanout is not None:
            # The native fan-out plane holds its own streams: drop
            # them too, so its in-flight ops dead-event (hint +
            # release) now instead of riding the C read timeout.
            self.quorum_fanout.drop_node(
                sorted(
                    {
                        s.connection.address
                        for s in self.shards
                        if s.node_name == node_name
                        and isinstance(
                            s.connection, RemoteShardConnection
                        )
                    }
                )
            )
        # Allow the node's next Alive announcement through the gossip
        # dedup immediately (see the matching reset in
        # handle_gossip_event).
        self._reset_gossip_counters(node_name, GossipEvent.ALIVE)
        removed = [s for s in self.shards if s.node_name == node_name]
        if removed and self.config.hint_ttl_ms > 0:
            # Keep the dead node's ring entries for hint targeting:
            # mutations keep hinting its natural replica slots until
            # it re-announces or its TTL window closes.
            import time as _time

            self.departed_shards[node_name] = removed
            self.departed_at[node_name] = _time.time()
        self.shards = [
            s for s in self.shards if s.node_name != node_name
        ]
        self.sort_consistent_hash_ring()
        # Membership changed: bump the epoch and cancel any in-flight
        # migration before re-planning from the shrunk ring below.
        self._fence_membership_change()
        closed: set = set()
        for s in removed:
            # Vnode rings carry one entry per token sharing ONE
            # pooled connection: close it once.
            if isinstance(
                s.connection, RemoteShardConnection
            ) and id(s.connection) not in closed:
                closed.add(id(s.connection))
                s.connection.close_pool()
        log.info(
            "after death of %s: %d nodes, %d shards",
            node_name,
            len(self.nodes),
            len(self.shards),
        )
        self.persist_peers()
        self.flow.notify(FlowEvent.DEAD_NODE_REMOVED)
        await self.migrate_data_on_node_removal(removed)

    # ------------------------------------------------------------------
    # Migration planning (shards.rs:853-1072)
    # ------------------------------------------------------------------

    async def migrate_data_on_node_removal(
        self, removed_shards: List[Shard]
    ) -> None:
        assert removed_shards
        old_ring = list(self.shards) + list(removed_shards)
        self.spawn_migration_tasks(
            self._plan_collection_actions(
                old_ring, list(self.shards)
            ),
            delay=None,
        )

    def migrate_data_on_node_addition(
        self, added_shards: List[Shard]
    ) -> None:
        assert added_shards
        added_names = {s.name for s in added_shards}
        old_ring = [
            s for s in self.shards if s.name not in added_names
        ]
        self.spawn_migration_tasks(
            self._plan_collection_actions(
                old_ring, list(self.shards)
            ),
            delay=NEW_NODE_MIGRATION_DELAY_S,
        )

    def _plan_collection_actions(
        self,
        old_ring: List[Shard],
        new_ring: List[Shard],
    ) -> List[Tuple[str, List[RangeAndAction]]]:
        """Per-collection migration plans for one ring transition.
        Plans depend only on the replication factor, so collections
        sharing an rf share one RangeAndAction list (the executor
        treats it read-only).  Per-collection skips use `continue`,
        not `return`: the reference returns out of the whole planning
        loop (shards.rs:869-876), silently aborting every collection
        after an rf=1 one — a durability hole with mixed-RF
        collections, fixed deliberately (PARITY.md)."""
        actions: List[Tuple[str, List[RangeAndAction]]] = []
        plans: Dict[int, List[RangeAndAction]] = {}
        for name, collection in list(self.collections.items()):
            rf = collection.replication_factor
            if rf <= 1:
                # rf=1 data lives only at its primary: no replica set
                # to rebuild.
                continue
            if rf not in plans:
                plans[rf] = self._plan_arc_diff(
                    old_ring, new_ring, rf
                )
            if plans[rf]:
                actions.append((name, plans[rf]))
        return actions

    @staticmethod
    def _ring_walk(
        ring: List[Shard],
        hashes: List[int],
        point: int,
        rf: int,
    ) -> List[Shard]:
        """Distinct-node replica walk of a hash-sorted ``ring`` (with
        its parallel ``hashes`` list) anchored at ``point``: the first
        shard of each of the first min(rf, n_nodes) distinct nodes
        at/after the point on the wrapping ring — the same walk
        owns_key, the clients, and anti-entropy derive ownership
        from."""
        n = len(ring)
        if n == 0:
            return []
        start = bisect.bisect_left(hashes, point) % n
        nodes: set = set()
        out: List[Shard] = []
        for off in range(n):
            s = ring[(start + off) % n]
            if s.node_name in nodes:
                continue
            nodes.add(s.node_name)
            out.append(s)
            if len(out) >= rf:
                break
        return out

    def _plan_arc_diff(
        self,
        old_ring: List[Shard],
        new_ring: List[Shard],
        rf: int,
    ) -> List[RangeAndAction]:
        """This shard's migration plan for the ring transition
        old_ring -> new_ring at replication factor ``rf``, as the
        arc-by-arc ownership diff (supersedes the hand-derived
        one-token special cases that accumulated four documented
        reference-bug fixes — the general form IS the fix, and it is
        what makes vnode rings plannable at all).

        The union of both rings' token hashes partitions the ring
        into arcs (U[i-1], U[i]]; no token of either ring lies
        strictly inside an arc, so each arc's replica walk is
        constant across the arc and can be evaluated once at its end
        point.  Per arc, diff the old and new distinct-node replica
        sets:

        - SEND: exactly one view streams each gained node its copy —
          the DESIGNATED SENDER, the first shard in the old walk
          whose node survives into the new set (deterministic across
          views: every node computes the same walks from the same
          membership).  This view emits only when that sender is
          itself.
        - DELETE: a view evacuates an arc its node lost only when its
          own entry was the node's serving shard for that arc in the
          old ring (other shards of the node never held the data).

        One membership event changes one node, so per arc per view at
        most ONE action fires (a designated sender's node survives,
        hence never deletes the same arc) — the executor's
        first-match dispatch over disjoint arcs stays exact.
        Consecutive arcs with identical actions merge (never across
        an actionless gap — widening a SEND range would plant
        unowned slices on the target; never across the wrap)."""
        old_sorted = sorted(old_ring, key=lambda s: (s.hash, s.name))
        new_sorted = sorted(new_ring, key=lambda s: (s.hash, s.name))
        old_hashes = [s.hash for s in old_sorted]
        new_hashes = [s.hash for s in new_sorted]
        union = sorted(set(old_hashes) | set(new_hashes))
        if len(union) < 2:
            return []  # single-point ring: no ownership to move
        arcs: List[tuple] = []  # (start, end, sig) per union arc
        for i, point in enumerate(union):
            start = union[i - 1]  # i=0 wraps: (U[-1], U[0]]
            old_sel = self._ring_walk(
                old_sorted, old_hashes, point, rf
            )
            new_sel = self._ring_walk(
                new_sorted, new_hashes, point, rf
            )
            old_nodes = {s.node_name for s in old_sel}
            new_nodes = {s.node_name for s in new_sel}
            sig: List[tuple] = []
            sender = next(
                (s for s in old_sel if s.node_name in new_nodes),
                None,
            )
            if sender is not None and sender.name == self.shard_name:
                for tgt_node in sorted(new_nodes - old_nodes):
                    tgt = next(
                        s
                        for s in new_sel
                        if s.node_name == tgt_node
                    )
                    sig.append((MigrationAction.SEND, tgt))
            if self.config.name in old_nodes - new_nodes:
                mine = next(
                    (
                        s
                        for s in old_sel
                        if s.node_name == self.config.name
                    ),
                    None,
                )
                if mine is not None and mine.name == self.shard_name:
                    sig.append((MigrationAction.DELETE, None))
            arcs.append((start, point, sig))
        # Merge runs of consecutive arcs with the same non-empty
        # signature (compare by action + target NAME: the same node's
        # serving entry is one object across arcs).
        merged: List[tuple] = []
        for start, end, sig in arcs:
            if (
                sig
                and merged
                and merged[-1][2]
                and merged[-1][1] == start
                and [
                    (a, t.name if t is not None else None)
                    for a, t in merged[-1][2]
                ]
                == [
                    (a, t.name if t is not None else None)
                    for a, t in sig
                ]
            ):
                merged[-1] = (merged[-1][0], end, sig)
            else:
                merged.append((start, end, sig))
        plan: List[RangeAndAction] = []
        for start, end, sig in merged:
            for action, tgt in sig:
                if action == MigrationAction.SEND:
                    plan.append(
                        RangeAndAction(
                            start, end, action, tgt.connection
                        )
                    )
                else:
                    plan.append(RangeAndAction(start, end, action))
        return plan

    def _fence_membership_change(self) -> None:
        """A membership change landed: bump the epoch (writes stamped
        with the previous ring view refuse retryably while migration
        is live) and cancel any in-flight migration plans — they were
        computed against a ring that no longer exists, and finishing
        them would double-stream arcs the caller is about to re-plan
        from the CURRENT ring."""
        self.membership_epoch += 1
        for task in list(self._migration_tasks):
            if not task.done():
                task.cancel()
                self.migrations_cancelled += 1
        self._migration_tasks.clear()

    def _migration_task_done(self, task) -> None:
        self._migration_tasks.discard(task)
        if not self._migration_tasks:
            # Last migration drained: lift the epoch fence and restore
            # the native ownership fast path (punted to Python while
            # the fence was up).
            self._refresh_dataplane_ownership()

    def spawn_migration_tasks(
        self,
        actions: List[Tuple[str, List[RangeAndAction]]],
        delay: Optional[float],
    ) -> None:
        from .migration import migrate_actions

        epoch = self.membership_epoch
        spawned = False
        for collection_name, ranges in actions:
            col = self.collections.get(collection_name)
            if col is None:
                continue
            self.migrations_started += 1

            async def run(name=collection_name, tree=col.tree, r=ranges):
                if delay:
                    await asyncio.sleep(delay)
                try:
                    await migrate_actions(
                        self, name, tree, r, plan_epoch=epoch
                    )
                except asyncio.CancelledError:
                    # Fenced by a newer membership change — counted
                    # there; the replacement plan owns the arcs now.
                    pass
                except Exception as e:
                    log.error("error migrating %s: %s", name, e)
                self.flow.notify(FlowEvent.DONE_MIGRATION)

            task = self.spawn(run())
            self._migration_tasks.add(task)
            task.add_done_callback(self._migration_task_done)
            spawned = True
        if spawned:
            # Epoch fence up: punt keyed ops to the Python dispatcher
            # (which reads the epoch stamp) for the migration window.
            self._refresh_dataplane_ownership()

    # ------------------------------------------------------------------

    async def stop(self) -> None:
        self.local_connection.send_stop()

    def try_to_stop_local_shards(self) -> None:
        for s in self.shards:
            if s.is_local:
                s.connection.send_stop()

    def close_db_connections(self) -> None:
        """Close live client AND peer transports so Server.wait_closed()
        (which waits on them in py3.12) can finish during shutdown."""
        for conn in (
            *list(self.db_connections),
            *list(self.remote_connections),
        ):
            conn.closing = True
            if conn.transport is not None:
                conn.transport.close()
        self.db_connections.clear()
        self.remote_connections.clear()

    def close(self) -> None:
        self.close_db_connections()
        if self.quorum_fanout is not None:
            self.quorum_fanout.close()
        self.hint_log.close()
        for col in self.collections.values():
            col.tree.close()
