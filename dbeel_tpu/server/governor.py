"""Per-shard load governor — the overload-control brain (PR 5).

The serving plane survives dead peers, corrupt disks and partitions,
but nothing protected it from *too much traffic*: past the fixed
per-connection window, queues grew without bound, a flush/compaction
backlog silently inflated tail latency until the WAL or memtable path
fell over, and a slow replica could absorb a coordinator's memory.
The compaction design-space literature (PAPERS.md: "Constructing and
Analyzing the LSM Compaction Design Space"; RESYSTANCE) is blunt about
the fix: backlog-aware admission and write throttling are what keep an
LSM store stable under sustained load.

This governor samples the shard's backlog signals and folds them into
one of three levels:

  * ``LEVEL_OK`` (0)   — nothing to do.
  * ``LEVEL_SOFT`` (1) — backlog building: LOW-PRIORITY work yields
    first.  Background units (anti-entropy, scrub, hint drain,
    migration — everything already under ``scheduler.bg_slice``) are
    delayed before they start, and every connection's AIMD window
    shrinks multiplicatively, pushing queueing back into the clients.
  * ``LEVEL_HARD`` (2) — backlog past the point where admitting more
    work only converts latency into collapse: NEW data ops are shed
    with the retryable ``Overloaded`` error (cheap to produce, honest
    to the client, and the client's backoff walk spreads the retry),
    while admin/observability ops (``get_stats``, metadata, rearm)
    keep serving so an operator can always see in.

Signals (sampled at most once per SAMPLE_S — the serving path pays a
cached integer compare):

  * admitted work: queued + in-flight + sync-parked ops across every
    client connection (the parked count IS the WAL-sync backlog at
    the serving layer: acks waiting on fdatasync);
  * memtable fill: entries and appends-since-swap against capacity on
    the busiest collection (appends >> capacity means flushes cannot
    keep up — the WAL grows without bound);
  * flush/compaction debt: sstable count beyond
    ``overload_compaction_debt`` on any collection.  An unfinished
    flush swap is reported (``flush_backlog``) for observability but
    is not itself a level trigger: a wedged flush blocks the next
    swap, so the memtable fill/appends signals above cross their own
    thresholds within one memtable's worth of traffic — and with no
    traffic there is nothing to govern;
  * event-loop lag: EWMA overshoot of a 50ms heartbeat sleep.  The
    native data plane answers RF=1 ops synchronously inside
    data_received — overload there never shows up in any tracked
    queue; it shows up as the loop's callback queue stretching, which
    is exactly what the heartbeat measures.  (Found by the
    --overload-knee bench: without this signal, 3x offered load
    collapsed goodput 5x through pure queueing with every queue
    signal reading zero.)

Shedding never applies to the PEER plane (replica work keeps quorums
alive; its protection is deadline drops + the per-peer outbound caps
in remote_comm), and never to reads of the governor's own state.
"""

from __future__ import annotations

import time
from typing import Optional, Tuple

from .qos import (
    CLASS_HARD_FACTOR,
    CLASS_SOFT_FACTOR,
    NCLASSES,
    QOS_INTERACTIVE,
    QOS_STANDARD,
)

LEVEL_OK = 0
LEVEL_SOFT = 1
LEVEL_HARD = 2

# Memtable-fill thresholds (fractions of tree capacity).  Soft at 85%
# of either signal; hard only when appends since the last swap exceed
# TWICE capacity — the flush trigger fires at 1x, so 2x means the
# flush path is genuinely behind, not merely scheduled.
MEMTABLE_SOFT_FILL = 0.85
MEMTABLE_HARD_APPENDS = 2.0

# Background work delayed at soft overload waits in these slices, up
# to the cap — maintenance yields to serving but is never starved
# outright (anti-entropy owns correctness tails).
BG_DELAY_SLICE_S = 0.05
BG_DELAY_MAX_S = 5.0

# Event-loop lag heartbeat: sleep HB_S, measure the overshoot, EWMA
# it.  Lag thresholds are intentionally far above a healthy loop's
# jitter (this class of host shows tens of ms under legitimate full
# load) — soft/hard fire only when the callback queue is genuinely
# stretching into client-visible latency.
HB_S = 0.05
LAG_EWMA_ALPHA = 0.3
LAG_SOFT_S = 0.10
LAG_HARD_S = 0.40

# Dead-completion fraction: the EWMA share of served data ops that
# finished AFTER the budget their client gave them (the propagated
# deadline_ms, or the op's own timeout field) — i.e. responses nobody
# was still waiting for.  This is the signal that fires when overload
# lives in WALL TIME rather than any queue: a saturated quorum path
# (CPU contention, fdatasync storms, slow replicas) stretches every
# op past its deadline while pending/inflight counts stay small
# because clients give up and retry.  Sustained dead work means new
# admissions are hopeless too — shed them instead.  The EWMA needs
# ~log(0.5)/log(1-alpha) ≈ 7 consecutive dead completions to cross
# the hard bar, so one pathological op (a 15s blackhole timeout)
# cannot flip the shard.
DEAD_EWMA_ALPHA = 0.1
DEAD_FRAC_SOFT = 0.25
DEAD_FRAC_HARD = 0.5


class LoadGovernor:
    SAMPLE_S = 0.02  # signal cache lifetime

    __slots__ = (
        "shard",
        "config",
        "_level",
        "_sampled_at",
        "_signals",
        "_forced",
        "_lag_ewma",
        "_hb_task",
        "_dead_ewma",
        "_pushed_level",
        "_class_levels",
        "_pushed_class_levels",
        "_soft_reasons",
        "telemetry_hook",
        "dead_completions",
        # counters (get_stats.overload)
        "shed_ops",
        "shed_by_op",
        "python_sheds",
        "deadline_drops",
        "replica_deadline_drops",
        "bg_delays",
        "bg_delayed_s",
        "soft_transitions",
        "hard_transitions",
        "window_decreases",
        "window_min_seen",
    )

    def __init__(self, shard, config) -> None:
        self.shard = shard
        self.config = config
        self._level = LEVEL_OK
        self._sampled_at = 0.0
        self._signals: dict = {}
        # Test seam (the set_fault pattern): force a level regardless
        # of the sampled signals; None disarms.
        self._forced: Optional[int] = None
        self._lag_ewma = 0.0
        self._hb_task = None
        self._dead_ewma = 0.0
        self._pushed_level: Optional[int] = None
        # Per-class levels (QoS plane, ISSUE 14): the same sampled
        # signals compared against thresholds scaled by each class's
        # factors — batch trips first, interactive last; STANDARD is
        # exactly the classic scalar level.
        self._class_levels: Tuple[int, ...] = (0,) * NCLASSES
        self._pushed_class_levels: Optional[Tuple[int, ...]] = None
        # Which signal families fired each class's soft level on the
        # last sample ("ops"/"memtable"/"debt"/"lag"/"dead") — the
        # scan plane paces instead of hard-parking when a resting
        # shard's memtable fill is the ONLY pressure (BENCH r13).
        self._soft_reasons: Tuple[frozenset, ...] = (
            frozenset(),
        ) * NCLASSES
        # Telemetry plane (PR 11): the continuous sampler rides THIS
        # heartbeat — one callable check per beat when armed, nothing
        # at all when --telemetry-interval is 0 (the hook stays None).
        self.telemetry_hook = None
        self.dead_completions = 0
        self.shed_ops = 0
        self.shed_by_op: dict = {}
        # Sheds that had to run through the Python dispatcher (frame
        # shapes the C parser punts).  With the native shed gate
        # armed this stays ~0 under a client flood — the measurable
        # claim of the all-native serving path.
        self.python_sheds = 0
        self.deadline_drops = 0
        self.replica_deadline_drops = 0
        self.bg_delays = 0
        self.bg_delayed_s = 0.0
        self.soft_transitions = 0
        self.hard_transitions = 0
        self.window_decreases = 0
        self.window_min_seen = float(config.pipeline_window_max)

    # -- test seam -----------------------------------------------------

    def force_level(self, level: Optional[int]) -> None:
        """Pin the governor to ``level`` (None disarms) — the
        deterministic fault seam tests drive shedding/AIMD through
        without constructing a real timing-dependent backlog."""
        self._forced = level
        self._sampled_at = 0.0  # next level() re-evaluates

    def note_completion(self, dead: bool) -> None:
        """One served data op finished; ``dead`` = after the budget
        its client gave it (the response fed nobody).  Called from
        the serving completion points — never from the shed path, so
        shedding itself cannot mask the signal it reacts to."""
        if dead:
            self.dead_completions += 1
        self._dead_ewma += DEAD_EWMA_ALPHA * (
            (1.0 if dead else 0.0) - self._dead_ewma
        )

    # -- event-loop lag heartbeat --------------------------------------

    def _ensure_heartbeat(self) -> None:
        if self._hb_task is not None and not self._hb_task.done():
            return
        import asyncio

        try:
            asyncio.get_running_loop()
        except RuntimeError:
            return  # no loop (direct construction in tests)
        self._hb_task = self.shard.spawn(self._heartbeat())

    async def _heartbeat(self) -> None:
        import asyncio

        loop = asyncio.get_event_loop()
        while True:
            t0 = loop.time()
            await asyncio.sleep(HB_S)
            lag = max(0.0, loop.time() - t0 - HB_S)
            e = self._lag_ewma
            self._lag_ewma = (
                lag if e == 0.0 else e + LAG_EWMA_ALPHA * (lag - e)
            )
            hook = self.telemetry_hook
            if hook is not None:
                # Telemetry sampling point: a monotonic compare per
                # beat; the due samples (one get_stats walk per
                # --telemetry-interval) happen here, never on the
                # serving path.  The hook swallows its own errors.
                hook()

    # -- sampling ------------------------------------------------------

    def _sample(self) -> int:
        shard = self.shard
        cfg = self.config
        ops = 0
        for conn in shard.db_connections:
            ops += len(conn.pending) + len(conn.parked)
            ops += len(getattr(conn, "inflight", ()))
        # Watch chunks parked in an empty-ring long-poll are idle
        # (an event-wait, not queued CPU work) but still count as
        # in-flight on their connections; exclude them so a large
        # idle-subscriber pool cannot read as hard overload and
        # shed real traffic.  Watch has its own admission: the
        # subscriber cap and per-subscriber byte buckets.
        wp = getattr(shard, "watch_plane", None)
        if wp is not None:
            ops = max(0, ops - wp.parked_chunks)
        mem_fill = 0.0
        appends_fill = 0.0
        flush_backlog = False
        debt = 0
        for col in shard.collections.values():
            tree = col.tree
            cap = max(1, tree.capacity)
            mem_fill = max(mem_fill, len(tree._active) / cap)
            appends_fill = max(
                appends_fill, tree._appends_since_swap / cap
            )
            if tree._pending_flush is not None:
                flush_backlog = True
            debt = max(debt, len(tree._sstables.tables))
        lag = self._lag_ewma
        dead = self._dead_ewma
        self._signals = {
            "ops": ops,
            "memtable_fill": round(max(mem_fill, appends_fill), 3),
            "flush_backlog": int(flush_backlog),
            "sstable_debt": debt,
            "loop_lag_ms": round(lag * 1000, 1),
            "dead_completion_frac": round(dead, 3),
        }
        # Per-class levels (QoS plane): the SAME signals against
        # thresholds scaled by each class's factors — factor < 1
        # trips earlier (batch sheds first), > 1 later (interactive's
        # knee moves to a strictly higher offered-load multiple).
        # STANDARD's factors are 1.0, so its level is exactly the
        # classic PR-5 scalar.
        levels = []
        all_reasons = []
        for cls in range(NCLASSES):
            fs = CLASS_SOFT_FACTOR[cls]
            fh = CLASS_HARD_FACTOR[cls]
            reasons = set()
            if cfg.overload_soft_ops and ops > cfg.overload_soft_ops * fs:
                reasons.add("ops")
            if max(mem_fill, appends_fill) > MEMTABLE_SOFT_FILL * fs:
                reasons.add("memtable")
            if (
                cfg.overload_compaction_debt
                and debt > cfg.overload_compaction_debt * fs
            ):
                reasons.add("debt")
            # Wall-time signals (loop lag, dead completions) keep the
            # UNSCALED soft thresholds for every class: they measure
            # the whole shard, not one lane's queue — halving them
            # for batch would pace analytics on any legitimately busy
            # host (this host class shows tens of ms of lag under
            # healthy full load).  The class factors still scale the
            # HARD bars below, which is what moves the shed knees.
            if lag > LAG_SOFT_S:
                reasons.add("lag")
            if dead > DEAD_FRAC_SOFT:
                reasons.add("dead")
            level = LEVEL_SOFT if reasons else LEVEL_OK
            if (
                (
                    cfg.overload_hard_ops
                    and ops > cfg.overload_hard_ops * fh
                )
                or appends_fill > MEMTABLE_HARD_APPENDS * fh
                or lag > LAG_HARD_S * fh
                or dead > DEAD_FRAC_HARD * fh
            ):
                level = LEVEL_HARD
            levels.append(level)
            all_reasons.append(frozenset(reasons))
        self._class_levels = tuple(levels)
        self._soft_reasons = tuple(all_reasons)
        return levels[QOS_STANDARD]

    def level(self) -> int:
        if self._forced is not None:
            self._push_level(self._forced)
            return self._forced
        self._ensure_heartbeat()
        now = time.monotonic()
        if now - self._sampled_at >= self.SAMPLE_S:
            self._sampled_at = now
            prev = self._level
            self._level = self._sample()
            if self._level > prev:
                if self._level >= LEVEL_HARD:
                    self.hard_transitions += 1
                else:
                    self.soft_transitions += 1
        self._push_level(self._level)
        return self._level

    def class_level(self, cls: int) -> int:
        """The QoS level of one traffic class (qos.QOS_*).  Under the
        forced test seam every class reads the forced level except
        INTERACTIVE, which reads one level lower — the deterministic
        mirror of its higher thresholds (a forced LEVEL_HARD sheds
        batch+standard while interactive keeps serving, the class-
        priority contract tests pin)."""
        level = self.level()
        if self._forced is not None:
            if cls == QOS_INTERACTIVE:
                return max(LEVEL_OK, level - 1)
            return level
        if 0 <= cls < NCLASSES:
            return self._class_levels[cls]
        return level

    def soft_reasons(self, cls: int = QOS_STANDARD) -> frozenset:
        """Signal families that fired this class's soft level on the
        last sample.  Empty under the forced seam (forcing has no
        attributable signal — consumers fall back to the classic
        behavior)."""
        if self._forced is not None or not 0 <= cls < NCLASSES:
            return frozenset()
        return self._soft_reasons[cls]

    def memtable_only_soft(self, cls: int = QOS_STANDARD) -> bool:
        """True when this class reads soft (not hard) and the ONLY
        pressure is memtable fill — a resting shard whose arena sits
        near capacity with no queue/lag/debt/dead-completion signal.
        Scan chunks PACE through this state instead of hard-parking
        (BENCH r13: an 88%-fill idle shard parked every chunk 2s);
        the memtable protection that matters (appends outrunning the
        flush) shows up as ops/lag pressure or the hard level."""
        if self._forced is not None or not 0 <= cls < NCLASSES:
            return False
        return (
            self.class_level(cls) == LEVEL_SOFT
            and self._soft_reasons[cls] == frozenset(("memtable",))
        )

    def _push_level(self, level: int) -> None:
        """Mirror the level into the native data plane (all-native
        serving path): at LEVEL_HARD the C client plane answers data
        verbs with the prebuilt retryable Overloaded response itself,
        so shed frames never reach the Python dispatcher whose
        backlog the governor is protecting.  The per-class levels ride
        along (QoS plane) so the native shed gate stays class-aware:
        a batch flood is refused in C while interactive frames keep
        serving natively."""
        if self._forced is not None:
            # The forced seam's class mapping, mirrored natively.
            class_levels = tuple(
                max(LEVEL_OK, level - 1)
                if cls == QOS_INTERACTIVE
                else level
                for cls in range(NCLASSES)
            )
        else:
            class_levels = self._class_levels
        if (
            level == self._pushed_level
            and class_levels == self._pushed_class_levels
        ):
            return
        self._pushed_level = level
        self._pushed_class_levels = class_levels
        dp = getattr(self.shard, "dataplane", None)
        if dp is not None:
            dp.set_overload(level)
            dp.set_class_levels(class_levels)

    # -- decision points ----------------------------------------------

    def should_shed(self) -> bool:
        """Hard-limit admission check for NEW public data ops of the
        STANDARD class (the classic PR-5 scalar; per-class decisions
        live on the QoS plane)."""
        return self.level() >= LEVEL_HARD

    def any_should_shed(self) -> bool:
        """True when ANY traffic class is at its hard limit (in
        practice batch first — its thresholds sit lowest).  The
        dispatcher's routing gate: while any class sheds and the
        native shed gate is unarmed, frames must take the interpreted
        path so Python can make the per-class decision."""
        level = self.level()
        if self._forced is not None:
            return level >= LEVEL_HARD
        return max(self._class_levels) >= LEVEL_HARD

    def soft_overloaded(self) -> bool:
        return self.level() >= LEVEL_SOFT

    def record_shed(self, op: str) -> None:
        self.shed_ops += 1
        self.shed_by_op[op] = self.shed_by_op.get(op, 0) + 1

    def note_window(self, window: float, decreased: bool) -> None:
        if decreased:
            self.window_decreases += 1
        if window < self.window_min_seen:
            self.window_min_seen = window

    async def bg_gate(self) -> None:
        """Delay point for low-priority work under soft overload:
        background units wait (bounded) for the backlog to ease
        before starting — serving latency recovers first, maintenance
        resumes the moment pressure lifts (and after BG_DELAY_MAX_S
        regardless: anti-entropy/scrub must never starve outright).

        Deliberately gated on the STANDARD level, not the batch
        lane's (QoS plane): the units behind this gate include the
        compaction/flush maintenance that CURES memtable-fill and
        debt pressure, and batch's half-scaled thresholds would hold
        them parked from ~43% fill — near-permanently on a
        write-heavy shard (measured: compaction-under-load p99 blew
        its bound).  The analytics lane that must not starve
        interactive point ops is the SCAN plane, whose chunk
        admission does consume the batch budget."""
        import asyncio

        if self.level() < LEVEL_SOFT:
            return
        self.bg_delays += 1
        waited = 0.0
        while waited < BG_DELAY_MAX_S and self.level() >= LEVEL_SOFT:
            await asyncio.sleep(BG_DELAY_SLICE_S)
            waited += BG_DELAY_SLICE_S
        self.bg_delayed_s += waited

    # -- observability -------------------------------------------------

    def stats(self) -> dict:
        level = self.level()
        return {
            "level": level,
            "signals": dict(self._signals),
            "shed_ops": self.shed_ops,
            "shed_by_op": dict(self.shed_by_op),
            "python_sheds": self.python_sheds,
            "deadline_drops": self.deadline_drops,
            "replica_deadline_drops": self.replica_deadline_drops,
            "dead_completions": self.dead_completions,
            "bg_delays": self.bg_delays,
            "bg_delayed_s": round(self.bg_delayed_s, 3),
            "soft_transitions": self.soft_transitions,
            "hard_transitions": self.hard_transitions,
            "window_decreases": self.window_decreases,
            "window_min_seen": round(self.window_min_seen, 2),
            "window_max": self.config.pipeline_window_max,
        }
