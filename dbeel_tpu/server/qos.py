"""Multi-tenant QoS plane — traffic classes, weighted admission,
per-tenant quotas (ISSUE 14).

PR 5's governor treats all public traffic as ONE class: past the hard
limit it sheds a paid-tier point get and a bulk-load batch write with
the same hand, and one tenant's analytics stream inflates every
tenant's p99.  The LSM compaction design-space literature (PAPERS.md)
is explicit that foreground admission and background debt compete for
the same per-shard budget — without classes the cheapest work to shed
(batch) is shed no earlier than the most latency-sensitive.

This plane splits admission three ways:

* **Traffic classes** — ``interactive`` > ``standard`` > ``batch``,
  stamped by the client on the request frame (``qos`` field, wire ints
  below) and propagated on data-op peer frames as a trailing dialect
  element.  Each class gets:

  - a *shed threshold factor*: the governor's backlog signals are
    divided by the class factor before comparing against the PR-5
    thresholds, so ``batch`` reads overload at half the pressure
    (sheds first) and ``interactive`` at 1.5x (its knee sits at a
    strictly higher offered-load multiple).  The per-class levels are
    pushed into the C data plane so native hard-shed answers stay
    class-aware (a batch flood is refused in C while interactive
    frames keep serving natively).
  - a *weighted admission share*: a per-shard, per-class AIMD window
    (multiplicative decrease while the class reads soft overload,
    additive recovery) whose ceiling is proportional to the class
    weight — under pressure ``batch`` is squeezed to a sliver of the
    admitted-work budget while ``interactive`` keeps most of it.
    The window only binds while the class is soft-overloaded: an
    idle shard serves any class at full speed.

* **Per-tenant token-bucket quotas** — ``--tenant-ops-per-sec`` /
  ``--tenant-bytes-per-sec`` (0 disables), keyed by the client-stamped
  ``tenant`` id with PER-COLLECTION buckets (the flag is the default
  rate each tenant gets in each collection, so one tenant's bulk load
  into ``logs`` cannot drain its own budget for ``users``).  Ops are
  charged at dispatch (an empty bucket refuses with the retryable
  ``QuotaExceeded``); bytes are charged as DEBT once the op's real
  size is known — the bucket may go negative and further ops are
  refused until the refill covers the overdraft (exact accounting
  without pre-reading payloads).

* **Scan integration** — scan-chunk admission consumes the BATCH
  lane's budget (the scan plane's default class), so one analytics
  stream cannot starve interactive point ops; a scan stamped
  ``interactive`` by an operator keeps its priority.  ``bg_gate``
  deliberately STAYS on the standard level — the units behind it
  include the compaction/flush maintenance that cures memtable/debt
  pressure, and batch's half-scaled fill bar would park them
  near-permanently on a write-heavy shard (governor.bg_gate
  documents the measured regression; tests/test_qos.py pins it).

The C planes serve every class natively below the shed thresholds
(QoS only costs anything under pressure); frames carrying a ``tenant``
id punt to the interpreted path, which owns the quota buckets — the
same division of labor as traced frames.
"""

from __future__ import annotations

import time
from collections import OrderedDict
from typing import Dict, Optional, Tuple

# Wire class ids + the stamp resolver live in cluster/messages.py
# (both sides of the wire share them; clients must not import server
# machinery to stamp a class) — re-exported here under the names the
# server-side policy machinery uses.
from ..cluster.messages import (
    NCLASSES,
    QOS_BATCH,
    QOS_CLASS_NAMES as CLASS_NAMES,
    QOS_INTERACTIVE,
    QOS_STANDARD,
    qos_class_of as class_of,
)
from ..errors import Overloaded, QuotaExceeded

# Per-class policy: (admission weight, soft factor, hard factor).
# Factors DIVIDE the sampled backlog signals before the PR-5 threshold
# compare — <1 trips earlier (sheds first), >1 later (knee moves to a
# strictly higher offered-load multiple).  STANDARD is exactly the
# PR-5 governor (factor 1.0), so untagged traffic behaves as before.
CLASS_WEIGHTS = (4, 2, 1)
CLASS_SOFT_FACTOR = (1.5, 1.0, 0.5)
CLASS_HARD_FACTOR = (1.5, 1.0, 0.75)

# Token-bucket burst: a tenant may spend this many seconds of its
# rate at once (refilled continuously).  >1 so a paced client that
# sleeps between batches is not punished for arriving in bursts.
BUCKET_BURST_S = 2.0


def request_class(request: dict) -> int:
    """Class index stamped on a client request map (``qos`` field)."""
    return class_of(request.get("qos"))


def request_tenant(request: dict) -> Optional[str]:
    """Tenant id stamped on a client request map, or None.  Only
    non-empty strings count (the quota key crosses the wire)."""
    t = request.get("tenant")
    if isinstance(t, str) and t:
        return t
    return None


class TokenBucket:
    """One (tenant, collection) quota bucket.  Continuous refill at
    ``rate``/s up to ``rate * BUCKET_BURST_S``; balance may go
    NEGATIVE via ``debit`` (bytes charged after the op's real size is
    known) — ``take`` refuses while the overdraft lasts."""

    __slots__ = ("rate", "burst", "tokens", "_at")

    def __init__(self, rate: float, now: Optional[float] = None) -> None:
        self.rate = float(rate)
        self.burst = max(1.0, self.rate * BUCKET_BURST_S)
        self.tokens = self.burst
        self._at = time.monotonic() if now is None else now

    def _refill(self, now: Optional[float]) -> None:
        t = time.monotonic() if now is None else now
        dt = t - self._at
        if dt > 0:
            self.tokens = min(self.burst, self.tokens + dt * self.rate)
        self._at = t

    def take(self, n: float, now: Optional[float] = None) -> bool:
        """Charge ``n`` tokens if the balance is positive (the charge
        itself may push it negative — multi-op batches are admitted
        whole or not at all).  False = refused, nothing charged."""
        self._refill(now)
        if self.tokens <= 0.0:
            return False
        self.tokens -= n
        return True

    def debit(self, n: float, now: Optional[float] = None) -> None:
        """Unconditional charge (byte debt after the fact)."""
        self._refill(now)
        self.tokens -= n


class _ClassLane:
    """Per-shard admission lane for one traffic class: inflight gauge,
    AIMD window, and the admitted/shed counters the stats block
    exports."""

    __slots__ = (
        "idx", "name", "wmin", "wmax", "window", "inflight",
        "admitted", "shed", "peer_ops", "_cooldown",
    )

    def __init__(self, idx: int, wmin: float, wmax: float) -> None:
        self.idx = idx
        self.name = CLASS_NAMES[idx]
        self.wmin = wmin
        self.wmax = wmax
        # Starts wide open: the window only matters under pressure.
        self.window = wmax
        self.inflight = 0
        self.admitted = 0
        self.shed = 0
        self.peer_ops = 0
        self._cooldown = 0

    def aimd(self, soft: bool) -> None:
        """One completed unit in this lane: multiplicative decrease
        while the CLASS reads soft overload (at most once per
        window's worth of completions — framed.aimd_tick's guard),
        additive recovery toward the class ceiling once it clears."""
        if self._cooldown > 0:
            self._cooldown -= 1
        if soft:
            if self._cooldown == 0:
                self.window = max(self.wmin, self.window / 2.0)
                self._cooldown = max(1, int(self.window))
        elif self.window < self.wmax:
            self.window = min(
                self.wmax, self.window + 1.0 / max(1.0, self.window)
            )


class QosPlane:
    """Per-shard QoS brain: class lanes + tenant buckets.  Owned by
    MyShard next to the governor; admission decisions combine the
    governor's per-class levels (signal thresholds scaled by the
    class factors) with the lane windows and the tenant buckets."""

    # Bound on distinct (tenant, collection) buckets kept live — the
    # tenant id arrives from the network; an adversarial id-per-op
    # stream must not grow this dict without bound.  Oldest-refill
    # eviction: a real tenant's bucket is touched constantly.
    MAX_BUCKETS = 4096

    def __init__(self, shard, config) -> None:
        self.shard = shard
        self.config = config
        wmin = float(max(1, config.overload_window_min))
        wmax_base = float(config.pipeline_window_max)
        max_w = max(CLASS_WEIGHTS)
        self.lanes = tuple(
            _ClassLane(
                i,
                wmin,
                max(wmin, wmax_base * CLASS_WEIGHTS[i] / max_w),
            )
            for i in range(NCLASSES)
        )
        # LRU by access (move_to_end on every touch): eviction is
        # O(1) — a min()-scan eviction would turn the adversarial
        # tenant-id-per-op stream this cap defends against into a
        # 4096-entry scan per op on the dispatch hot path.
        self._buckets: "OrderedDict[Tuple[str, str, str], TokenBucket]" = (
            OrderedDict()
        )
        # Per-tenant counters (stats): ops admitted / quota refusals.
        self.tenant_ops: Dict[str, int] = {}
        self.tenant_throttles: Dict[str, int] = {}
        self.quota_refusals = 0

    # -- class admission ----------------------------------------------

    def class_level(self, cls: int) -> int:
        return self.shard.governor.class_level(cls)

    def should_shed(self, cls: int) -> bool:
        """Hard-limit admission for NEW data ops of this class.

        Above STANDARD's floor the PR-5 contract holds unchanged:
        soft = backpressure (per-connection AIMD windows shrink),
        hard = shed.  Only the BATCH lane additionally sheds work
        beyond its weighted AIMD window while it reads soft — the
        admission-share squeeze that keeps one bulk load from
        occupying the backlog standard/interactive ops queue in
        (standard soft NEVER sheds, exactly as before this plane)."""
        from .governor import LEVEL_HARD, LEVEL_SOFT

        level = self.class_level(cls)
        if level >= LEVEL_HARD:
            return True
        if cls != QOS_BATCH:
            return False
        lane = self.lanes[cls]
        return level >= LEVEL_SOFT and lane.inflight >= lane.window

    def note_shed(self, cls: int) -> None:
        self.lanes[cls].shed += 1

    def begin(self, cls: int) -> None:
        lane = self.lanes[cls]
        lane.admitted += 1
        lane.inflight += 1

    def end(self, cls: int) -> None:
        from .governor import LEVEL_SOFT

        lane = self.lanes[cls]
        if lane.inflight > 0:
            lane.inflight -= 1
        lane.aimd(self.class_level(cls) >= LEVEL_SOFT)

    def note_peer(self, cls: int) -> None:
        """A replica-plane data frame carried this class (peer-frame
        dialect element): accounting only — the peer plane never
        sheds (replica work keeps quorums alive)."""
        self.lanes[cls].peer_ops += 1

    # -- tenant quotas -------------------------------------------------

    def _bucket(
        self, tenant: str, collection: str, kind: str, rate: int
    ) -> TokenBucket:
        key = (tenant, collection, kind)
        b = self._buckets.get(key)
        if b is None:
            if len(self._buckets) >= self.MAX_BUCKETS:
                self._buckets.popitem(last=False)  # LRU evict, O(1)
            b = self._buckets[key] = TokenBucket(rate)
        else:
            self._buckets.move_to_end(key)
            if b.rate != float(rate):
                # A per-collection override landed (or changed) after
                # this bucket was minted: adopt the new rate in place,
                # keeping the accumulated balance/debt.
                b.rate = float(rate)
                b.burst = max(1.0, b.rate * BUCKET_BURST_S)
        return b

    def quota_rates(self, collection) -> "Tuple[int, int]":
        """Effective (ops_per_sec, bytes_per_sec) for one collection:
        DDL-carried per-collection overrides (``create_collection``'s
        ``quotas`` metadata, ISSUE 15 satellite) beat the
        ``--tenant-*`` flag defaults; 0 disables a limit either way."""
        cfg = self.config
        ops, byts = cfg.tenant_ops_per_sec, cfg.tenant_bytes_per_sec
        cols = getattr(self.shard, "collections", None)
        col = (
            cols.get(collection)
            if cols is not None and isinstance(collection, str)
            else None
        )
        q = getattr(col, "quotas", None) if col is not None else None
        if q:
            if q.get("ops_per_sec") is not None:
                ops = int(q["ops_per_sec"])
            if q.get("bytes_per_sec") is not None:
                byts = int(q["bytes_per_sec"])
        return ops, byts

    def charge_ops(
        self, tenant: Optional[str], collection, n: int = 1
    ) -> None:
        """Admission-time op charge.  Raises the retryable
        ``QuotaExceeded`` when the tenant's op OR byte bucket for this
        collection is exhausted (byte debt blocks new ops until the
        refill covers it)."""
        if tenant is None:
            return
        col = collection if isinstance(collection, str) else ""
        ops_rate, bytes_rate = self.quota_rates(col)
        # Byte-debt check FIRST: it charges nothing, so an op refused
        # for byte debt must not burn ops tokens (a tenant retrying
        # through a byte overdraft would otherwise drain its ops
        # bucket on refusals and stay throttled past the byte quota).
        if bytes_rate > 0:
            b = self._bucket(tenant, col, "bytes", bytes_rate)
            b._refill(None)
            if b.tokens <= 0.0:
                self._refuse(tenant, "bytes")
        if ops_rate > 0:
            if not self._bucket(tenant, col, "ops", ops_rate).take(n):
                self._refuse(tenant, "ops")
        self._bump(self.tenant_ops, tenant, n)

    def charge_bytes(
        self, tenant: Optional[str], collection, nbytes: int
    ) -> None:
        """Post-op byte debt (the real payload size is only known
        after encode/serve).  Never raises — the NEXT op pays."""
        if tenant is None or nbytes <= 0:
            return
        col = collection if isinstance(collection, str) else ""
        rate = self.quota_rates(col)[1]
        if rate <= 0:
            return
        self._bucket(tenant, col, "bytes", rate).debit(nbytes)

    def _bump(self, d: Dict[str, int], tenant: str, n: int) -> None:
        """Bounded per-tenant counter bump: the tenant id arrives
        from the network, so these dicts carry the same adversarial-
        id-per-op exposure as the bucket table — past the cap an
        arbitrary existing entry is dropped (observability counters,
        not accounting state; real tenants are re-bumped constantly
        and every get_stats response stays bounded)."""
        if tenant not in d and len(d) >= self.MAX_BUCKETS:
            d.pop(next(iter(d)))
        d[tenant] = d.get(tenant, 0) + n

    def _refuse(self, tenant: str, which: str) -> None:
        self.quota_refusals += 1
        self._bump(self.tenant_throttles, tenant, 1)
        raise QuotaExceeded(
            f"tenant {tenant!r} over its {which} quota; retry after "
            "backoff — tokens refill continuously"
        )

    # -- errors shared with the dispatcher ----------------------------

    def shed_error(self, cls: int) -> Overloaded:
        """The interpreted shed error.  Message BYTE-IDENTICAL to the
        prebuilt native shed response (install_native_overload_
        responses packs the same text) — the two paths must answer
        the same bytes; which CLASS shed lives in the lane counters,
        not the message."""
        self.note_shed(cls)
        return Overloaded(
            f"shard {self.shard.shard_name} shedding load"
        )

    # -- observability -------------------------------------------------

    def stats(self) -> dict:
        classes = {}
        for lane in self.lanes:
            classes[lane.name] = {
                "admitted": lane.admitted,
                "shed": lane.shed,
                "inflight": lane.inflight,
                "window": round(lane.window, 2),
                "window_max": round(lane.wmax, 2),
                "peer_ops": lane.peer_ops,
                "level": self.class_level(lane.idx),
            }
        dp = getattr(self.shard, "dataplane", None)
        native_sheds = (
            dp.sheds_by_class() if dp is not None else None
        )
        if native_sheds is not None:
            for i, lane in enumerate(self.lanes):
                classes[lane.name]["native_sheds"] = native_sheds[i]
        # Native lane accounting (ISSUE 15 satellite): frames the C
        # planes served per class.  ``peer_ops`` counts interpreted
        # replica frames; ``peer_ops_native`` adds the C-served share
        # so replica-plane class accounting covers BOTH paths.
        native_admits = (
            dp.admits_by_class() if dp is not None else None
        )
        if native_admits is not None:
            client_adm, peer_adm = native_admits
            for i, lane in enumerate(self.lanes):
                classes[lane.name]["native_admits"] = client_adm[i]
                classes[lane.name]["peer_ops_native"] = peer_adm[i]
        tenants = {}
        for t in self.tenant_ops:
            tenants[t] = {
                "ops": self.tenant_ops.get(t, 0),
                "throttles": self.tenant_throttles.get(t, 0),
            }
        for t in self.tenant_throttles:
            if t not in tenants:
                tenants[t] = {
                    "ops": 0,
                    "throttles": self.tenant_throttles[t],
                }
        # Live token balances (rounded): the operator's "why is this
        # tenant throttled" answer.  Keyed tenant/collection/kind.
        tokens = {}
        for (t, col, kind), b in self._buckets.items():
            tokens.setdefault(t, {}).setdefault(col, {})[kind] = round(
                b.tokens, 1
            )
        return {
            "classes": classes,
            "tenants": tenants,
            "tenant_tokens": tokens,
            "quota_refusals": self.quota_refusals,
            "ops_per_sec_limit": self.config.tenant_ops_per_sec,
            "bytes_per_sec_limit": self.config.tenant_bytes_per_sec,
        }
