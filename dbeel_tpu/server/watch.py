"""Watch/CDC streaming plane — change feeds that survive kill,
partition, and churn (ISSUE 20).

The document API was strictly request/response; this module adds a
resumable, loss-free change stream on top of the planes that already
exist:

* **Per-shard change feed** — every acked mutation (client writes,
  replica SET/DELETE/MULTI_SET applies, decided CAS outcomes,
  migration RANGE_PUSH applies, hint replays) funnels through
  ``LSMTree.set_with_timestamp``/``set_batch_with_timestamp``, whose
  ``on_commit`` hook fires at the WAL group-commit release point.
  The hook feeds a bounded in-memory ring stamped with a monotonic
  per-shard ``(boot_epoch, seq)`` cursor.  Evicted history is NOT
  lost: a subscriber whose cursor fell off the ring (or predates the
  current boot) catches up from durable state via the PR 12 scan
  machinery — every replayed event explicitly dup-flagged, never
  silent.
* **Coordinator fan-out** — ``watch``/``watch_next`` client verbs
  serve CHUNKED event frames.  The coordinator assigns every ring
  arc (``all_arcs``) to one live replica, grouped per replica shard
  (one WATCH_FEED peer page per distinct replica per chunk, ranges
  partitioning the keyspace so feeds never systematically overlap),
  dedups newest-wins per key inside the chunk, and stamps a fully
  self-contained cursor token into EVERY chunk — the stream resumes
  on ANY node, across coordinator death, Overloaded sheds, and
  membership churn.
* **Failure handling** — the cursor carries the membership epoch; a
  stale one refuses retryably as ``not-owned`` mid-migration (the
  PR 18/19 fence discipline) and the client resyncs.  An arc whose
  replica died or whose bounds changed restarts from durable state
  (``handoff_resumes``), flagged.  Subscribers are admitted through
  the governor in the batch lane with per-subscriber byte budgets:
  slow or greedy watchers shed with the retryable ``Overloaded``
  instead of wedging the shard — the pull model means a stalled
  subscriber holds zero server-side buffer.

Delivery semantics are STATE delivery (etcd-style compaction): for
every acked write ``(k, ts)`` the stream delivers some event
``(k, ts' >= ts)`` after the ack — exactly once, or flagged as a
possible duplicate during catch-up/handoff windows.  Tombstones
arrive as empty values (deletes).  A filter spec (PR 13 dialect)
is evaluated replica-side on the tail path; under a spec, deletes
and non-matching versions are elided — the stream is then a filtered
view, not a full ledger.
"""

from __future__ import annotations

import asyncio
import secrets
import time
from collections import deque
from itertools import islice
from typing import Dict, List, Optional, Tuple

import msgpack

from .. import query as Q
from ..cluster.local_comm import LocalShardConnection
from ..cluster.messages import ShardRequest, ShardResponse
from ..errors import (
    BadFieldType,
    KeyNotOwnedByShard,
    Overloaded,
    PeerDead,
    ProtocolError,
    from_wire,
)
from ..utils.murmur import hash_bytes
from . import qos as qos_mod
from . import trace as trace_mod

# w1: the self-contained watch cursor.  Arity lint-pinned
# (analysis/wire_parity.py) against encode_cursor/decode_cursor —
# [version, collection, spec, membership_epoch, sub_id, groups];
# each group [shard_name, ranges, boot, seq, flag_until, catchup,
# flag_ts],
# catchup nil or [range_idx, start_after, probe_boot, probe_seq].
CURSOR_VERSION = "w1"
_CURSOR_ARITY = 6
_GROUP_ARITY = 7
_CATCHUP_ARITY = 4

# Event flag bits (4th element of every delivered event).
FLAG_DUP = 1  # may have been delivered before (catch-up/handoff)

# Commit-lag flag threshold: every state-transfer re-commit (hinted
# handoff replay, anti-entropy heal, read repair, migration ingest)
# applies entries with their ORIGINAL mint timestamp, so it reaches
# the ring well behind the wall clock — and a subscriber may already
# have received that key from a previously-tailed replica before a
# handoff, with the catch-up flag window long closed.  Flagging any
# commit this far behind the clock at the SOURCE keeps the "exactly
# once or explicitly dup-flagged" contract through hint drain.  A
# fresh quorum write commits within milliseconds of minting; a false
# flag (slow legitimate write) is safe — the flag only ever means
# "MAY have been delivered before".
LATE_COMMIT_FLAG_S = 2.0

# The per-group wall-clock flag window (``_FeedGroup.flag_ts``) can
# retire once every event minted inside it would be flagged at the
# source by the commit-lag rule anyway; 2x the threshold leaves no
# boundary gap between the two.
_FLAG_TS_GRACE_NS = int(2 * LATE_COMMIT_FLAG_S * 1e9)

# Per-feed page bounds (the scan plane's discipline).
PAGE_MAX_EVENTS = 4096
PAGE_MIN_BYTES = 4 << 10
ENTRY_OVERHEAD = 24

# Long-poll clamp: an empty chunk parks at most this long on the
# LOCAL ring before answering empty (remote-arc events surface on
# the next poll — the client's backoff is the latency bound there).
WAIT_MAX_S = 2.0

# Subscriber registry TTL: a sub_id not seen for this long stops
# counting toward the subscribers gauge and frees its byte bucket.
SUB_TTL_S = 60.0

# Soft-level pacing (scan.py's bounded-park discipline).
PACE_SLICE_S = 0.05
PACE_MAX_S = 2.0

# after_seq sentinel: position probe — no events, just the ring's
# current (boot_epoch, seq).
_PROBE = -1

# Per-subscriber byte bucket burst: seconds of the refill rate
# (--watch-bytes-per-slice per second) a subscriber may consume at
# once before shedding.
_BUCKET_BURST_S = 2.0

# Unpacked client filter specs, keyed by the raw blob (the tail path
# re-evaluates the same spec on every event — validate once).
_spec_cache: dict = {}


def _spec_where(spec_raw: bytes):
    w = _spec_cache.get(spec_raw)
    if w is None:
        if len(_spec_cache) > 256:
            _spec_cache.clear()
        try:
            where, agg = Q.unpack_spec(spec_raw)
        except BadFieldType:
            raise
        except Exception as e:
            raise BadFieldType(f"spec: {e}") from e
        if agg is not None:
            raise BadFieldType("spec: aggregate with a watch")
        w = _spec_cache[spec_raw] = where
    return w


def encode_cursor(
    collection: str,
    spec: Optional[bytes],
    epoch: int,
    sub_id: str,
    groups: List["_FeedGroup"],
) -> bytes:
    """Opaque resumable cursor: self-contained, so ANY node can
    continue the stream — across coordinator death, sheds, and
    fail-over.  Re-stamped with the CURRENT membership epoch every
    chunk, so a long-lived subscriber never goes stale-fenced while
    it keeps polling."""
    return msgpack.packb(
        [
            CURSOR_VERSION,
            collection,
            spec,
            epoch,
            sub_id,
            [
                [
                    g.shard_name,
                    g.ranges,
                    g.boot,
                    g.seq,
                    g.flag_until,
                    g.catchup,
                    g.flag_ts,
                ]
                for g in groups
            ],
        ],
        use_bin_type=True,
    )


def decode_cursor(raw) -> dict:
    if not isinstance(raw, (bytes, bytearray)):
        raise BadFieldType("cursor")
    try:
        w = msgpack.unpackb(bytes(raw), raw=False)
    except Exception as e:
        raise BadFieldType(f"cursor: {e}") from e
    if (
        not isinstance(w, list)
        or len(w) != _CURSOR_ARITY
        or w[0] != CURSOR_VERSION
        or not isinstance(w[1], str)
        or not isinstance(w[3], int)
        or not isinstance(w[4], str)
        or not isinstance(w[5], list)
    ):
        raise BadFieldType("cursor: unknown version or shape")
    groups = []
    for g in w[5]:
        if not isinstance(g, (list, tuple)) or len(g) != _GROUP_ARITY:
            raise BadFieldType("cursor: group shape")
        name, ranges, boot, seq, flag_until, catchup, flag_ts = g
        if not isinstance(name, str) or not isinstance(ranges, list):
            raise BadFieldType("cursor: group shape")
        try:
            ranges = [[int(r[0]), int(r[1])] for r in ranges]
        except Exception as e:
            raise BadFieldType(f"cursor: ranges ({e})") from e
        if catchup is not None:
            if (
                not isinstance(catchup, (list, tuple))
                or len(catchup) != _CATCHUP_ARITY
            ):
                raise BadFieldType("cursor: catchup shape")
            catchup = [
                int(catchup[0]),
                bytes(catchup[1]) if catchup[1] is not None else None,
                int(catchup[2]),
                int(catchup[3]),
            ]
        groups.append(
            {
                "shard_name": name,
                "ranges": ranges,
                "boot": int(boot),
                "seq": int(seq),
                "flag_until": int(flag_until),
                "catchup": catchup,
                "flag_ts": int(flag_ts),
            }
        )
    return {
        "collection": w[1],
        "spec": bytes(w[2]) if w[2] is not None else None,
        "epoch": w[3],
        "sub_id": w[4],
        "groups": groups,
    }


class _FeedGroup:
    """One replica shard's tail feed over its assigned ring arcs."""

    __slots__ = (
        "shard_name",
        "ranges",
        "boot",  # -1 = fresh group, init-probe to start at the tail
        "seq",
        "flag_until",  # tail events with seq <= this are dup-flagged
        "catchup",  # [range_idx, start_after, probe_boot, probe_seq]
        # Wall-clock flag window: tail events MINTED at or before
        # this (ns) are dup-flagged too.  Closes the replication-lag
        # gap the seq window cannot: a write the subscriber already
        # received from the PREVIOUS replica may still be in flight
        # to this one when the catch-up's closing probe runs, so it
        # lands past flag_until with a fresh-looking seq.  Events
        # minted before the catch-up completed are exactly the ones
        # that could have been delivered elsewhere first.
        "flag_ts",
        "shard",  # ring entry; None = serve locally
    )

    def __init__(self, shard_name, ranges, shard):
        self.shard_name = shard_name
        self.ranges = ranges
        self.boot = -1
        self.seq = 0
        self.flag_until = 0
        self.catchup = None
        self.flag_ts = 0
        self.shard = shard


def _feed_result(resp) -> tuple:
    """(events, boot_epoch, tail_seq, status) out of a WATCH_FEED
    peer response list."""
    if (
        not isinstance(resp, (list, tuple))
        or len(resp) < 2
        or resp[0] != "response"
    ):
        raise ProtocolError(f"not a response: {resp!r}")
    if resp[1] == ShardResponse.ERROR:
        raise from_wire(resp[2:4])
    if resp[1] != ShardResponse.WATCH_FEED or len(resp) < 6:
        raise ProtocolError(
            f"expected watch_feed response, got {resp[1]!r}"
        )
    events = resp[2] if isinstance(resp[2], (list, tuple)) else []
    return events, int(resp[3]), int(resp[4]), int(resp[5])


class WatchPlane:
    """Per-shard change ring (replica role) + watch fan-out
    (coordinator role) + counters (exported as ``get_stats.watch``)."""

    def __init__(self, shard, config) -> None:
        self.shard = shard
        self.config = config
        # ---- replica role: the change ring -------------------------
        # boot_epoch makes (boot_epoch, seq) monotonic ACROSS process
        # restarts under the same loosely-synced wall clock the LWW
        # timestamps already assume: a restarted shard's ring starts
        # a new epoch, and any cursor from the old one catches up
        # from durable state.
        self.boot_epoch = int(time.time() * 1000)
        self.seq = 0
        self.ring: deque = deque(maxlen=max(16, config.watch_ring))
        self._ring_events: Dict[str, asyncio.Event] = {}
        # ---- counters (stats-schema lint: all exported below) ------
        self.watches_started = 0
        self.chunks = 0
        self.events_delivered = 0
        self.bytes_streamed = 0
        self.cursor_resumes = 0
        self.catchup_replays = 0
        self.ring_evictions = 0
        self.handoff_resumes = 0
        self.dup_flagged = 0
        self.late_commit_flags = 0
        self.sheds = 0
        self.fence_refusals = 0
        self.feed_pages = 0
        self.pages_pulled = 0
        self.paced = 0
        self.paced_s = 0.0
        self.native_suspends = 0
        self.active_chunks = 0
        # Chunks currently parked in an empty-ring long-poll wait.
        # The governor subtracts this from its admitted-ops signal:
        # a park holds an event-wait and some registry bytes, not a
        # CPU queue slot, and counting it as work would let a big
        # idle-subscriber pool push the shard to hard overload and
        # shed REAL traffic.  Watch admission is the subscriber cap
        # + per-subscriber byte buckets, not the ops ledger.
        self.parked_chunks = 0
        # sub_id -> [last_seen_mono, bucket_tokens, refill_mono,
        #            last_local_tail_seq|None] (the lag gauge compares
        # local tails against this ring's head).
        self._subs: Dict[str, list] = {}
        self._native_suspended: set = set()

    def stats(self) -> dict:
        self._prune_subs()
        lag = 0
        for e in self._subs.values():
            if e[3] is not None:
                lag = max(lag, self.seq - e[3])
        return {
            "subscribers": len(self._subs),
            "watches_started": self.watches_started,
            "chunks": self.chunks,
            "events_delivered": self.events_delivered,
            "bytes_streamed": self.bytes_streamed,
            "cursor_resumes": self.cursor_resumes,
            "catchup_replays": self.catchup_replays,
            "ring_evictions": self.ring_evictions,
            "handoff_resumes": self.handoff_resumes,
            "dup_flagged": self.dup_flagged,
            "late_commit_flags": self.late_commit_flags,
            "sheds": self.sheds,
            "fence_refusals": self.fence_refusals,
            "feed_pages": self.feed_pages,
            "pages_pulled": self.pages_pulled,
            "paced": self.paced,
            "paced_s": round(self.paced_s, 3),
            "native_suspends": self.native_suspends,
            "active_chunks": self.active_chunks,
            "parked_chunks": self.parked_chunks,
            "ring_seq": self.seq,
            "ring_len": len(self.ring),
            "lag_events": lag,
            "ring_capacity": self.config.watch_ring,
            "max_subscribers": self.config.watch_max_subscribers,
            "bytes_per_slice": self.config.watch_bytes_per_slice,
        }

    # -- replica role: feed + publish ----------------------------------

    def publish(self, collection: str, key, value, ts: int) -> None:
        """The LSMTree ``on_commit`` hook target: one acked mutation
        enters the ring at the WAL group-commit release point.  A
        commit whose timestamp lags the wall clock by more than
        LATE_COMMIT_FLAG_S is a state-transfer re-apply (hint
        replay, anti-entropy, read repair, migration) and is
        dup-flagged at the source — see the constant's comment."""
        ts = int(ts)
        flags = 0
        if ts < int((time.time() - LATE_COMMIT_FLAG_S) * 1e9):
            flags = FLAG_DUP
            self.late_commit_flags += 1
        if len(self.ring) == self.ring.maxlen:
            self.ring_evictions += 1
        self.seq += 1
        self.ring.append(
            (self.seq, collection, bytes(key), bytes(value), ts,
             flags)
        )
        evt = self._ring_events.get(collection)
        if evt is not None and not evt.is_set():
            evt.set()

    def _listen(self, collection: str) -> asyncio.Event:
        """Current-publish event for ONE collection: set once on its
        next publish (the flush_start_event.listen() idiom — publish
        swaps a fresh Event in so late listeners never miss a set).
        Per-collection so a thousand idle watchers parked on a quiet
        collection do not wake (and re-poll) on every write to a hot
        one — publish pays one dict probe either way."""
        evt = self._ring_events.get(collection)
        if evt is None or evt.is_set():
            self._ring_events[collection] = evt = asyncio.Event()
        return evt

    def suspend_native(self, name: str) -> None:
        """First watch interest in a collection suspends its native
        fast path (sticky, like a quarantine suspension): writes the
        C plane serves never cross the Python commit hook, so a
        watched collection must route every write through the
        interpreted path or the ring would silently miss events.
        Writes already served in C before suspension are durable —
        the catch-up scan covers them."""
        if name in self._native_suspended:
            return
        self._native_suspended.add(name)
        shard = self.shard
        if getattr(shard, "dataplane", None) is not None:
            try:
                shard.dataplane.unregister(name)
                self.native_suspends += 1
            except Exception:
                # Not registered / stale .so: the interpreted path
                # already owns the collection's writes.
                pass

    def feed_page(
        self,
        collection: str,
        boot_epoch: int,
        after_seq: int,
        ranges,
        limit: int,
        max_bytes: int,
        spec: Optional[bytes],
    ) -> Tuple[list, int, int, int]:
        """One WATCH_FEED page off the local ring: events strictly
        after ``after_seq`` of ``boot_epoch``, ascending by seq,
        filtered to the collection, the key-hash ranges, and the
        optional spec.  Status 1 = the position is not servable from
        the ring (older boot, or evicted) — the coordinator must
        catch up from durable state.  The O(1) empty fast path is
        the idle-watcher scalability hinge: a thousand idle polls
        cost a thousand integer compares, not a thousand ring
        walks."""
        self.feed_pages += 1
        if after_seq == _PROBE:
            return [], self.boot_epoch, self.seq, 0
        first = self.seq - len(self.ring)
        if boot_epoch != self.boot_epoch or after_seq < first:
            return [], self.boot_epoch, self.seq, 1
        if after_seq >= self.seq:
            return [], self.boot_epoch, self.seq, 0
        where = _spec_where(bytes(spec)) if spec is not None else None
        in_range = self.shard._in_ae_range
        events: list = []
        out = 0
        tail = after_seq
        for ev in islice(self.ring, after_seq - first, None):
            seq, col, key, value, ts, fl = ev
            tail = seq
            if col != collection:
                continue
            if ranges:
                h = hash_bytes(key)
                if not any(
                    in_range(h, r[0], r[1]) for r in ranges
                ):
                    continue
            if spec is not None and not Q.match_entry(
                where, key, value
            ):
                continue
            events.append([key, value, ts, seq, fl])
            out += len(key) + len(value) + ENTRY_OVERHEAD
            if len(events) >= limit or out >= max_bytes:
                break
        return events, self.boot_epoch, tail, 0

    # -- subscriber registry / byte buckets ----------------------------

    def _prune_subs(self) -> None:
        now = time.monotonic()
        dead = [
            k
            for k, e in self._subs.items()
            if now - e[0] > SUB_TTL_S
        ]
        for k in dead:
            del self._subs[k]

    def _bucket_admit(self, sub_id: str) -> bool:
        """Refill-and-check the subscriber's byte bucket (capacity =
        burst seconds of --watch-bytes-per-slice per second).  The
        bucket may go negative on a served chunk (a chunk is never
        truncated for it); the NEXT chunk sheds until it refills."""
        now = time.monotonic()
        rate = float(max(1, self.config.watch_bytes_per_slice))
        cap = _BUCKET_BURST_S * rate
        e = self._subs.get(sub_id)
        if e is None:
            self._subs[sub_id] = [now, cap, now, None]
            return True
        e[1] = min(cap, e[1] + (now - e[2]) * rate)
        e[2] = now
        e[0] = now
        return e[1] > 0

    def _bucket_charge(self, sub_id: str, n: int) -> None:
        e = self._subs.get(sub_id)
        if e is not None:
            e[1] -= n

    def _note_local_tail(self, sub_id: str, tail: int) -> None:
        e = self._subs.get(sub_id)
        if e is not None:
            e[3] = tail

    # -- admission -----------------------------------------------------

    def _shed(self, why: str, cls: Optional[int] = None):
        self.sheds += 1
        if cls is not None:
            self.shard.qos.note_shed(cls)
        return Overloaded(f"watch chunk shed: {why}")

    async def _admit(self, ctx, cls: int = qos_mod.QOS_BATCH) -> None:
        from .governor import LEVEL_HARD, LEVEL_SOFT

        gov = self.shard.governor
        if gov.class_level(cls) >= LEVEL_HARD:
            raise self._shed(
                f"shard {self.shard.shard_name} at hard overload "
                f"for {qos_mod.CLASS_NAMES[cls]}-class work",
                cls,
            )
        if gov.class_level(cls) >= LEVEL_SOFT:
            if gov.memtable_only_soft(cls):
                self.paced += 1
                self.paced_s += PACE_SLICE_S
                await asyncio.sleep(PACE_SLICE_S)
            else:
                self.paced += 1
                waited = 0.0
                while (
                    waited < PACE_MAX_S
                    and gov.class_level(cls) >= LEVEL_SOFT
                    and not gov.memtable_only_soft(cls)
                ):
                    if gov.class_level(cls) >= LEVEL_HARD:
                        raise self._shed(
                            "hard overload during watch pacing", cls
                        )
                    await asyncio.sleep(PACE_SLICE_S)
                    waited += PACE_SLICE_S
                self.paced_s += waited
        if ctx is not None:
            ctx.mark("pace")

    # -- coordinator role: the chunk loop ------------------------------

    async def handle(self, request: dict, rtype: str) -> bytes:
        """One watch/watch_next client frame → one chunk payload
        {"events": [[key, value, ts, flags], ...], "cursor": bin}.
        The cursor is present in EVERY chunk; value b"" = delete."""
        my_shard = self.shard
        deadline_ms = request.get("deadline_ms")
        if (
            isinstance(deadline_ms, int)
            and deadline_ms > 0
            and time.time() * 1000.0 > deadline_ms
        ):
            my_shard.governor.deadline_drops += 1
            raise Overloaded(
                "client deadline expired before the watch chunk ran"
            )
        if rtype == "watch":
            collection = request.get("collection")
            if not isinstance(collection, str):
                raise BadFieldType("collection")
            spec_raw = request.get("spec")
            if spec_raw is not None:
                spec_raw = bytes(spec_raw)
                _spec_where(spec_raw)  # validate before first use
            sub_id = request.get("sub_id")
            if not isinstance(sub_id, str) or not sub_id:
                sub_id = secrets.token_hex(8)
            groups_wire = None
            self.watches_started += 1
        else:  # watch_next
            cur = decode_cursor(request.get("cursor"))
            collection = cur["collection"]
            spec_raw = cur["spec"]
            sub_id = cur["sub_id"]
            # Membership-epoch fence (the PR 18/19 discipline): a
            # cursor stamped before the current churn began may map
            # arcs that moved mid-migration — refuse retryably, the
            # client resyncs metadata and retries the SAME cursor
            # (which this node then re-stamps with the new epoch).
            epoch = cur["epoch"]
            if (
                isinstance(epoch, int)
                and epoch > 0
                and epoch < my_shard.membership_epoch
                and my_shard._migration_tasks
            ):
                my_shard.fence_refusals += 1
                self.fence_refusals += 1
                raise KeyNotOwnedByShard(
                    f"watch cursor epoch {epoch} predates membership "
                    f"epoch {my_shard.membership_epoch} mid-migration"
                )
            groups_wire = cur["groups"]
            self.cursor_resumes += 1

        ctx = trace_mod.current()
        q = request.get("qos")
        cls = (
            qos_mod.class_of(q) if q is not None else qos_mod.QOS_BATCH
        )
        tenant = qos_mod.request_tenant(request)
        col = my_shard.get_collection(collection)
        self.suspend_native(collection)
        my_shard.qos.charge_ops(tenant, collection, 1)
        self._prune_subs()
        cap = self.config.watch_max_subscribers
        if (
            cap > 0
            and sub_id not in self._subs
            and len(self._subs) >= cap
        ):
            raise self._shed(
                f"{len(self._subs)} watch subscribers already "
                "registered",
                cls,
            )
        if not self._bucket_admit(sub_id):
            raise self._shed(
                f"subscriber {sub_id} over its byte budget", cls
            )
        wait_ms = request.get("wait_ms")
        wait_s = (
            min(WAIT_MAX_S, wait_ms / 1000.0)
            if isinstance(wait_ms, int) and wait_ms > 0
            else 0.0
        )
        self.active_chunks += 1
        began = False
        try:
            await self._admit(ctx, cls)
            my_shard.qos.begin(cls)
            began = True
            payload = await self._chunk(
                col,
                collection,
                spec_raw,
                sub_id,
                groups_wire,
                cls,
                wait_s,
                ctx,
            )
            my_shard.qos.charge_bytes(tenant, collection, len(payload))
            self._bucket_charge(sub_id, len(payload))
            return payload
        finally:
            if began:
                my_shard.qos.end(cls)
            self.active_chunks -= 1

    def _reconcile_groups(
        self, col, groups_wire: Optional[list]
    ) -> List[_FeedGroup]:
        """Assign every current ring arc to one live replica shard
        and fold the assignment into feed groups (one per distinct
        replica).  Sticky: arcs prefer a replica the cursor already
        tails, so steady-state chunks keep their positions.  A group
        whose range set changed — churn moved an arc, or its replica
        died/handed off — restarts from durable state with every
        replayed event dup-flagged (state redelivery is correct and
        loss-free; only stale positions are discarded)."""
        my_shard = self.shard
        arcs = my_shard.all_arcs(col.replication_factor)
        old_by_shard = {}
        if groups_wire:
            for g in groups_wire:
                old_by_shard[g["shard_name"]] = g
        assign: Dict[str, list] = {}  # name -> [shard_entry, ranges]
        for start, end, selected in arcs:
            live = [
                s
                for s in selected
                if s.name == my_shard.shard_name
                or s.node_name not in my_shard.dead_nodes
            ]
            if not live:
                raise PeerDead(
                    f"watch: every replica of arc [{start}, {end}) "
                    "is marked Dead"
                )
            pick = next(
                (s for s in live if s.name in old_by_shard), None
            )
            if pick is None:
                pick = next(
                    (
                        s
                        for s in live
                        if s.name == my_shard.shard_name
                    ),
                    live[0],
                )
            entry = assign.get(pick.name)
            if entry is None:
                assign[pick.name] = entry = [
                    None
                    if pick.name == my_shard.shard_name
                    else pick,
                    [],
                ]
            entry[1].append([int(start), int(end)])
        groups: List[_FeedGroup] = []
        for name, (shard_entry, ranges) in assign.items():
            ranges.sort()
            g = _FeedGroup(name, ranges, shard_entry)
            old = old_by_shard.get(name)
            if old is not None and old["ranges"] == ranges:
                g.boot = old["boot"]
                g.seq = old["seq"]
                g.flag_until = old["flag_until"]
                g.catchup = old["catchup"]
                g.flag_ts = old["flag_ts"]
            elif groups_wire is not None:
                # Arc handoff / churn: the position (if any) no
                # longer covers this range set — replay durable
                # state, flagged, then re-tail.
                self.handoff_resumes += 1
                g.catchup = [0, None, 0, 0]  # probe pending
            # groups_wire None = fresh watch: boot stays -1 and the
            # init probe below starts the tail AT NOW (no replay).
            groups.append(g)
        return groups

    async def _peer_call(self, g: _FeedGroup, req: list):
        my_shard = self.shard
        if g.shard is None:
            return await my_shard.handle_shard_request(req)
        if isinstance(g.shard.connection, LocalShardConnection):
            return await g.shard.connection.send_request(
                my_shard.id, req
            )
        return await g.shard.connection.send_request(req)

    async def _fetch_feed(
        self,
        g: _FeedGroup,
        collection: str,
        spec: Optional[bytes],
        page_bytes: int,
        cls: int,
        after_seq: int,
        boot: int,
    ) -> tuple:
        req = ShardRequest.watch_feed(
            collection,
            boot,
            after_seq,
            g.ranges,
            PAGE_MAX_EVENTS,
            page_bytes,
            spec,
            cls,
        )
        resp = await self._peer_call(g, req)
        self.pages_pulled += 1
        return _feed_result(resp)

    async def _catchup_page(
        self,
        g: _FeedGroup,
        collection: str,
        spec: Optional[bytes],
        where,
        page_bytes: int,
        cls: int,
        out_events: list,
    ) -> None:
        """One durable-state page of this group's catch-up: scan peer
        frames over the assigned ranges (the PR 12 machinery), every
        entry dup-flagged.  When the last range drains, probe the
        feed once more: tail events at or before that probed seq may
        also be in the scanned state — the flag window — and events
        after it cannot be (the ring is ordered by commit)."""
        if g.catchup[2] == 0 and g.catchup[3] == 0:
            # Start of catch-up: probe the feed position FIRST — the
            # scan view includes everything committed before this
            # point, so the tail resumes here.
            _e, boot, tail, _s = await self._fetch_feed(
                g, collection, spec, page_bytes, cls, _PROBE, 0
            )
            g.catchup[2] = boot
            g.catchup[3] = tail
            self.catchup_replays += 1
        range_idx = g.catchup[0]
        if range_idx < len(g.ranges):
            start, end = g.ranges[range_idx]
            req = ShardRequest.scan(
                collection,
                start,
                end,
                g.catchup[1],
                None,
                PAGE_MAX_EVENTS,
                page_bytes,
                True,
                None,
                cls,
            )
            resp = await self._peer_call(g, req)
            self.pages_pulled += 1
            if (
                not isinstance(resp, (list, tuple))
                or len(resp) < 4
                or resp[0] != "response"
            ):
                raise ProtocolError(f"not a response: {resp!r}")
            if resp[1] == ShardResponse.ERROR:
                raise from_wire(resp[2:4])
            if resp[1] != ShardResponse.SCAN:
                raise ProtocolError(
                    f"expected scan response, got {resp[1]!r}"
                )
            entries = resp[2] or []
            more = bool(resp[3])
            for key, value, ts in entries:
                key = bytes(key)
                value = bytes(value) if value is not None else b""
                if spec is not None:
                    if not Q.match_entry(where, key, value):
                        continue
                out_events.append([key, value, int(ts), FLAG_DUP])
                self.dup_flagged += 1
            if entries:
                g.catchup[1] = bytes(entries[-1][0])
            if not more:
                g.catchup[0] = range_idx + 1
                g.catchup[1] = None
            return
        # Every range drained: close the flag window with a second
        # probe and resume the tail from the FIRST probe's position.
        _e, boot, tail, _s = await self._fetch_feed(
            g, collection, spec, page_bytes, cls, _PROBE, 0
        )
        g.boot = g.catchup[2]
        g.seq = g.catchup[3]
        g.flag_until = tail if boot == g.catchup[2] else 0
        g.flag_ts = time.time_ns()
        g.catchup = None

    async def _serve_groups(
        self,
        groups: List[_FeedGroup],
        collection: str,
        spec: Optional[bytes],
        where,
        sub_id: str,
        page_bytes: int,
        cls: int,
        out_events: list,
    ) -> None:
        for g in groups:
            if g.catchup is not None:
                await self._catchup_page(
                    g,
                    collection,
                    spec,
                    where,
                    page_bytes,
                    cls,
                    out_events,
                )
                continue
            if g.boot == -1:
                # Fresh group: start the tail at the ring's head —
                # a new watch observes from NOW.
                _e, boot, tail, _s = await self._fetch_feed(
                    g, collection, spec, page_bytes, cls, _PROBE, 0
                )
                g.boot = boot
                g.seq = tail
                if g.shard is None:
                    self._note_local_tail(sub_id, tail)
                continue
            events, boot, tail, status = await self._fetch_feed(
                g,
                collection,
                spec,
                page_bytes,
                cls,
                g.seq,
                g.boot,
            )
            if status != 0:
                # The position fell off the ring (or the replica
                # rebooted): replay durable state, flagged.
                g.catchup = [0, None, 0, 0]
                continue
            if g.flag_ts and (
                time.time_ns() - g.flag_ts > _FLAG_TS_GRACE_NS
            ):
                # Anything minted before flag_ts now publishes at
                # least LATE_COMMIT_FLAG_S behind the clock, so the
                # source-side flag takes over — drop the window.
                g.flag_ts = 0
            for key, value, ts, seq, fl in events:
                flags = int(fl)
                if g.flag_until and seq <= g.flag_until:
                    flags |= FLAG_DUP
                if g.flag_ts and int(ts) <= g.flag_ts:
                    flags |= FLAG_DUP
                if flags:
                    self.dup_flagged += 1
                out_events.append(
                    [bytes(key), bytes(value), int(ts), flags]
                )
            g.boot = boot
            g.seq = tail
            if g.flag_until and tail >= g.flag_until:
                g.flag_until = 0
            if g.shard is None:
                self._note_local_tail(sub_id, tail)

    async def _chunk(
        self,
        col,
        collection: str,
        spec_raw: Optional[bytes],
        sub_id: str,
        groups_wire: Optional[list],
        cls: int,
        wait_s: float,
        ctx,
    ) -> bytes:
        my_shard = self.shard
        where = (
            _spec_where(spec_raw) if spec_raw is not None else None
        )
        groups = self._reconcile_groups(col, groups_wire)
        budget = self.config.watch_bytes_per_slice
        page_bytes = max(
            PAGE_MIN_BYTES, budget // max(1, len(groups))
        )
        events: list = []
        await self._serve_groups(
            groups,
            collection,
            spec_raw,
            where,
            sub_id,
            page_bytes,
            cls,
            events,
        )
        if ctx is not None:
            ctx.mark("iterate")
        if not events and wait_s > 0 and all(
            g.catchup is None for g in groups
        ):
            # Long-poll: park on the LOCAL ring (bounded) — a local
            # publish wakes the chunk for one more serve round;
            # remote-arc events surface on the client's next poll.
            evt = self._listen(collection)
            self.parked_chunks += 1
            try:
                await asyncio.wait_for(evt.wait(), wait_s)
            except asyncio.TimeoutError:
                pass
            finally:
                self.parked_chunks -= 1
            await self._serve_groups(
                groups,
                collection,
                spec_raw,
                where,
                sub_id,
                page_bytes,
                cls,
                events,
            )
        if len(events) > 1:
            # Newest-wins per-key dedup inside the chunk (state
            # delivery): keep each key's newest version, preserving
            # the dup flag if ANY occurrence carried it.
            newest: dict = {}
            for ev in events:
                cur = newest.get(ev[0])
                if cur is None:
                    newest[ev[0]] = ev
                else:
                    if ev[2] >= cur[2]:
                        ev[3] |= cur[3]
                        newest[ev[0]] = ev
                    else:
                        cur[3] |= ev[3]
            events = list(newest.values())
        cursor = encode_cursor(
            collection,
            spec_raw,
            my_shard.membership_epoch,
            sub_id,
            groups,
        )
        payload = msgpack.packb(
            {"events": events, "cursor": cursor},
            use_bin_type=True,
        )
        self.chunks += 1
        self.events_delivered += len(events)
        self.bytes_streamed += len(payload)
        if ctx is not None:
            ctx.mark("merge")
        return payload
