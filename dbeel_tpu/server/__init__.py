"""L1/L5/L6: shard runtime, cluster hub, and the public document API."""

from .shard import MyShard, Shard, ShardConnection  # noqa: F401
