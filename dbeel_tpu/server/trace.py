"""Per-request tracing plane: span contexts + the flight recorder.

Every plane so far was tuned against *aggregate* evidence — the
``get_stats`` histograms say a p99 spike exists, but not whether the
time went to queue wait, WAL fsync, the table probe, peer RTT, or the
quorum settle.  This module adds Dapper-style per-request attribution
(PAPERS.md related work on production LSM serving):

* ``TraceCtx`` — one sampled request's span: strictly sequential
  stage marks (they partition [t0, end], so the stage sum equals the
  total by construction), a ``detail`` side-channel for overlapping
  measurements (the local write that runs concurrently with the
  quorum fan-out), and per-replica entries carrying each peer's RTT
  plus the stage summary the replica piggybacked on its response
  frame.
* ``FlightRecorder`` — a bounded per-shard ring holding full spans
  for sampled ops (server-side 1-in-N via ``--trace-sample``, or any
  op whose client stamped a ``trace`` id on the request frame) plus a
  minimal record for EVERY op that finishes slow (>``--slow-op-us``)
  or with a taxonomy error — the always-sample-the-slow-tail rule, so
  the interesting ops are in the ring even at sample=0.  Queried over
  the wire via the admin ``trace_dump`` verb (always served, like
  ``get_stats``).

Sampling is deliberately routed through the interpreted path: a
sampled (or client-stamped) frame bypasses the native fast paths so
the span gets real stage marks, and the peer frames it fans out carry
the trace id so replicas punt their native plane and piggyback their
own stage summary.  Unsampled traffic pays nothing — the native plane
keeps serving it, and its latency shows up in the coarse per-verb
stage counters the C side stamps (``get_stats.trace.native``).
"""

from __future__ import annotations

import contextvars
import itertools
import time
from collections import deque
from typing import List, Optional

# The active span for the current task tree (the fan-out helpers in
# shard.py read it to time replicas without threading a parameter
# through every call site).
CURRENT: contextvars.ContextVar = contextvars.ContextVar(
    "dbeel_trace", default=None
)

_ids = itertools.count(1)


def current() -> "Optional[TraceCtx]":
    return CURRENT.get()


def new_trace_id() -> int:
    """Server-assigned trace ids: wall-ms prefix + counter, unique
    enough per process and sortable in a dump."""
    return (int(time.time() * 1000) << 20) | (next(_ids) & 0xFFFFF)


# Response base arities for piggyback stripping: a replica's stage
# summary rides as ONE extra trailing element on its response frame,
# so anything beyond the base arity that looks like a span is one.
# (Kept here, next to the absorb logic; the encoders in
# cluster/messages.py are the source of truth for the base shapes.)
_RESP_BASE = {
    "set": 2,
    "delete": 2,
    "multi_set": 2,
    "range_push": 2,
    "rearm": 2,
    "get": 3,
    "get_digest": 3,
    "multi_get": 3,
    "range_pull": 3,
}


def split_peer_span(resp):
    """(response, replica_span|None): pop a piggybacked stage summary
    off a peer response list.  A span is a list of non-negative ints
    sitting exactly one element beyond the verb's base arity; old-
    dialect responses simply lack it."""
    if not isinstance(resp, list) or len(resp) < 2:
        return resp, None
    base = _RESP_BASE.get(resp[1])
    if base is None or len(resp) != base + 1:
        return resp, None
    tail = resp[-1]
    if isinstance(tail, (list, tuple)) and all(
        isinstance(x, int) and x >= 0 for x in tail
    ):
        return resp[:base], list(tail)
    return resp, None


class TraceCtx:
    """One sampled request's span under construction."""

    __slots__ = (
        "trace_id",
        "op",
        "collection",
        "client_stamped",
        "t0",
        "_last",
        "stages",
        "detail",
        "replicas",
    )

    def __init__(
        self,
        trace_id: int,
        op: str = "?",
        collection: Optional[str] = None,
        t0: Optional[float] = None,
        client_stamped: bool = False,
    ) -> None:
        self.trace_id = trace_id
        self.op = op
        self.collection = collection
        self.client_stamped = client_stamped
        self.t0 = time.monotonic() if t0 is None else t0
        self._last = self.t0
        self.stages: List[list] = []  # [name, us] in wall order
        self.detail: dict = {}  # overlapping sub-measurements (us)
        self.replicas: List[dict] = []

    def mark(self, stage: str) -> None:
        """Close the wall segment since the previous mark under
        ``stage``.  Marks are strictly sequential, so
        sum(stage us) == total us by construction."""
        now = time.monotonic()
        us = int((now - self._last) * 1e6)
        self._last = now
        if self.stages and self.stages[-1][0] == stage:
            self.stages[-1][1] += us
        else:
            self.stages.append([stage, us])

    def note(self, key: str, us: int) -> None:
        """Overlapping measurement (e.g. the local write inside the
        quorum gather): attributed but NOT part of the stage sum."""
        self.detail[key] = self.detail.get(key, 0) + int(us)

    def replica(
        self, node: str, rtt_us: int, span: "Optional[list]"
    ) -> None:
        self.replicas.append(
            {
                "node": node,
                "rtt_us": int(rtt_us),
                # Replica stage summary (u32 micros piggybacked on
                # the peer response frame): [queue_us, serve_us].
                "stages": span,
            }
        )

    def absorb_peer(self, node: str, rtt_us: int, resp):
        """Record one replica's RTT (+ piggybacked span when present)
        and return the response with the piggyback stripped, so the
        quorum interpret path sees the base-arity frame.  Accepts the
        raw payload bytes of the packed fan-out path too (unpacked
        here; the interpreter tolerates pre-unpacked lists)."""
        if isinstance(resp, (bytes, bytearray)):
            from ..cluster import messages as msgs

            try:
                resp = msgs.unpack_message(bytes(resp))
            except Exception:
                self.replica(node, rtt_us, None)
                return resp
        resp, span = split_peer_span(resp)
        self.replica(node, rtt_us, span)
        return resp

    def finish(self, error_kind: Optional[str] = None) -> dict:
        total_us = int((time.monotonic() - self.t0) * 1e6)
        return {
            "trace_id": self.trace_id,
            "op": self.op,
            "collection": self.collection,
            "client_stamped": self.client_stamped,
            "sampled": True,
            "ts_ms": int(time.time() * 1000),
            "total_us": total_us,
            "stages": [list(s) for s in self.stages],
            "detail": dict(self.detail),
            "replicas": list(self.replicas),
            "error": error_kind,
        }


class FlightRecorder:
    """Bounded per-shard ring of trace records.

    ``sample_every`` = N means every Nth client frame dispatched by
    this shard gets a full span (0 disables server-side sampling;
    client-stamped traces always record).  Slow (> ``slow_op_us``)
    and taxonomy-error ops ALWAYS land in the ring — as their full
    span when they happened to be sampled, else as a minimal record —
    so the tail is diagnosable post-hoc at any sampling rate."""

    # Minimal (slow/error) records admitted per second: under a hard
    # overload EVERY op is slow or shed, and an unbounded capture
    # rate would churn the whole ring with homogeneous drop records
    # within milliseconds — evicting the sampled spans and
    # pre-overload evidence the dump exists to serve.  Full spans
    # (record_span) are never limited: sampling already bounds them.
    MINIMAL_PER_S = 200

    def __init__(
        self,
        sample_every: int = 0,
        slow_op_us: int = 100_000,
        capacity: int = 512,
    ) -> None:
        self.sample_every = max(0, int(sample_every))
        self.slow_op_us = max(1, int(slow_op_us))
        self.capacity = max(8, int(capacity))
        self._ring: deque = deque(maxlen=self.capacity)
        self._tick = 0
        self._min_tokens = float(self.MINIMAL_PER_S)
        self._min_refill_at: "float | None" = None
        # Counters (exported via get_stats.trace).
        self.recorded = 0
        self.evicted = 0
        self.sampled = 0
        self.client_traced = 0
        self.slow_captured = 0
        self.error_captured = 0
        self.capture_suppressed = 0

    # -- sampling decisions -------------------------------------------

    @property
    def sampling(self) -> bool:
        return self.sample_every > 0

    def tick(self) -> bool:
        """One client frame considered: True when this one is the
        1-in-N sample.  A cheap counter compare on the serving path;
        never True while sampling is disabled."""
        if self.sample_every <= 0:
            return False
        self._tick += 1
        if self._tick >= self.sample_every:
            self._tick = 0
            return True
        return False

    # -- recording -----------------------------------------------------

    def _push(self, entry: dict) -> None:
        if len(self._ring) >= self.capacity:
            self.evicted += 1
        self._ring.append(entry)
        self.recorded += 1

    def record_span(
        self, ctx: TraceCtx, error_kind: Optional[str] = None
    ) -> dict:
        """Finalize and ring a full sampled span."""
        entry = ctx.finish(error_kind)
        self.sampled += 1
        if ctx.client_stamped:
            self.client_traced += 1
        if entry["total_us"] >= self.slow_op_us:
            entry["slow"] = True
            self.slow_captured += 1
        if error_kind is not None:
            self.error_captured += 1
        self._push(entry)
        return entry

    def _admit_minimal(self) -> bool:
        """Token bucket over minimal records; suppressed captures are
        counted (they remain visible in the error/shed counters of
        get_stats — the ring just stops churning on them)."""
        now = time.monotonic()
        if self._min_refill_at is None:
            self._min_refill_at = now
        self._min_tokens = min(
            float(self.MINIMAL_PER_S),
            self._min_tokens
            + (now - self._min_refill_at) * self.MINIMAL_PER_S,
        )
        self._min_refill_at = now
        if self._min_tokens >= 1.0:
            self._min_tokens -= 1.0
            return True
        self.capture_suppressed += 1
        return False

    def note_op(
        self, op: str, us: int, error_kind: Optional[str] = None
    ) -> None:
        """Unsampled completion: capture ONLY when slow or errored
        (minimal record — op, latency, error; no stages)."""
        slow = us >= self.slow_op_us
        if not slow and error_kind is None:
            return
        if not self._admit_minimal():
            return
        if slow:
            self.slow_captured += 1
        if error_kind is not None:
            self.error_captured += 1
        self._push(
            {
                "op": op,
                "sampled": False,
                "slow": slow,
                "ts_ms": int(time.time() * 1000),
                "total_us": int(us),
                "error": error_kind,
            }
        )

    # -- querying ------------------------------------------------------

    def dump(self) -> dict:
        """The ``trace_dump`` payload: ring contents (oldest first) +
        recorder counters.  Always served, like get_stats — an
        operator must be able to read the tail OF an overload DURING
        the overload."""
        return {
            "capacity": self.capacity,
            "sample_every": self.sample_every,
            "slow_op_us": self.slow_op_us,
            "entries": list(self._ring),
            **self.stats(),
        }

    def stats(self) -> dict:
        return {
            "recorded": self.recorded,
            "evicted": self.evicted,
            "sampled": self.sampled,
            "client_traced": self.client_traced,
            "slow_captured": self.slow_captured,
            "error_captured": self.error_captured,
            "capture_suppressed": self.capture_suppressed,
        }
