"""Per-shard background tasks.

Role parity with /root/reference/src/tasks/: local shard server
(local_shard_server.rs), remote shard server (remote_shard_server.rs),
compaction scheduler (compaction.rs), gossip server (gossip_server.rs),
failure detector (failure_detector.rs), and the stop-event waiter
(stop_event_waiter.rs).
"""

from __future__ import annotations

import asyncio
import logging
import os
import random
import time

from ..errors import DbeelError, ShardStopped
from ..flow_events import FlowEvent
from ..cluster import messages as msgs
from ..cluster.local_comm import ShardPacket
from ..cluster.messages import (
    ShardEvent,
    ShardResponse,
    pack_message,
    unpack_message,
)
from ..cluster.remote_comm import (
    MAX_MESSAGE,
    RemoteShardConnection,
)
from . import framed
from .shard import MyShard

log = logging.getLogger(__name__)

GOSSIP_REQUEST_EXPIRATION_S = 30.0  # gossip_server.rs:17
UDP_PACKET_BUFFER_SIZE = 65536
MIN_COMPACTION_FACTOR = 2  # compaction.rs:13


# ----------------------------------------------------------------------
# Local shard server (local_shard_server.rs:8-66)
# ----------------------------------------------------------------------


async def run_local_shard_server(my_shard: MyShard) -> None:
    queue = my_shard.local_connection.queue
    while True:
        packet: ShardPacket = await queue.get()
        try:
            response = await my_shard.handle_shard_message(packet.message)
        except DbeelError as e:
            response = msgs.ShardResponse.error(e)
        except Exception as e:
            log.exception("local shard message failed")
            response = ["response", ShardResponse.ERROR, "Internal", str(e)]
        if packet.response_future is not None:
            if not packet.response_future.done():
                packet.response_future.set_result(
                    response
                    if response is not None
                    else ShardResponse.pong()
                )


# ----------------------------------------------------------------------
# Remote shard server (remote_shard_server.rs:19-102)
# ----------------------------------------------------------------------


class _RemoteShardProtocol(framed.FramedServerProtocol):
    """Raw-protocol remote shard server (the db server's _DbProtocol
    treatment applied to the peer plane): 4-byte-LE-length msgpack
    frames parsed in data_received, replica-plane set/delete/get
    answered synchronously by the native data plane
    (dataplane.try_handle_shard), everything else drained in arrival
    order through the unchanged handle_shard_message path.  Wire
    format and error behavior identical to the stream version
    (remote_shard_server.rs:23-49 parity: persistent multi-message
    connections).

    Overload plane (ISSUE 5): the peer plane never SHEDS (replica
    work is what keeps quorums alive; its admission happened at the
    coordinator), but its read-pause watermark rides the same AIMD
    window the public plane uses — while this shard's governor reads
    backlog, frames pause earlier, pushing bytes back into the
    coordinator's capped outbound queue instead of buffering them
    here.  Expired-deadline peer frames are dropped by
    handle_shard_request (deadline propagation)."""

    HEADER = 4
    MAX_FRAME = MAX_MESSAGE
    WINDOW_MIN = 8.0

    __slots__ = ()

    def __init__(self, my_shard) -> None:
        super().__init__(my_shard)
        self.window = float(self.PENDING_HIGH)

    def _pending_high(self) -> int:
        return max(int(self.WINDOW_MIN), int(self.window))

    def _registry(self) -> set:
        # Tracked for shutdown: py3.12 Server.wait_closed() waits on
        # open protocol connections, and peer streams are persistent.
        return self.shard.remote_connections

    def _on_disconnect(self) -> None:
        # Fire-and-forget senders (send_event, migration streams)
        # write their last frames and close immediately: frames
        # already received MUST still be applied, exactly like the
        # stream server kept serving readexactly's buffer after EOF.
        # So the drain is NOT cancelled here — it finishes
        # self.pending (skipping response writes once the transport
        # is closing) and exits.  Shard shutdown cancels it via
        # _background_tasks; the base drain suppresses its respawn on
        # cancellation.
        pass

    def _try_fast(self, frame: bytes) -> int:
        dp = self.shard.dataplane
        if dp is None:
            return framed.FAST_MISS
        fast = dp.try_handle_shard(frame)
        if fast is None:
            return framed.FAST_MISS
        # Replica-side serving is foreground work (set/delete/get/
        # multi only on this path; the anti-entropy exemption applies
        # to RANGE_* messages, which always punt).
        self.shard.scheduler.fg_mark()
        resp, flush_tree, notify_set, defer, deadline_dropped = fast
        if deadline_dropped:
            # Expired propagated budget answered natively with the
            # retryable Overloaded frame: count it exactly like the
            # interpreted drop (handle_shard_request parity).
            self.shard.governor.replica_deadline_drops += 1
        if flush_tree is not None:
            self.shard.spawn(flush_tree.flush())
        if defer is not None:
            # wal-sync: a replica ack is a durability promise to the
            # coordinator — park it (and the flow notification, which
            # the Python handler also fires only after the synced
            # write) until the fdatasync watermark covers the ticket.
            syncer, ticket = defer
            entry = self.park_response(resp)
            shard = self.shard

            def _release(e=entry, notify=notify_set):
                self.finish_park(e)
                if notify:
                    shard.flow.notify(
                        FlowEvent.ITEM_SET_FROM_SHARD_MESSAGE
                    )

            syncer.park(ticket, _release)
            return framed.FAST_HANDLED
        if resp is not None:
            if self.parked:
                self.park_response(resp, done=True)
            else:
                self._write_out(resp)
        if notify_set:
            self.shard.flow.notify(
                FlowEvent.ITEM_SET_FROM_SHARD_MESSAGE
            )
        return framed.FAST_HANDLED

    async def _serve_one(self, frame: bytes, arrived: float = 0.0) -> bool:
        my_shard = self.shard
        try:
            message = unpack_message(frame)
        except Exception:
            # Malformed msgpack: stop talking to this peer, but the
            # remaining length-delimited frames were received intact
            # — keep applying them (writes skipped, transport
            # closing).
            self.transport.close()
            return True
        # Replica-side serving (quorum writes/reads from peers) is
        # foreground work too.  Anti-entropy's own requests must NOT
        # mark: they are background traffic, and marking would make
        # the peer-side bg_slice throttle against the very request it
        # serves.
        if not (
            isinstance(message, (list, tuple))
            and len(message) > 1
            and message[0] == "request"
            and message[1]
            in (
                msgs.ShardRequest.RANGE_DIGEST,
                msgs.ShardRequest.RANGE_PULL,
                msgs.ShardRequest.RANGE_PUSH,
                # Scan pages are governed background work too: the
                # coordinator admitted the chunk; the replica-side
                # page must not mark foreground activity or the
                # bg_slice it runs under would throttle against the
                # very request it serves.
                msgs.ShardRequest.SCAN,
            )
        ):
            my_shard.scheduler.fg_mark()
        # Tracing plane: a coordinator stamped a trace id on this
        # peer frame — measure our own stages and piggyback the
        # summary on the response, so an RF>1 op's span decomposes
        # into coordinator + per-replica time.  The native replica
        # plane punts traced frames (want+2 dialect), so every
        # sampled frame lands here.
        trace_id = MyShard.peer_trace_id(message)
        t_serve = time.monotonic()
        try:
            response = await my_shard.handle_shard_message(message)
        except DbeelError as e:
            response = msgs.ShardResponse.error(e)
        except Exception as e:
            log.exception("remote shard message failed")
            response = [
                "response",
                ShardResponse.ERROR,
                "Internal",
                str(e),
            ]
        if (
            trace_id is not None
            and isinstance(response, list)
            and len(response) >= 2
            and response[0] == "response"
            and response[1] != ShardResponse.ERROR
        ):
            # Replica stage summary (u32 micros): [queue_us,
            # serve_us] — frame receipt → dispatch, and the storage
            # work itself.  One extra trailing element past the base
            # arity; the coordinator's fan-out strips it before the
            # quorum interpret (trace.split_peer_span).
            now = time.monotonic()
            queue_us = int(
                max(0.0, t_serve - (arrived or t_serve)) * 1e6
            )
            response = response + [
                [queue_us, int((now - t_serve) * 1e6)]
            ]
        if (
            response is not None
            and not self.closing
            and not self.transport.is_closing()
        ):
            # Ack order per stream: queue behind parked fast-path
            # acks still awaiting their WAL sync.
            await self._wait_parked_drained()
            await self.writable.wait()
            if self.closing or self.transport.is_closing():
                return True  # keep applying buffered frames
            payload = pack_message(response)
            self._write_out(
                len(payload).to_bytes(4, "little") + payload
            )
        self.aimd_tick(self.WINDOW_MIN, float(self.PENDING_HIGH))
        return True


async def bind_remote_shard_server(my_shard: MyShard) -> asyncio.Server:
    port = my_shard.config.remote_port(my_shard.id)
    server = await asyncio.get_event_loop().create_server(
        lambda: _RemoteShardProtocol(my_shard),
        my_shard.config.ip,
        port,
    )
    log.info(
        "listening for distributed messages on %s:%d",
        my_shard.config.ip,
        port,
    )
    return server


async def run_remote_shard_server(my_shard: MyShard, server=None) -> None:
    if server is None:
        server = await bind_remote_shard_server(my_shard)
    async with server:
        await server.serve_forever()


# ----------------------------------------------------------------------
# Compaction scheduler (compaction.rs:13-153)
# ----------------------------------------------------------------------


def _leading_zeros64(n: int) -> int:
    return 64 - n.bit_length() if n else 64


async def compact_tree(
    tree, compaction_factor: int, scheduler=None
) -> None:
    """Size-tiered grouping by size order (leading_zeros) with cascade
    merge of adjacent orders (compaction.rs:35-102).  Each merge is one
    background unit under the share scheduler: while serving is busy,
    consecutive merges are spaced to the fg/bg share ratio."""
    indices_and_sizes = tree.sstable_indices_and_sizes()

    odd = [i for i, _ in indices_and_sizes if i % 2 != 0]
    index_to_compact = (max(odd) + 2) if odd else 1

    groups: dict = {}
    for i, size in indices_and_sizes:
        groups.setdefault(_leading_zeros64(size), []).append((i, size))

    # Largest sstables first (smallest leading_zeros first).
    ordered = sorted(groups.items())
    optimized: dict = {}
    for size_order, items in ordered:
        if size_order in optimized:
            items = items + optimized.pop(size_order)
        estimated = _leading_zeros64(sum(s for _, s in items))
        target = min(estimated, size_order)
        optimized.setdefault(target, []).extend(items)

    for i, items in enumerate(optimized.values()):
        if len(items) < MIN_COMPACTION_FACTOR or len(
            items
        ) < compaction_factor:
            continue
        indices = [idx for idx, _ in items]
        # Drop tombstones only on the final (largest) level
        # (compaction.rs:90-92).
        keep_tombstones = i > 0
        try:
            if scheduler is not None:
                async with scheduler.bg_slice():
                    await tree.compact(
                        indices, index_to_compact, keep_tombstones
                    )
            else:
                await tree.compact(
                    indices, index_to_compact, keep_tombstones
                )
        except Exception as e:
            log.error("failed to compact files: %s", e)
        index_to_compact += 2


async def run_compaction_loop(my_shard: MyShard) -> None:
    compaction_factor = my_shard.config.compaction_factor
    if compaction_factor < MIN_COMPACTION_FACTOR:
        return

    async def trees_and_listeners():
        while not my_shard.collections:
            await my_shard.collections_change_event.listen()
        trees = [c.tree for c in my_shard.collections.values()]
        listeners = [t.flush_done_event.listen() for t in trees]
        return trees, listeners

    trees, listeners = await trees_and_listeners()

    # Compact once on startup (crash may have left ungrouped files).
    await asyncio.gather(
        *[
            compact_tree(t, compaction_factor, my_shard.scheduler)
            for t in trees
        ]
    )

    while True:
        change = asyncio.ensure_future(
            my_shard.collections_change_event.wait()
        )
        done, _pending = await asyncio.wait(
            [change, *listeners], return_when=asyncio.FIRST_COMPLETED
        )
        if change.done():
            for fut in listeners:
                fut.cancel()
            trees, listeners = await trees_and_listeners()
            continue
        change.cancel()
        for i, fut in enumerate(listeners):
            if fut.done():
                listeners[i] = trees[i].flush_done_event.listen()
                await compact_tree(
                    trees[i], compaction_factor, my_shard.scheduler
                )


# ----------------------------------------------------------------------
# Anti-entropy (beyond-reference: SURVEY §5 lists anti-entropy as a gap
# in the reference's replication design).  Each shard periodically
# compares per-bucket digests of every arc in its EXACT owned-range
# union (MyShard.replica_arcs: primary range + the replicated
# predecessor slices, exact under interleaved multi-shard nodes) with
# that arc's replica shards — successors AND predecessors; on
# mismatch it pushes its entries (batched RANGE_PUSH, applied on the
# peer only when strictly newer than the peer's newest — never through
# raw Set events, which could shadow newer flushed values) and pulls
# the peer's (same strictly-newer guard locally), so both sides
# converge on the union.  Every unit runs under the share scheduler.
#
# Known caveats (documented, Cassandra has the same fundamentals):
#  * Granularity is the whole primary range: one diverged key
#    transfers the range's entries (the strictly-newer guard makes the
#    applies no-ops, but the bytes still cross).  Sub-range/merkle
#    digests are the refinement path.
#  * Bottom-level compaction drops tombstones (reference parity); a
#    replica that GC'd a delete before every peer saw it can have the
#    old value resurrected by a later sync — the classic
#    tombstone-GC-before-repair window (Cassandra's gc_grace).  Keep
#    the anti-entropy interval well below compaction churn.
# ----------------------------------------------------------------------

ANTI_ENTROPY_PAGE = 2048


async def _sync_range_with_peer(
    my_shard, name, tree, peer, start, end, counts, digests
):
    """Compare per-bucket digests with one peer; push+pull ONLY the
    diverged hash sub-ranges.  A single diverged key now transfers
    ~range/nbuckets entries instead of the whole primary range (the
    round-2 whole-range caveat, resolved with a flat merkle layer)."""
    from ..cluster.messages import ShardRequest, ShardResponse

    nb = len(counts)
    resp = await peer.connection.send_request(
        ShardRequest.range_digest(name, start, end, nb)
    )
    msgs.response_to_result(resp, ShardResponse.RANGE_DIGEST)
    diverged = _diverged_buckets(counts, digests, resp, nb)
    if not diverged:
        return False
    bucket_set = set(diverged)

    # Push ours in batched pages from ONE materialized snapshot of the
    # diverged buckets; the peer applies strictly-newer only.
    async with my_shard.scheduler.bg_slice():
        mine = await my_shard.collect_range_entries(
            tree, start, end, None, bucket_set, nb
        )
    pushed = 0
    for off in range(0, len(mine), ANTI_ENTROPY_PAGE):
        page = mine[off : off + ANTI_ENTROPY_PAGE]
        # Counter stamped at SEND: the peer applies the page before
        # its ack travels back, so an observer who sees the data
        # converge must also see the transfer counted — stamping
        # after the await left a window where convergence was
        # visible with ae_entries_pushed still 0.
        my_shard.ae_entries_pushed += len(page)
        async with my_shard.scheduler.bg_slice():
            msgs.response_to_result(
                await peer.connection.send_request(
                    ShardRequest.range_push(name, page)
                ),
                ShardResponse.RANGE_PUSH,
            )
        pushed += len(page)
    # ...and pull theirs (same diverged buckets), applying only
    # strictly-newer entries.
    fetched, pulled = await _pull_buckets_from_peer(
        my_shard, name, tree, peer, start, end, diverged, nb
    )
    if pushed or pulled:
        log.info(
            "anti-entropy %s with %s: %d/%d buckets diverged, "
            "pushed %d, fetched %d, applied %d pulled",
            name,
            peer.name,
            len(diverged),
            nb,
            pushed,
            fetched,
            pulled,
        )
    my_shard.flow.notify(FlowEvent.ANTI_ENTROPY_SYNCED)
    # Local state changed only if a pull applied — the caller
    # recomputes the shared digest exactly then.
    return pulled > 0


async def run_anti_entropy(my_shard: MyShard) -> None:
    """Background anti-entropy — the convergence backstop that fires
    with no reads and no hints (expired TTL, capacity drops, crashed
    coordinators): every interval, exchange per-bucket range digests
    with the replicas of each arc in this shard's EXACT owned-range
    union (MyShard.replica_arcs — the same helper the quarantine
    repair scopes its pulls with) and push/pull only the diverged
    buckets.  Every unit runs under the share scheduler, a sibling of
    the scrub loop: continuous maintenance priced like compaction."""
    interval = my_shard.config.anti_entropy_interval_ms / 1000.0
    if interval <= 0:
        return
    nb = max(1, my_shard.config.anti_entropy_buckets)
    while True:
        await asyncio.sleep(interval)
        for name, col in list(my_shard.collections.items()):
            rf = col.replication_factor
            if rf <= 1:
                continue
            # The owned-range union, one entry per merged arc with
            # the peer shards that replicate that arc.  On the common
            # single-shard-per-node ring with nodes <= rf the arcs
            # collapse to ONE whole-ring range; interleaved
            # multi-shard nodes get their exact slices.
            for start, end, peers in my_shard.replica_arcs(rf):
                if not peers:
                    continue
                try:
                    # One digest scan per arc fills ALL sub-range
                    # buckets, shared by that arc's peer comparisons.
                    # The LOCAL scans sit inside the same guard as
                    # the peer exchanges: a corrupted page raises
                    # CorruptedFile right here (quarantining the
                    # table as a side effect), and before this guard
                    # that exception escaped the task set and took
                    # the whole shard down (observed in the chaos
                    # soak when the disk-fault bit-flip landed on the
                    # partition victim) — quarantine repair owns the
                    # heal; AE just skips the arc this round.
                    async with my_shard.scheduler.bg_slice():
                        counts, digests = (
                            await my_shard.compute_range_digests(
                                col.tree, start, end, nb
                            )
                        )
                    for peer in peers:
                        try:
                            pulled_any = await _sync_range_with_peer(
                                my_shard,
                                name,
                                col.tree,
                                peer,
                                start,
                                end,
                                counts,
                                digests,
                            )
                            if pulled_any:
                                # A pull changed our range: later
                                # peers must compare against the
                                # CURRENT digests or every one of
                                # them re-syncs.
                                async with my_shard.scheduler.bg_slice():
                                    counts, digests = (
                                        await my_shard.compute_range_digests(
                                            col.tree, start, end, nb
                                        )
                                    )
                        except (DbeelError, OSError) as e:
                            log.warning(
                                "anti-entropy %s with %s failed: %s",
                                name,
                                peer.name,
                                e,
                            )
                except (DbeelError, OSError) as e:
                    log.warning(
                        "anti-entropy %s local digest scan failed "
                        "(skipping arc this round): %s",
                        name,
                        e,
                    )
        my_shard.ae_rounds += 1
        my_shard.flow.notify(FlowEvent.ANTI_ENTROPY_DONE)


# ----------------------------------------------------------------------
# Hint drain (replica-convergence plane, PR 4): the periodic retry leg
# of hinted handoff.  The Alive-gossip edge replays immediately; this
# loop covers everything the edge misses — hints reloaded from the WAL
# after a restart (the target was discovered at boot, no Alive edge
# fires), a replay that failed midway, a target that bounced.  Skips
# nodes still believed down; every page runs under the share scheduler
# at the configured keys/sec ceiling (MyShard.replay_hints).
# ----------------------------------------------------------------------


async def run_hint_drain(my_shard: MyShard) -> None:
    import time as _time

    interval = my_shard.config.hint_drain_interval_ms / 1000.0
    ttl_s = my_shard.config.hint_ttl_ms / 1000.0
    if interval <= 0 or my_shard.config.hint_ttl_ms <= 0:
        return
    while True:
        await asyncio.sleep(interval)
        # Close the TTL window of nodes that never came back: stop
        # hinting them (every write was paying a hint-log append),
        # expire their queued hints, and hand their backfill to
        # anti-entropy.  A node decommissioned via the detector-Dead
        # path stops costing anything after one TTL.
        now = _time.time()
        for node, since in list(my_shard.departed_at.items()):
            if now - since > ttl_s:
                my_shard.departed_shards.pop(node, None)
                my_shard.departed_at.pop(node, None)
                my_shard._merged_walk_cache = None
                dropped = my_shard.hint_log.expire_node(node)
                log.info(
                    "hint TTL window for %s closed: %d hints "
                    "expired; anti-entropy owns its backfill",
                    node,
                    dropped,
                )
        for node in my_shard.hint_log.nodes_with_hints():
            if (
                node in my_shard.dead_nodes
                or node not in my_shard.nodes
            ):
                # Still down/unknown: keep queued, but the TTL clock
                # runs regardless — expiry cannot depend on a drain
                # that may never happen (a coordinator restart also
                # loses departed_at, so log-reloaded hints for a
                # never-rediscovered node expire HERE).
                my_shard.hint_log.expire_ttl_dead(node)
                continue
            try:
                await my_shard.replay_hints(node)
            except (DbeelError, OSError) as e:
                log.warning(
                    "hint drain to %s failed: %s", node, e
                )


# ----------------------------------------------------------------------
# Quarantine repair + background scrub (durability plane, PR 3 — no
# reference analog: the reference trusts every byte it reads back).
#
# Repair: when a checksum failure quarantines an sstable, the shard
# pulls the lost range back from its replicas THROUGH the existing
# anti-entropy machinery — per-bucket range digests gate the transfer,
# so only the buckets the quarantine actually diverged move, and
# apply_if_newer keeps the pulls LWW-safe.  The pull covers the EXACT
# owned-range union (MyShard.replica_arcs), one pull per arc per
# replica of that arc, and buckets that agree cost one digest frame.  Only after the pull
# completes are the quarantined files retired (tree.finish_repair)
# and suspect-miss reads re-enabled.
#
# Scrub: a background pass re-reads cold blocks directly (no page-
# cache pollution) at a bounded byte rate under the share scheduler,
# verifying them against the checksum sidecar — bit rot is found in
# weeks-old tables BEFORE a client read trips over it; a mismatch
# funnels into the exact same quarantine → repair path.
# ----------------------------------------------------------------------


async def _pull_buckets_from_peer(
    my_shard, name, tree, peer, start, end, buckets, nb
) -> "tuple[int, int]":
    """Paged RANGE_PULL of ``buckets`` from one peer, applying each
    entry strictly-newer — the pull half shared by the anti-entropy
    exchange and the quarantine repair (one implementation, so paging
    or dialect fixes can never diverge between them).  Returns
    (entries fetched, entries applied)."""
    from ..cluster.messages import ShardRequest, ShardResponse

    fetched = applied = 0
    page_after = None
    while True:
        resp = await peer.connection.send_request(
            ShardRequest.range_pull(
                name,
                start,
                end,
                page_after,
                ANTI_ENTROPY_PAGE,
                buckets,
                nb,
            )
        )
        entries = msgs.response_to_result(
            resp, ShardResponse.RANGE_PULL
        )
        if not entries:
            break
        fetched += len(entries)
        my_shard.ae_entries_fetched += len(entries)
        async with my_shard.scheduler.bg_slice():
            for key, value, ts in entries:
                if await my_shard.apply_if_newer(
                    tree, bytes(key), bytes(value), int(ts)
                ):
                    applied += 1
                    # Convergence accounting (get_stats.convergence):
                    # AE and repair pulls heal keys locally here.
                    my_shard.keys_healed += 1
        if len(entries) < ANTI_ENTROPY_PAGE:
            break
        page_after = bytes(entries[-1][0])
    return fetched, applied


def _diverged_buckets(counts, digests, resp, nb) -> list:
    """Bucket indices where our (count, digest) disagrees with a
    peer's RANGE_DIGEST response; defensive about old-dialect/junk
    shapes (everything diverged → whole-range sync, never a crash)."""
    try:
        p_counts, p_digests = list(resp[2]), list(resp[3])
    except TypeError:
        p_counts, p_digests = [], []
    if len(p_counts) != nb or len(p_digests) != nb:
        p_counts = [-1] * nb
        p_digests = [0] * nb
    return [
        b
        for b in range(nb)
        if (counts[b], digests[b]) != (p_counts[b], p_digests[b])
    ]


async def _pull_diverged_from_peer(
    my_shard, name, tree, peer, start, end, nb
) -> int:
    """Pull-only half of the anti-entropy exchange: compare per-bucket
    digests with one peer and apply (strictly-newer) everything in the
    diverged buckets.  Returns entries applied."""
    from ..cluster.messages import ShardRequest, ShardResponse

    async with my_shard.scheduler.bg_slice():
        counts, digests = await my_shard.compute_range_digests(
            tree, start, end, nb
        )
    resp = await peer.connection.send_request(
        ShardRequest.range_digest(name, start, end, nb)
    )
    msgs.response_to_result(resp, ShardResponse.RANGE_DIGEST)
    diverged = _diverged_buckets(counts, digests, resp, nb)
    if not diverged:
        return 0
    _fetched, applied = await _pull_buckets_from_peer(
        my_shard, name, tree, peer, start, end, diverged, nb
    )
    return applied


async def repair_collection(my_shard: MyShard, name: str) -> None:
    """Re-fetch whatever a quarantined table lost from this
    collection's replicas, then retire the quarantined files.

    Scope: the EXACT owned-range union (MyShard.replica_arcs — the
    same helper the anti-entropy loop walks), one digest-gated pull
    per (arc, replica-of-that-arc).  The old
    (rf-th-distinct-predecessor, self] arc over-approximated the
    union under interleaved multi-shard nodes, importing ranges this
    shard can never serve (ROADMAP open item, now closed); the exact
    arcs also pick each arc's TRUE replicas instead of a blanket
    both-directions node walk.  RF=1 (or a ring with no other node)
    has NO peer holding our data: the honest outcome is the
    lost-data branch, never a pull from a non-replica."""
    col = my_shard.collections.get(name)
    if col is None:
        return
    tree = col.tree
    covered = tree._quarantine_pending
    rf = col.replication_factor
    nb = max(1, my_shard.config.anti_entropy_buckets)
    arcs = my_shard.replica_arcs(rf) if rf > 1 else []
    arcs = [a for a in arcs if a[2]]  # only arcs with live peers
    if not arcs:
        log.warning(
            "repair of %s: no replica holds this shard's data — "
            "whatever only the quarantined table held is LOST; "
            "clearing the suspect state so reads answer again",
            name,
        )
        tree.finish_repair(covered, recovered=False)
        my_shard.flow.notify(FlowEvent.REPAIR_DONE)
        return
    applied = 0
    ok = 0
    for start, end, peers in arcs:
        arc_ok = 0
        for peer in peers:
            try:
                applied += await _pull_diverged_from_peer(
                    my_shard, name, tree, peer, start, end, nb
                )
                arc_ok += 1
            except (DbeelError, OSError) as e:
                log.warning(
                    "repair pull of %s from %s failed: %s",
                    name,
                    peer.name,
                    e,
                )
        if arc_ok == 0:
            # Every replica of this arc failed: the arc's lost range
            # is NOT yet recovered — keep the suspect state (reads
            # keep walking to replicas) and retry on a later
            # quarantine/scrub trigger rather than declaring a
            # repair that left a hole.
            log.error(
                "repair of %s: no peer reachable for arc "
                "[%d, %d); will retry",
                name,
                start,
                end,
            )
            return
        ok += arc_ok
    log.info(
        "repair of %s complete: %d entries re-applied over %d arcs "
        "(%d peer pulls)",
        name,
        applied,
        len(arcs),
        ok,
    )
    tree.finish_repair(covered)
    my_shard.flow.notify(FlowEvent.REPAIR_DONE)


SCRUB_CHUNK_PAGES = 64


def _scrub_read_chunk(fd: int, first_page: int, n: int, page_size: int):
    out = []
    for i in range(n):
        raw = os.pread(fd, page_size, (first_page + i) * page_size)
        if len(raw) < page_size:
            raw = raw + b"\x00" * (page_size - len(raw))
        out.append(raw)
    return out


async def _scrub_table(my_shard, tree, table, rate: int) -> None:
    import zlib

    from ..errors import CorruptedFile
    from ..storage.entry import PAGE_SIZE

    for reader, crcs in (
        (table._data, table.sums.data_crcs),
        (table._index, table.sums.index_crcs),
    ):
        page = 0
        npages = len(crcs)
        while page < npages:
            chunk = min(SCRUB_CHUNK_PAGES, npages - page)
            # Short acquire windows per chunk: holding the list
            # refcount for a whole rate-limited table would stall
            # compaction's reader-drain for minutes.
            lst = tree._sstables
            if (
                table not in lst.tables
                or table.index in tree._quarantined_indices
                or reader._fd < 0
            ):
                return  # compacted away / quarantined mid-scrub
            lst.acquire()
            try:
                async with my_shard.scheduler.bg_slice():
                    try:
                        raws = await asyncio.get_event_loop().run_in_executor(
                            None,
                            _scrub_read_chunk,
                            reader._fd,
                            page,
                            chunk,
                            PAGE_SIZE,
                        )
                    except OSError:
                        return  # fd closed under us: table retired
                for j, raw in enumerate(raws):
                    if zlib.crc32(raw) != crcs[page + j]:
                        exc = CorruptedFile(
                            f"{reader.path}: scrub found page "
                            f"{page + j} failing its CRC"
                        )
                        exc.path = reader.path
                        tree._handle_table_corruption(table, exc)
                        return
            finally:
                lst.release()
            my_shard.scrub_bytes_verified += chunk * PAGE_SIZE
            page += chunk
            # Bounded byte rate: cold-block verification must never
            # compete with foreground I/O (Pome's lesson: overlap is
            # where LSM throughput lives).
            await asyncio.sleep(chunk * PAGE_SIZE / rate)


async def run_scrub_loop(my_shard: MyShard) -> None:
    interval = my_shard.config.scrub_interval_ms / 1000.0
    if interval <= 0:
        return
    rate = max(1, my_shard.config.scrub_bytes_per_sec)
    while True:
        await asyncio.sleep(interval)
        from ..storage import checksums

        if not checksums.verification_enabled():
            # DBEEL_NO_CHECKSUMS=1 is the whole-plane kill switch
            # (distrusted sidecars / emergency): the scrub must not
            # keep quarantining behind the operator's back.
            continue
        for _name, col in list(my_shard.collections.items()):
            tables = list(col.tree._sstables.tables)
            for table in tables:
                if table.sums is None:
                    continue  # legacy table: nothing to verify against
                await _scrub_table(my_shard, col.tree, table, rate)
        my_shard.scrub_cycles += 1
        my_shard.flow.notify(FlowEvent.SCRUB_PASS_DONE)


# ----------------------------------------------------------------------
# Gossip server (gossip_server.rs:16-112) — node-managing shard only
# ----------------------------------------------------------------------


class _GossipProtocol(asyncio.DatagramProtocol):
    def __init__(self, my_shard: MyShard) -> None:
        self.my_shard = my_shard

    def datagram_received(self, data: bytes, addr) -> None:
        self.my_shard.spawn(handle_gossip_packet(self.my_shard, data))


async def handle_gossip_packet(my_shard: MyShard, buf: bytes) -> None:
    try:
        source, event, digest = msgs.deserialize_gossip_message(buf)
    except Exception as e:
        log.error("bad gossip packet: %s", e)
        return
    if digest is not None:
        # Telemetry plane (PR 11): the sender piggybacked its node
        # health digest — absorb it regardless of the event's dedup
        # fate (a re-seen event can still carry a fresher digest).
        my_shard.absorb_health_digest(digest)

    kind = event[0]
    if kind == msgs.GossipEvent.HEALTH and len(event) > 2:
        # Each interval's health digest is a FRESH epidemic: salt the
        # dedup key with the announce seq so the seen-count dedup
        # suppresses copies of ONE announce, not all future ones.
        kind = f"{kind}#{event[2]}"
    key = (source, kind)
    seen = my_shard.gossip_requests.get(key, 0)
    if seen == 0:
        # Every key expires eventually (not only ones that reach the
        # max-seen count): boot-id-salted sources would otherwise
        # accumulate one entry per boot per kind forever.
        async def expire_new():
            await asyncio.sleep(GOSSIP_REQUEST_EXPIRATION_S * 2)
            my_shard.gossip_requests.pop(key, None)

        my_shard.spawn(expire_new())
    if seen >= my_shard.config.gossip_max_seen_count:
        if seen == my_shard.config.gossip_max_seen_count:
            my_shard.gossip_requests[key] = seen + 1

            async def expire():
                await asyncio.sleep(GOSSIP_REQUEST_EXPIRATION_S)
                my_shard.gossip_requests.pop(key, None)

            my_shard.spawn(expire())
        return
    my_shard.gossip_requests[key] = seen + 1
    seen_first_time = seen == 0

    continue_with_gossip = True
    if seen_first_time:
        log.debug("gossip: %r from %s", event, source)
        await my_shard.broadcast_message_to_local_shards(
            ShardEvent.gossip(event)
        )
        continue_with_gossip = await my_shard.handle_gossip_event(event)

    if continue_with_gossip:
        await my_shard.gossip_buffer(buf)


async def run_gossip_server(my_shard: MyShard) -> None:
    loop = asyncio.get_event_loop()
    transport, _ = await loop.create_datagram_endpoint(
        lambda: _GossipProtocol(my_shard),
        local_addr=(my_shard.config.ip, my_shard.config.gossip_port),
    )
    log.info(
        "listening for gossip on %s:%d",
        my_shard.config.ip,
        my_shard.config.gossip_port,
    )
    try:
        await asyncio.Event().wait()  # runs until cancelled
    finally:
        transport.close()


# ----------------------------------------------------------------------
# Failure detector (failure_detector.rs:17-105) — managing shard only
# ----------------------------------------------------------------------


async def run_failure_detector(my_shard: MyShard) -> None:
    interval = my_shard.config.failure_detection_interval_ms / 1000
    while True:
        await asyncio.sleep(interval)
        # Membership anti-entropy: periodically re-gossip our own
        # ALIVE.  A peer that falsely removed us (CPU-starved ping
        # timeout, UDP loss) reset our ALIVE dedup counter inside
        # handle_dead_node, so the next re-announce is accepted and
        # re-adds us — without this, an asymmetric removal only heals
        # if the DEAD accusation happens to reach us (self-defense),
        # and a lost datagram makes the split permanent.  Healthy
        # peers absorb the duplicate through the gossip dedup.
        try:
            await my_shard.gossip(
                msgs.GossipEvent.alive(my_shard.get_node_metadata())
            )
        except Exception as e:
            log.error("alive re-announce failed: %s", e)
        candidates = [
            n for n in my_shard.nodes.values() if n.ids
        ]
        if not candidates:
            continue
        node = random.choice(candidates)
        await asyncio.sleep(interval)
        port = node.remote_shard_base_port + random.choice(node.ids)
        # Detection probes get TIGHT timeouts (bounded blind window):
        # with the config's serving timeouts (5 s connect / 15 s
        # read), a black-holed peer would stay undetected for 15+ s
        # while client ops stall against it.  A ping is tiny — cap
        # its round trip at ~4 detection intervals (floor 1 s), so
        # the worst-case blind window tracks the detector cadence.
        probe_ms = max(1000, int(interval * 4000))
        connection = RemoteShardConnection(
            f"{node.ip}:{port}",
            connect_timeout_ms=min(
                probe_ms,
                my_shard.config.remote_shard_connect_timeout_ms,
            ),
            read_timeout_ms=min(
                probe_ms, my_shard.config.remote_shard_read_timeout_ms
            ),
            write_timeout_ms=min(
                probe_ms,
                my_shard.config.remote_shard_write_timeout_ms,
            ),
        )
        try:
            await connection.ping()
        except DbeelError as e:
            log.info(
                "failed to ping %s (%s): %s",
                node.name,
                connection.address,
                e,
            )
            await my_shard.handle_dead_node(node.name)
            event = msgs.GossipEvent.dead(node.name)
            try:
                await my_shard.broadcast_message_to_local_shards(
                    ShardEvent.gossip(event)
                )
                await my_shard.gossip(event)
                # The accusation must reach the accused: the victim
                # was just popped from my_shard.nodes, so the fanout
                # above can never select it.  Unicast the death
                # certificate so a false positive can self-defend
                # with an ALIVE re-announce.
                await my_shard.gossip_to_node(event, node)
            except Exception as e2:
                log.error("failed to gossip node death: %s", e2)


# ----------------------------------------------------------------------
# Stop event waiter (stop_event_waiter.rs:11-27)
# ----------------------------------------------------------------------


async def wait_for_stop(my_shard: MyShard) -> None:
    await my_shard.stop_event.wait()
    raise ShardStopped(my_shard.shard_name)
