"""Structured per-shard metrics: latency histograms + slow-op log.

SURVEY.md §5 marks observability as the axis to IMPROVE on (the
reference has logs only; its latency visibility lives entirely in
blackbox_bench's client-side percentile report).  Here every served
request is recorded into a log-bucketed latency histogram per op type,
queryable over the wire via ``get_stats`` — so an operator reads
p50/p99/p999 per shard from the live system, no external bench needed.

Design: power-of-two microsecond buckets (1µs … ~67s, 27 buckets).
Recording is two integer ops (bit_length + increment) — nanoseconds of
overhead on the serving path.  Percentiles are reconstructed
server-side at query time from bucket counts (upper-bound estimate,
within 2× worst case, far tighter in the populated range).
"""

from __future__ import annotations

import logging
import time
from typing import Dict, List, Optional

log = logging.getLogger(__name__)

_BUCKETS = 27  # 2^0 .. 2^26 µs (~67 s)


class LatencyHistogram:
    __slots__ = ("counts", "count", "total_us", "max_us")

    def __init__(self) -> None:
        self.counts = [0] * _BUCKETS
        self.count = 0
        self.total_us = 0
        self.max_us = 0

    def record_us(self, us: int) -> None:
        b = min(_BUCKETS - 1, max(0, int(us).bit_length() - 1))
        # raw buckets are internal; snapshot() exports them as
        # percentiles/mean/max.  lint: allow(stats-schema)
        self.counts[b] += 1
        self.count += 1
        self.total_us += us
        if us > self.max_us:
            self.max_us = us

    def percentile_us(self, q: float) -> Optional[int]:
        """Upper-bound estimate of the q-quantile in µs."""
        if self.count == 0:
            return None
        import math

        # Nearest-rank (ceil) convention; epsilon guards float fuzz
        # like 8 * 0.999 = 7.992000000000001.
        target = max(1, math.ceil(self.count * q - 1e-9))
        seen = 0
        for b, c in enumerate(self.counts):
            seen += c
            if seen >= target:
                return 1 << (b + 1)  # bucket upper bound
        return self.max_us

    def snapshot(self) -> dict:
        return {
            "count": self.count,
            "mean_us": (
                round(self.total_us / self.count, 1) if self.count else None
            ),
            "p50_us": self.percentile_us(0.50),
            "p90_us": self.percentile_us(0.90),
            "p99_us": self.percentile_us(0.99),
            "p999_us": self.percentile_us(0.999),
            "max_us": self.max_us,
        }


# Point data ops whose completions count as foreground activity for
# the scan plane's chunk pacing (scan/scan_next deliberately absent).
_POINT_DATA_OPS = frozenset(
    {"set", "get", "delete", "multi_set", "multi_get"}
)


class ShardMetrics:
    """Per-shard metrics hub: request histograms by op type, a slow-op
    threshold log, and background-stage counters."""

    SLOW_OP_US = 100_000  # default slow threshold (--slow-op-us)
    # Slow-op WARNING lines are rate-limited per op type: under
    # overload every op can cross the threshold and one line per op
    # floods (and further slows) the serving loop.  The structured
    # record still lands in the flight recorder for every slow op —
    # the log line is a human tap, not the evidence.
    SLOW_LOG_PERIOD_S = 1.0
    # Histograms are keyed by the CLIENT-supplied request type: cap the
    # key set so garbage types can't grow shard memory / stats output.
    # (Module-level twin lives below the class: _POINT_DATA_OPS.)
    KNOWN_OPS = frozenset(
        {
            "set",
            "get",
            "delete",
            "multi_set",
            "multi_get",
            "scan",
            "scan_next",
            "create_collection",
            "drop_collection",
            "get_collection",
            "get_cluster_metadata",
            "get_stats",
            "cluster_stats",
            "telemetry_dump",
            "trace_dump",
            "invalid",
        }
    )

    def __init__(self) -> None:
        self.requests: Dict[str, LatencyHistogram] = {}
        self.slow_ops = 0
        self.slow_op_us = self.SLOW_OP_US
        # Flight recorder (tracing plane, PR 9): every slow/error op
        # is captured there; sampled ops record full spans at the
        # serving layer and pass traced=True so they are not
        # double-recorded here.  None until MyShard wires it.
        self.recorder = None
        self._slow_logged_at: Dict[str, float] = {}
        self._slow_suppressed: Dict[str, int] = {}
        # Pipelined-plane shape counters.  The two histograms reuse
        # the log-bucketed LatencyHistogram with a COUNT (not µs) as
        # the recorded value — bucket b covers [2^b, 2^{b+1}) items:
        #  * pipeline_depth: concurrent in-flight requests on a
        #    connection at each pipelined dispatch;
        #  * batch_sizes: sub-ops per multi_set/multi_get frame.
        self.pipeline_depth = LatencyHistogram()
        self.batch_sizes = LatencyHistogram()
        # Responses that were ready but had to wait for an earlier
        # (slower) response on the same connection before leaving —
        # the head-of-line pressure the in-order release rule costs.
        self.hol_waits = 0
        # Failure-taxonomy counters (errors.ERROR_CLASSES): every
        # client-visible failure this shard answered with an error
        # frame, by class — the server-side half of the soak report's
        # per-class breakdown.
        from ..errors import ERROR_CLASSES

        self.errors: Dict[str, int] = {c: 0 for c in ERROR_CLASSES}
        # Scan plane (PR 12): when this shard last completed a POINT
        # data op — the foreground-activity signal the scan plane's
        # chunk pacing keys off (the scan's own frames must NOT count
        # as foreground, or scans would throttle themselves on an
        # otherwise idle shard).
        self.last_point_op_mono = 0.0

    def record_error(self, error_class: Optional[str]) -> None:
        """Count one client-visible failure by taxonomy class (None =
        benign application outcome, not counted)."""
        if error_class is None:
            return
        if error_class not in self.errors:
            error_class = "other"
        self.errors[error_class] += 1

    def record_pipeline_depth(self, depth: int) -> None:
        self.pipeline_depth.record_us(max(1, depth))

    def record_batch_size(self, n: int) -> None:
        self.batch_sizes.record_us(max(1, n))

    def record_hol_wait(self) -> None:
        self.hol_waits += 1

    def record_request(
        self,
        op: str,
        started: float,
        error_kind: "Optional[str]" = None,
        traced: bool = False,
    ) -> None:
        """``started`` from time.monotonic() at frame receipt.
        ``error_kind`` (taxonomy class, when the caller knows it) and
        ``traced`` (a full span was already recorded) feed the flight
        recorder's slow/error capture."""
        us = int((time.monotonic() - started) * 1e6)
        if op not in self.KNOWN_OPS:
            op = "other"
        if op in _POINT_DATA_OPS:
            self.last_point_op_mono = time.monotonic()
        hist = self.requests.get(op)
        if hist is None:
            hist = self.requests[op] = LatencyHistogram()
        hist.record_us(us)
        if self.recorder is not None and not traced:
            self.recorder.note_op(op, us, error_kind)
        if us >= self.slow_op_us:
            self.slow_ops += 1
            now = time.monotonic()
            last = self._slow_logged_at.get(op, 0.0)
            if now - last >= self.SLOW_LOG_PERIOD_S:
                self._slow_logged_at[op] = now
                muted = self._slow_suppressed.pop(op, 0)
                if muted:
                    log.warning(
                        "slow %s: %.1f ms (+%d slow %s in the last "
                        "%.0fs not logged; see trace_dump)",
                        op, us / 1e3, muted, op, now - last,
                    )
                else:
                    log.warning("slow %s: %.1f ms", op, us / 1e3)
            else:
                # lint: allow(stats-schema) — log suppression state,
                # not an operator counter.
                self._slow_suppressed[op] = (
                    self._slow_suppressed.get(op, 0) + 1
                )

    def snapshot(self) -> dict:
        return {
            "requests": {
                op: hist.snapshot()
                for op, hist in self.requests.items()
            },
            "slow_ops": self.slow_ops,
            "pipeline_depth": self.pipeline_depth.snapshot(),
            "batch_sizes": self.batch_sizes.snapshot(),
            "hol_waits": self.hol_waits,
            "errors": dict(self.errors),
        }
