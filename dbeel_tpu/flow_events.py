"""Flow events — deterministic test synchronization hooks.

Mirrors the reference's feature-gated flow-events pub/sub
(/root/reference/src/flow_events.rs:5-14, shards.rs:1202-1223): tests never
sleep; they subscribe to named code-path milestones and block on them.
Disabled (near-zero cost) unless ``enable()`` is called — the analog of the
reference compiling the macro out of release builds.
"""

from __future__ import annotations

import asyncio
import enum
from collections import defaultdict
from typing import Dict, List


class FlowEvent(enum.Enum):
    # Reference milestones (flow_events.rs:7-14).
    START_TASKS = "StartTasks"
    ALIVE_NODE_GOSSIP = "AliveNodeGossip"
    DEAD_NODE_REMOVED = "DeadNodeRemoved"
    COLLECTION_CREATED = "CollectionCreated"
    COLLECTION_DROPPED = "CollectionDropped"
    DONE_MIGRATION = "DoneMigration"
    ITEM_SET_FROM_SHARD_MESSAGE = "ItemSetFromShardMessage"
    # Rebuild-specific milestones.
    MEMTABLE_FLUSH_DONE = "MemtableFlushDone"
    COMPACTION_DONE = "CompactionDone"
    WAL_SYNCED = "WalSynced"
    READ_REPAIR = "ReadRepair"
    HINT_RECORDED = "HintRecorded"
    HINTS_REPLAYED = "HintsReplayed"
    ANTI_ENTROPY_DONE = "AntiEntropyDone"
    ANTI_ENTROPY_SYNCED = "AntiEntropySynced"  # a mismatch was repaired
    # Durability plane (PR 3).
    TABLE_QUARANTINED = "TableQuarantined"
    REPAIR_DONE = "RepairDone"  # quarantine repair pull completed
    SCRUB_PASS_DONE = "ScrubPassDone"  # one full scrub cycle finished
    SHARD_DEGRADED = "ShardDegraded"  # WAL EIO/ENOSPC: now read-only
    # Replica-convergence plane (PR 4).
    SHARD_REARMED = "ShardRearmed"  # admin rearm cleared degraded mode


_enabled = False


def enable() -> None:
    global _enabled
    _enabled = True


def disable() -> None:
    global _enabled
    _enabled = False


def enabled() -> bool:
    return _enabled


class FlowEventNotifier:
    """Per-shard notifier. Sticky per subscription: each ``subscribe()``
    returns a fresh future resolved by the next ``notify`` of that event."""

    def __init__(self) -> None:
        self._waiters: Dict[FlowEvent, List[asyncio.Future]] = defaultdict(
            list
        )

    def subscribe(self, event: FlowEvent) -> "asyncio.Future[None]":
        fut: asyncio.Future = asyncio.get_event_loop().create_future()
        self._waiters[event].append(fut)
        return fut

    def notify(self, event: FlowEvent) -> None:
        if not _enabled:
            return
        waiters = self._waiters.pop(event, [])
        for fut in waiters:
            if not fut.done():
                fut.set_result(None)
