"""Distributed compaction merge over a device mesh (sample sort).

Coalesces many shards' compaction batches into ONE sharded device launch
(the BASELINE.json north star): entries are sharded over the ``shards``
mesh axis, and a classic distributed sample sort runs under ``shard_map``
with XLA collectives over ICI —

  1. local sort of each device's slice (lax.sort, 8 key operands)
  2. splitter selection: evenly-spaced local samples → ``all_gather`` →
     identical global splitters on every device
  3. bucket partition + ``all_to_all`` exchange (fixed-capacity rows,
     sentinel-padded; overflow is detected and reported so the caller can
     fall back to the single-device kernel — it never corrupts output)
  4. final local sort of the received key range + duplicate marking

Partitioning is by the first 4 key bytes (word k0); entries with equal
full keys share k0, so duplicates always land on the same device and
dedup needs no cross-device boundary pass.  Heavy first-word skew only
costs balance, never correctness (overflow triggers the fallback).
"""

from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

try:  # newer jax exports shard_map at top level...
    from jax import shard_map
except ImportError:  # ...older releases keep it in experimental
    from jax.experimental.shard_map import shard_map

from ..storage import columnar
from ..ops import bitonic

_SENTINEL = jnp.uint32(0xFFFFFFFF)
_NUM_SAMPLES = 32  # per-device splitter samples

NUM_COLS = 9  # k0..k3, key_len, ~ts_hi, ~ts_lo, ~src, idx


def _pow2(n: int) -> int:
    p = 1
    while p < n:
        p <<= 1
    return p


def _local_sort(stack: jnp.ndarray):
    """Sort rows of an (M, NUM_COLS) stack by the first 8 columns via the
    bitonic network (lax.sort's multi-key TPU comparator is pathological;
    see ops/bitonic.py).  Pads to a power of two with sentinel rows that
    sort last, then slices back.  Returns (sorted, same-key flags)."""
    m = stack.shape[0]
    p = _pow2(m)
    if p != m:
        pad = jnp.full((p - m, NUM_COLS), _SENTINEL)
        stack = jnp.concatenate([stack, pad], axis=0)
    out, same = bitonic.sort_stack_kernel(stack)
    return out[:m], same[:m]


def _per_device(stack: jnp.ndarray, capacity: int, n_dev: int):
    """shard_map body. stack: (M, NUM_COLS) local slice."""
    m = stack.shape[0]
    local, _ = _local_sort(stack)  # (M, NUM_COLS), sorted

    # -- splitters: sample k0 evenly, gather everywhere ---------------
    k0 = local[:, 0]
    sample_pos = (
        jnp.arange(_NUM_SAMPLES) * m // _NUM_SAMPLES
    )
    samples = k0[sample_pos]  # (S,)
    all_samples = jax.lax.all_gather(
        samples, "shards", tiled=True
    )  # (n_dev*S,)
    all_samples = jnp.sort(all_samples)
    step = all_samples.shape[0] // n_dev
    splitters = all_samples[step - 1 :: step][: n_dev - 1]  # (n_dev-1,)

    # -- bucket + scatter into fixed-capacity send rows ---------------
    bucket = jnp.sum(
        k0[:, None] > splitters[None, :], axis=1
    )  # (M,) in [0, n_dev)
    valid = local[:, 4] != _SENTINEL  # key_len column
    counts = jnp.sum(
        (bucket[:, None] == jnp.arange(n_dev)[None, :]) & valid[:, None],
        axis=0,
    )
    starts = jnp.concatenate(
        [jnp.zeros((1,), counts.dtype), jnp.cumsum(counts)[:-1]]
    )
    col = jnp.arange(m) - starts[bucket]  # local sorted => contiguous runs
    overflow = jnp.sum((col >= capacity) & valid).astype(jnp.uint32)
    send = jnp.full((n_dev, capacity, NUM_COLS), _SENTINEL)
    send = send.at[bucket, col].set(
        jnp.where(valid[:, None], local, _SENTINEL), mode="drop"
    )

    recv = jax.lax.all_to_all(
        send, "shards", split_axis=0, concat_axis=0, tiled=True
    )  # (n_dev*capacity, NUM_COLS) after tiling

    # -- final local sort over this device's key range ----------------
    flat = recv.reshape(n_dev * capacity, NUM_COLS)
    out, same = _local_sort(flat)
    return out, same, overflow[None]


@functools.partial(
    jax.jit, static_argnames=("mesh", "capacity", "n_dev")
)
def _dist_kernel(stack, mesh: Mesh, capacity: int, n_dev: int):
    body = functools.partial(
        _per_device, capacity=capacity, n_dev=n_dev
    )
    return shard_map(
        body,
        mesh=mesh,
        in_specs=P("shards", None),
        out_specs=(P("shards", None), P("shards"), P("shards")),
    )(stack)


def build_stack(cols: columnar.MergeColumns, n_dev: int) -> np.ndarray:
    """(N_padded, NUM_COLS) uint32 operand stack, padded so the leading
    dim divides the mesh.

    Rows are INTERLEAVED across device blocks (block d gets original
    rows d::n_dev): inputs are concatenated sorted runs, so a contiguous
    block layout would give each device a narrow slice of the keyspace
    and funnel its whole slice into a handful of all_to_all buckets
    (~m/ceil(n_dev/n_runs) rows each), overflowing the fixed exchange
    capacity of ~2m/n_dev even with zero skew.  Interleaving makes every
    local slice a stride-sample of the global key distribution — bucket
    loads concentrate around m/n_dev and the splitter samples on each
    device see the whole keyspace.  The idx column carries original row
    identity, so downstream consumers never see the permutation."""
    n = len(cols)
    m = -(-n // n_dev)  # ceil
    m = max(m, _NUM_SAMPLES)
    p = m * n_dev
    stack = np.full((p, NUM_COLS), 0xFFFFFFFF, dtype=np.uint32)
    kw = cols.key_words
    ts_inv = ~cols.timestamp
    stack[:n, 0] = kw[:, 0]
    stack[:n, 1] = kw[:, 1]
    stack[:n, 2] = kw[:, 2]
    stack[:n, 3] = kw[:, 3]
    stack[:n, 4] = cols.key_size
    stack[:n, 5] = (ts_inv >> np.uint64(32)).astype(np.uint32)
    stack[:n, 6] = (ts_inv & np.uint64(0xFFFFFFFF)).astype(np.uint32)
    stack[:n, 7] = ~cols.src
    stack[:n, 8] = np.arange(n, dtype=np.uint32)
    # Interleave: device block d = rows d::n_dev of the run-concatenated
    # order (sentinel padding rows disperse too; they sort last on every
    # device and are masked out of bucket counts).
    return np.ascontiguousarray(
        stack.reshape(m, n_dev, NUM_COLS)
        .transpose(1, 0, 2)
        .reshape(p, NUM_COLS)
    )


def distributed_sort_dedup(
    cols: columnar.MergeColumns,
    mesh: Mesh,
    capacity_factor: float = 2.0,
) -> Tuple[np.ndarray, np.ndarray]:
    """Multi-device merge: returns (perm, same) like
    ops.merge.device_sort_dedup.  Falls back to the single-device kernel
    if bucket skew overflows the exchange capacity."""
    n = len(cols)
    n_dev = mesh.devices.size
    if n == 0 or n_dev == 1:
        return _single_device_fallback(cols)
    stack = build_stack(cols, n_dev)
    m = stack.shape[0] // n_dev
    capacity = int(m * capacity_factor / n_dev) + _NUM_SAMPLES
    out, same, overflow = _dist_kernel(
        stack, mesh=mesh, capacity=capacity, n_dev=n_dev
    )
    if int(np.asarray(overflow).sum()) > 0:
        return _single_device_fallback(cols)
    out = np.asarray(out)
    same = np.asarray(same)
    # Per-device blocks are disjoint ascending key ranges: concatenate
    # valid rows in block order.
    block = out.shape[0] // n_dev
    perms, sames = [], []
    for d in range(n_dev):
        blk = out[d * block : (d + 1) * block]
        msk = same[d * block : (d + 1) * block]
        is_real = blk[:, 8] != 0xFFFFFFFF
        perms.append(blk[is_real, 8].astype(np.int64))
        sames.append(msk[is_real])
    perm = np.concatenate(perms)
    same_np = np.concatenate(sames)
    if perm.size != n:
        # Defensive: anything unexpected (shouldn't happen) → fallback.
        return _single_device_fallback(cols)
    return perm, same_np


def _single_device_fallback(cols: columnar.MergeColumns):
    """cols always stage sorted sstable runs, so the bitonic merge
    network serves as the single-device path."""
    run_counts = np.bincount(cols.src).tolist() if len(cols) else []
    return bitonic.device_merge_sorted_runs(cols, run_counts)


def DistributedMergeStrategy(mesh: Mesh):
    """CompactionStrategy running the sort across the whole mesh.
    Factory (rather than top-level subclass) so this module stays
    importable without dragging the storage stack in at import time."""
    from ..storage.compaction import ColumnarMergeStrategy

    class _DistributedMergeStrategy(ColumnarMergeStrategy):
        name = "distributed"

        # Mirrors DeviceMergeStrategy.PIPELINE_MIN_BYTES: big merges
        # take the partitioned native pipeline with the launch-batch
        # axis sharded over the mesh (O_DIRECT reads, per-device
        # keyspace partitions, native gather-write) — NOT the serial
        # load-everything host path (round-2 VERDICT weak #2).
        PIPELINE_MIN_BYTES = 64 << 20

        def __init__(self, mesh_: Mesh) -> None:
            self.mesh = mesh_

        def merge(
            self,
            sources,
            dir_path,
            output_index,
            cache,
            keep_tombstones,
            bloom_min_size,
        ):
            total = sum(getattr(s, "data_size", 0) for s in sources)
            if total >= self.PIPELINE_MIN_BYTES:
                from ..ops.pipeline import pipeline_merge

                result = pipeline_merge(
                    sources,
                    dir_path,
                    output_index,
                    keep_tombstones,
                    bloom_min_size,
                    mesh=self.mesh,
                    throttle=self.throttle,
                )
                if result is not None:
                    return result
            return super().merge(
                sources,
                dir_path,
                output_index,
                cache,
                keep_tombstones,
                bloom_min_size,
            )

        def sort_and_dedup(self, cols):
            perm, same = distributed_sort_dedup(cols, self.mesh)
            # Long keys: host fixes order + dedup (see
            # DeviceMergeStrategy).
            if (cols.key_size > columnar.KEY_PREFIX_BYTES).any():
                perm = columnar.fixup_long_key_ties(cols, perm)
                return perm, columnar.dedup_mask(cols, perm)
            return perm, ~same

    return _DistributedMergeStrategy(mesh)
