"""Device mesh helpers."""

from __future__ import annotations

from typing import Optional, Sequence

import jax
from jax.sharding import Mesh


def shard_mesh(
    n_devices: Optional[int] = None,
    devices: Optional[Sequence] = None,
) -> Mesh:
    """1-D mesh over the ``shards`` axis — the compaction-coalescing /
    key-space data-parallel axis of this framework."""
    if devices is None:
        devices = jax.devices()
        if n_devices is not None:
            devices = devices[:n_devices]
    import numpy as np

    return Mesh(np.array(devices), axis_names=("shards",))
