"""Multi-chip parallelism: device meshes and the distributed merge.

The reference scales by shard-per-core over a hash ring; the TPU-native
analog scales the *bulk compute* (compaction merge) over a device mesh
with XLA collectives riding ICI — per-shard compaction jobs coalesce into
one sharded launch (BASELINE.json north star).
"""

from .mesh import shard_mesh  # noqa: F401
