"""ctypes wrapper over the compiled C++ smart client
(native/src/dbeel_client.cpp) — the compiled analog of
/root/reference/dbeel_client (lib.rs:85-152, 336-417): metadata
bootstrap, client-side ring, replica walk with replica_index,
resync-and-retry on KeyNotOwnedByShard, persistent keepalive
connections.

This is also the serving-path latency yardstick: one Python→C call per
operation, everything else (framing, routing, socket IO) compiled.
"""

from __future__ import annotations

import ctypes
from typing import Any, Optional

import msgpack

from ..errors import DbeelError, KeyNotFound
from ..storage import native as native_mod

# The get buffer starts small and grows on demand (the C side reports
# the needed size); eager 16MB-per-client buffers measurably crowd the
# page cache when dozens of bench clients colocate with the server.
_GET_BUF_INITIAL = 256 << 10
_GET_BUF_MAX = 64 << 20

# Sentinel: "no expect_value armed" must be distinguishable from an
# expected value of None (a legitimate stored document).
_CAS_NO_EXPECT = object()


def _bind(lib) -> None:
    if getattr(lib, "_cli_bound", False):
        return
    u8p = ctypes.POINTER(ctypes.c_uint8)
    lib.dbeel_cli_new.restype = ctypes.c_void_p
    lib.dbeel_cli_new.argtypes = [ctypes.c_char_p, ctypes.c_uint16]
    lib.dbeel_cli_free.restype = None
    lib.dbeel_cli_free.argtypes = [ctypes.c_void_p]
    lib.dbeel_cli_sync.restype = ctypes.c_int
    lib.dbeel_cli_sync.argtypes = [ctypes.c_void_p]
    lib.dbeel_cli_ring_size.restype = ctypes.c_uint64
    lib.dbeel_cli_ring_size.argtypes = [ctypes.c_void_p]
    lib.dbeel_cli_last_error.restype = ctypes.c_char_p
    lib.dbeel_cli_last_error.argtypes = [ctypes.c_void_p]
    if hasattr(lib, "dbeel_cli_set_retry"):  # stale .so tolerance
        lib.dbeel_cli_set_retry.restype = None
        lib.dbeel_cli_set_retry.argtypes = [
            ctypes.c_void_p,
            ctypes.c_uint32,
            ctypes.c_uint32,
            ctypes.c_uint32,
        ]
    lib.dbeel_cli_create_collection.restype = ctypes.c_int
    lib.dbeel_cli_create_collection.argtypes = [
        ctypes.c_void_p,
        ctypes.c_char_p,
        ctypes.c_uint32,
    ]
    if hasattr(lib, "dbeel_cli_create_collection_indexed"):
        # stale .so tolerance (ISSUE 17 DDL surface)
        lib.dbeel_cli_create_collection_indexed.restype = ctypes.c_int
        lib.dbeel_cli_create_collection_indexed.argtypes = [
            ctypes.c_void_p,
            ctypes.c_char_p,
            ctypes.c_uint32,
            ctypes.c_char_p,
        ]
    lib.dbeel_cli_set.restype = ctypes.c_int
    lib.dbeel_cli_set.argtypes = [
        ctypes.c_void_p,
        ctypes.c_char_p,
        u8p,
        ctypes.c_uint32,
        u8p,
        ctypes.c_uint32,
        ctypes.c_int,
        ctypes.c_uint32,
    ]
    lib.dbeel_cli_delete.restype = ctypes.c_int
    lib.dbeel_cli_delete.argtypes = [
        ctypes.c_void_p,
        ctypes.c_char_p,
        u8p,
        ctypes.c_uint32,
        ctypes.c_int,
        ctypes.c_uint32,
    ]
    if hasattr(lib, "dbeel_cli_cas"):  # atomic plane (ISSUE 19)
        lib.dbeel_cli_cas.restype = ctypes.c_int
        lib.dbeel_cli_cas.argtypes = [
            ctypes.c_void_p,
            ctypes.c_char_p,
            u8p,
            ctypes.c_uint32,
            u8p,
            ctypes.c_uint32,
            ctypes.c_int,
            u8p,
            ctypes.c_uint32,
            ctypes.c_int,
            ctypes.c_int64,
            ctypes.c_int,
            ctypes.c_uint32,
        ]
    lib.dbeel_cli_get.restype = ctypes.c_int64
    lib.dbeel_cli_get.argtypes = [
        ctypes.c_void_p,
        ctypes.c_char_p,
        u8p,
        ctypes.c_uint32,
        ctypes.c_int,
        ctypes.c_uint32,
        u8p,
        ctypes.c_uint64,
    ]
    if hasattr(lib, "dbeel_cli_pipe_set"):  # stale .so tolerance
        lib.dbeel_cli_pipe_set.restype = ctypes.c_int
        lib.dbeel_cli_pipe_set.argtypes = [
            ctypes.c_void_p,
            ctypes.c_char_p,
            u8p,
            ctypes.c_uint32,
            u8p,
            ctypes.c_uint32,
            ctypes.c_int,
            ctypes.c_uint32,
            ctypes.c_uint32,
        ]
        lib.dbeel_cli_pipe_get.restype = ctypes.c_int
        lib.dbeel_cli_pipe_get.argtypes = [
            ctypes.c_void_p,
            ctypes.c_char_p,
            u8p,
            ctypes.c_uint32,
            ctypes.c_int,
            ctypes.c_uint32,
            ctypes.c_uint32,
        ]
        lib.dbeel_cli_pipe_drain.restype = ctypes.c_int64
        lib.dbeel_cli_pipe_drain.argtypes = [ctypes.c_void_p]
        lib.dbeel_cli_pipe_run.restype = ctypes.c_int64
        lib.dbeel_cli_pipe_run.argtypes = [
            ctypes.c_void_p,
            ctypes.c_char_p,
            ctypes.c_int,
            u8p,
            ctypes.c_uint64,
            u8p,
            ctypes.c_uint64,
            ctypes.c_uint32,
            ctypes.c_int,
            ctypes.c_uint32,
            ctypes.c_uint32,
        ]
    if hasattr(lib, "dbeel_cli_get_stats"):  # stale .so tolerance
        lib.dbeel_cli_get_stats.restype = ctypes.c_int64
        lib.dbeel_cli_get_stats.argtypes = [
            ctypes.c_void_p,
            ctypes.c_char_p,
            ctypes.c_uint16,
            u8p,
            ctypes.c_uint64,
        ]
    if hasattr(lib, "dbeel_cli_cluster_stats"):  # telemetry (PR 11)
        lib.dbeel_cli_cluster_stats.restype = ctypes.c_int64
        lib.dbeel_cli_cluster_stats.argtypes = [
            ctypes.c_void_p,
            ctypes.c_char_p,
            ctypes.c_uint16,
            u8p,
            ctypes.c_uint64,
        ]
    if hasattr(lib, "dbeel_cli_trace_dump"):  # tracing plane (PR 9)
        lib.dbeel_cli_trace_dump.restype = ctypes.c_int64
        lib.dbeel_cli_trace_dump.argtypes = [
            ctypes.c_void_p,
            ctypes.c_char_p,
            ctypes.c_uint16,
            u8p,
            ctypes.c_uint64,
        ]
        lib.dbeel_cli_set_trace.restype = None
        lib.dbeel_cli_set_trace.argtypes = [
            ctypes.c_void_p,
            ctypes.c_uint64,
        ]
    if hasattr(lib, "dbeel_cli_set_qos"):  # QoS plane (ISSUE 14)
        lib.dbeel_cli_set_qos.restype = None
        lib.dbeel_cli_set_qos.argtypes = [
            ctypes.c_void_p,
            ctypes.c_int32,
            ctypes.c_char_p,
        ]
    if hasattr(lib, "dbeel_cli_scan_chunk"):  # scan plane (PR 12)
        # +spec pass-through (query compute plane, PR 13).
        lib.dbeel_cli_scan_chunk.restype = ctypes.c_int64
        lib.dbeel_cli_scan_chunk.argtypes = [
            ctypes.c_void_p,
            ctypes.c_char_p,
            ctypes.c_uint16,
            ctypes.c_char_p,
            u8p,
            ctypes.c_uint32,
            ctypes.c_int,
            u8p,
            ctypes.c_uint32,
            ctypes.c_uint64,
            ctypes.c_uint64,
            u8p,
            ctypes.c_uint32,
            u8p,
            ctypes.c_uint64,
        ]
    if hasattr(lib, "dbeel_cli_multi_set"):
        lib.dbeel_cli_multi_set.restype = ctypes.c_int64
        lib.dbeel_cli_multi_set.argtypes = [
            ctypes.c_void_p,
            ctypes.c_char_p,
            u8p,
            ctypes.c_uint64,
            ctypes.c_uint32,
            ctypes.c_int,
            ctypes.c_uint32,
            u8p,
        ]
        lib.dbeel_cli_multi_get.restype = ctypes.c_int64
        lib.dbeel_cli_multi_get.argtypes = [
            ctypes.c_void_p,
            ctypes.c_char_p,
            u8p,
            ctypes.c_uint64,
            ctypes.c_uint32,
            ctypes.c_int,
            ctypes.c_uint32,
            u8p,
            ctypes.c_uint64,
        ]
    lib._cli_bound = True


def available() -> bool:
    lib = native_mod.load_if_built()
    return lib is not None and hasattr(lib, "dbeel_cli_new")


class NativeDbeelClient:
    """Synchronous compiled client.  Blocking — use from scripts,
    benchmarks, and worker threads (never on a server event loop)."""

    def __init__(self, seed_ip: str, seed_port: int):
        lib = native_mod._load()
        if lib is None or not hasattr(lib, "dbeel_cli_new"):
            raise RuntimeError("native client library unavailable")
        _bind(lib)
        self._lib = lib
        self._h = lib.dbeel_cli_new(
            seed_ip.encode(), ctypes.c_uint16(seed_port)
        )
        if not self._h:
            raise ConnectionError(
                f"could not bootstrap from {seed_ip}:{seed_port}"
            )
        self._buf = None  # allocated lazily by the first get

    def close(self) -> None:
        if self._h:
            self._lib.dbeel_cli_free(self._h)
            self._h = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    # ------------------------------------------------------------------

    def _err(self) -> str:
        return self._lib.dbeel_cli_last_error(self._h).decode(
            "utf-8", "replace"
        )

    @property
    def ring_size(self) -> int:
        return int(self._lib.dbeel_cli_ring_size(self._h))

    def sync_metadata(self) -> None:
        if self._lib.dbeel_cli_sync(self._h) != 0:
            raise DbeelError(self._err())

    def set_retry(
        self,
        op_deadline_ms: int = 0,
        backoff_base_ms: int = 0,
        backoff_cap_ms: int = 0,
    ) -> bool:
        """Tune the C walk's failure budget (0 keeps a knob's current
        value: 10 s deadline, 20 ms backoff base, 500 ms cap).
        Returns False on a stale .so without the retry ABI — the C
        walk then still advances past dead coordinators, just with
        its single-round pre-deadline behavior."""
        if not hasattr(self._lib, "dbeel_cli_set_retry"):
            return False
        self._lib.dbeel_cli_set_retry(
            self._h, op_deadline_ms, backoff_base_ms, backoff_cap_ms
        )
        return True

    def get_stats(
        self, ip: str = "", port: int = 0
    ) -> dict:
        """One server's get_stats snapshot (the bootstrap seed by
        default), unpacked — same schema as the Python client's
        get_stats(), incl. the replica-convergence block.  Raises on
        a stale .so without the ABI."""
        if not hasattr(self._lib, "dbeel_cli_get_stats"):
            raise DbeelError(
                "native library predates dbeel_cli_get_stats"
            )
        cap = 1 << 20
        for _ in range(2):
            buf = (ctypes.c_uint8 * cap)()
            n = self._lib.dbeel_cli_get_stats(
                self._h, ip.encode(), port, buf, cap
            )
            if n <= -10:
                cap = -int(n) - 10
                continue
            break
        if n < 0:
            raise DbeelError(self._err())
        return msgpack.unpackb(bytes(buf[: int(n)]), raw=False)

    def cluster_stats(self, ip: str = "", port: int = 0) -> dict:
        """One node's gossip-aggregated cluster health view (the
        bootstrap seed by default), unpacked — same schema as the
        Python client's cluster_stats().  Raises on a stale .so
        without the ABI."""
        if not hasattr(self._lib, "dbeel_cli_cluster_stats"):
            raise DbeelError(
                "native library predates dbeel_cli_cluster_stats"
            )
        cap = 1 << 20
        for _ in range(2):
            buf = (ctypes.c_uint8 * cap)()
            n = self._lib.dbeel_cli_cluster_stats(
                self._h, ip.encode(), port, buf, cap
            )
            if n <= -10:
                cap = -int(n) - 10
                continue
            break
        if n < 0:
            raise DbeelError(self._err())
        return msgpack.unpackb(bytes(buf[: int(n)]), raw=False)

    def set_trace(self, base_trace_id: int) -> bool:
        """Arm per-op trace stamping in the C walk: every single-op
        request carries an auto-incrementing ``trace`` id starting at
        ``base_trace_id`` (0 disarms) — the server serves those
        interpreted and records full per-stage spans.  Returns False
        on a stale .so without the tracing ABI."""
        if not hasattr(self._lib, "dbeel_cli_set_trace"):
            return False
        self._lib.dbeel_cli_set_trace(self._h, base_trace_id)
        return True

    def set_qos(
        self, qos_class: "str | int | None" = None,
        tenant: "str | None" = None,
    ) -> bool:
        """Arm QoS stamping (QoS plane, ISSUE 14): every data-op
        frame carries the traffic class ("interactive" > "standard" >
        "batch", or the wire int) and/or the tenant id the server's
        per-collection token buckets key by.  ``None, None`` disarms.
        Returns False on a stale .so without the QoS ABI."""
        if not hasattr(self._lib, "dbeel_cli_set_qos"):
            return False
        from ..cluster.messages import qos_class_of

        cls = -1 if qos_class is None else qos_class_of(qos_class)
        self._lib.dbeel_cli_set_qos(
            self._h, cls, (tenant or "").encode()
        )
        return True

    def trace_dump(self, ip: str = "", port: int = 0) -> dict:
        """One server's flight-recorder dump (the bootstrap seed by
        default), unpacked — same schema as the Python client's
        trace_dump().  Raises on a stale .so without the ABI."""
        if not hasattr(self._lib, "dbeel_cli_trace_dump"):
            raise DbeelError(
                "native library predates dbeel_cli_trace_dump"
            )
        cap = 1 << 20
        for _ in range(2):
            buf = (ctypes.c_uint8 * cap)()
            n = self._lib.dbeel_cli_trace_dump(
                self._h, ip.encode(), port, buf, cap
            )
            if n <= -10:
                cap = -int(n) - 10
                continue
            break
        if n < 0:
            raise DbeelError(self._err())
        return msgpack.unpackb(bytes(buf[: int(n)]), raw=False)

    def _scan_chunk(
        self,
        collection: str,
        cursor: Optional[bytes],
        count_only: bool,
        prefix: Optional[bytes],
        limit: int,
        max_bytes: int,
        spec: Optional[bytes] = None,
        ip: str = "",
        port: int = 0,
    ) -> dict:
        """One raw scan chunk through the C client (retryable server
        sheds back off and resume — the cursor is client-held).
        ``spec`` is the packed filter/aggregate blob
        (dbeel_tpu.query.pack_spec), forwarded verbatim."""
        if not hasattr(self._lib, "dbeel_cli_scan_chunk"):
            raise DbeelError(
                "native library predates dbeel_cli_scan_chunk"
            )
        cur = (
            (ctypes.c_uint8 * len(cursor)).from_buffer_copy(cursor)
            if cursor
            else None
        )
        pfx = (
            (ctypes.c_uint8 * len(prefix)).from_buffer_copy(prefix)
            if prefix
            else None
        )
        spc = (
            (ctypes.c_uint8 * len(spec)).from_buffer_copy(spec)
            if spec
            else None
        )
        cap = 1 << 20
        backoff = 0.02
        for attempt in range(64):
            buf = (ctypes.c_uint8 * cap)()
            n = self._lib.dbeel_cli_scan_chunk(
                self._h,
                ip.encode(),
                port,
                collection.encode(),
                cur,
                len(cursor) if cursor else 0,
                1 if count_only else 0,
                pfx,
                len(prefix) if prefix else 0,
                limit,
                max_bytes,
                spc,
                len(spec) if spec else 0,
                buf,
                cap,
            )
            if n <= -10:
                cap = -int(n) - 10
                continue
            if n == -3 and attempt < 63:
                # Retryable (Overloaded shed / transport): back off
                # with the walk's jittered cap, then resume.
                import random as _random
                import time as _time

                _time.sleep(backoff * (0.5 + 0.5 * _random.random()))
                backoff = min(0.5, backoff * 2)
                continue
            break
        if n < 0:
            raise DbeelError(self._err())
        return msgpack.unpackb(bytes(buf[: int(n)]), raw=False)

    def scan(
        self,
        collection: str,
        prefix: Optional[bytes] = None,
        limit: int = 0,
        max_bytes: int = 0,
        filter: Optional[Any] = None,
    ) -> list:
        """Full/range streaming scan through the C client: decoded
        (key, value) pairs in encoded-key byte order, chunked and
        cursor-resumed under the hood (same stream semantics as the
        Python client's ``DbeelCollection.scan``).  ``filter`` is a
        predicate tree (dbeel_tpu.query) pushed down to the
        replicas' staged columns — spec pass-through: this client
        packs it once and forwards bytes."""
        spec = None
        if filter is not None:
            from .. import query as _query

            w, _ = _query.build_spec(filter, None)
            spec = _query.pack_spec(w, None)
        out: list = []
        cursor: Optional[bytes] = None
        while True:
            chunk = self._scan_chunk(
                collection, cursor, False, prefix, limit,
                max_bytes, spec,
            )
            # Entries decode with the chunk itself (spliced stored
            # encodings — one unpack per chunk).
            for key, value in chunk.get("entries") or ():
                out.append((key, value))
            cursor = chunk.get("cursor")
            if not cursor:
                return out

    def count(
        self,
        collection: str,
        prefix: Optional[bytes] = None,
        limit: int = 0,
        filter: Optional[Any] = None,
        aggregate: Optional[dict] = None,
    ) -> Any:
        """Live-document count via the keys-only pushdown — no value
        bytes cross any wire.  ``filter`` counts matches only;
        ``aggregate`` returns the pushed-down aggregate result
        instead (the final chunk's "agg" field), mirroring the
        Python client's ``DbeelCollection.count``."""
        spec = None
        count_only = True
        if aggregate is not None:
            from .. import query as _query

            w, a = _query.build_spec(filter, aggregate)
            spec = _query.pack_spec(w, a)
            count_only = False
        elif filter is not None:
            from .. import query as _query

            w, _ = _query.build_spec(filter, None)
            spec = _query.pack_spec(w, None)
        cursor: Optional[bytes] = None
        total = 0
        while True:
            chunk = self._scan_chunk(
                collection, cursor, count_only, prefix, limit, 0,
                spec,
            )
            total = int(chunk.get("count") or 0)
            cursor = chunk.get("cursor")
            if not cursor:
                if aggregate is not None:
                    return chunk.get("agg")
                return total

    def create_collection(
        self,
        name: str,
        replication_factor: int = 1,
        index: Optional[list] = None,
    ) -> None:
        if index:
            if not hasattr(self._lib, "dbeel_cli_create_collection_indexed"):
                raise DbeelError(
                    "native client .so predates indexed DDL — rebuild"
                )
            csv = ",".join(str(f) for f in index)
            rc = self._lib.dbeel_cli_create_collection_indexed(
                self._h, name.encode(), replication_factor, csv.encode()
            )
        else:
            rc = self._lib.dbeel_cli_create_collection(
                self._h, name.encode(), replication_factor
            )
        if rc != 0:
            raise DbeelError(self._err())

    @staticmethod
    def _enc(obj: Any) -> bytes:
        return msgpack.packb(obj, use_bin_type=True)

    def set(
        self,
        collection: str,
        key: Any,
        value: Any,
        consistency: int = 0,
        rf: int = 1,
    ) -> None:
        k = self._enc(key)
        v = self._enc(value)
        rc = self._lib.dbeel_cli_set(
            self._h,
            collection.encode(),
            (ctypes.c_uint8 * len(k)).from_buffer_copy(k),
            len(k),
            (ctypes.c_uint8 * len(v)).from_buffer_copy(v),
            len(v),
            consistency,
            rf,
        )
        if rc != 0:
            raise DbeelError(self._err())

    def get(
        self,
        collection: str,
        key: Any,
        consistency: int = 0,
        rf: int = 1,
    ) -> Optional[Any]:
        k = self._enc(key)
        kb = (ctypes.c_uint8 * len(k)).from_buffer_copy(k)
        if self._buf is None:
            self._buf = (ctypes.c_uint8 * _GET_BUF_INITIAL)()
        for _ in range(2):
            n = self._lib.dbeel_cli_get(
                self._h,
                collection.encode(),
                kb,
                len(k),
                consistency,
                rf,
                self._buf,
                len(self._buf),
            )
            if n <= -10:
                # Buffer too small: the C side reports the needed
                # size; grow and retry once.
                needed = -int(n) - 10
                if needed > _GET_BUF_MAX:
                    raise DbeelError(self._err())
                self._buf = (ctypes.c_uint8 * needed)()
                continue
            break
        if n == -1:
            raise KeyNotFound(repr(key))
        if n < 0:
            raise DbeelError(self._err())
        return msgpack.unpackb(bytes(self._buf[: int(n)]), raw=False)

    # -- pipelined mode (windowed in-flight train per connection) ------

    def pipe_set(
        self,
        collection: str,
        key: Any,
        value: Any,
        consistency: int = 0,
        rf: int = 1,
        window: int = 16,
    ) -> None:
        """Enqueue one set on the pipelined train (replica-0 routed);
        at most ``window`` responses ride unread per connection.
        Application errors surface at pipe_drain()."""
        k, v = self._enc(key), self._enc(value)
        rc = self._lib.dbeel_cli_pipe_set(
            self._h,
            collection.encode(),
            (ctypes.c_uint8 * len(k)).from_buffer_copy(k),
            len(k),
            (ctypes.c_uint8 * len(v)).from_buffer_copy(v),
            len(v),
            consistency,
            rf,
            window,
        )
        if rc != 0:
            raise DbeelError(self._err())

    def pipe_get(
        self,
        collection: str,
        key: Any,
        consistency: int = 0,
        rf: int = 1,
        window: int = 16,
    ) -> None:
        """Enqueue one get on the pipelined train (value discarded —
        throughput-path API; correctness checks use get())."""
        k = self._enc(key)
        rc = self._lib.dbeel_cli_pipe_get(
            self._h,
            collection.encode(),
            (ctypes.c_uint8 * len(k)).from_buffer_copy(k),
            len(k),
            consistency,
            rf,
            window,
        )
        if rc != 0:
            raise DbeelError(self._err())

    def pipe_run(
        self,
        collection: str,
        op: str,
        keys,
        values=None,
        consistency: int = 0,
        rf: int = 1,
        window: int = 16,
    ) -> int:
        """Pipeline a whole train of ops in ONE C call (the ctypes
        boundary releases the GIL for the entire train, so worker
        threads overlap fully) and drain it; returns the application
        failure count.  ``op`` is "set" (values required) or "get"."""
        keys = list(keys)
        if not keys:
            return 0
        is_set = op == "set"
        kbuf = bytearray()
        for key in keys:
            k = self._enc(key)
            kbuf += len(k).to_bytes(4, "little") + k
        vbuf = bytearray()
        if is_set:
            for value in values:
                v = self._enc(value)
                vbuf += len(v).to_bytes(4, "little") + v
        rc = int(
            self._lib.dbeel_cli_pipe_run(
                self._h,
                collection.encode(),
                1 if is_set else 0,
                (ctypes.c_uint8 * len(kbuf)).from_buffer(kbuf),
                len(kbuf),
                (ctypes.c_uint8 * len(vbuf)).from_buffer(vbuf)
                if vbuf
                else None,
                len(vbuf),
                len(keys),
                consistency,
                rf,
                window,
            )
        )
        if rc < 0:
            raise DbeelError(self._err())
        return rc

    def pipe_drain(self) -> int:
        """Read every outstanding pipelined response; returns how
        many were application errors (0 on a healthy run)."""
        rc = int(self._lib.dbeel_cli_pipe_drain(self._h))
        if rc < 0:
            raise DbeelError(self._err())
        return rc

    # -- batched multi-ops ---------------------------------------------

    def multi_set(
        self,
        collection: str,
        items,
        consistency: int = 0,
        rf: int = 1,
    ) -> None:
        """Batched set: one multi_set frame per owning node (C-side
        grouping/chunking); sub-ops the batch path could not land
        retry through the single-op walk (full failover)."""
        pairs = (
            list(items.items())
            if isinstance(items, dict)
            else list(items)
        )
        if not pairs:
            return
        buf = bytearray()
        for key, value in pairs:
            k, v = self._enc(key), self._enc(value)
            buf += len(k).to_bytes(4, "little") + k
            buf += len(v).to_bytes(4, "little") + v
        status = (ctypes.c_uint8 * len(pairs))()
        rc = self._lib.dbeel_cli_multi_set(
            self._h,
            collection.encode(),
            (ctypes.c_uint8 * len(buf)).from_buffer(buf),
            len(buf),
            len(pairs),
            consistency,
            rf,
            status,
        )
        if rc < 0:
            raise DbeelError(self._err())
        for i in range(len(pairs)):
            if status[i]:
                self.set(
                    collection, pairs[i][0], pairs[i][1],
                    consistency, rf,
                )

    def multi_get(
        self,
        collection: str,
        keys,
        consistency: int = 0,
        rf: int = 1,
    ) -> list:
        """Batched get: returns values aligned with ``keys`` (None
        for missing); retryable sub-ops fall back to the single-op
        walk."""
        keys = list(keys)
        if not keys:
            return []
        buf = bytearray()
        for key in keys:
            k = self._enc(key)
            buf += len(k).to_bytes(4, "little") + k
        kb = (ctypes.c_uint8 * len(buf)).from_buffer(buf)
        if self._buf is None:
            self._buf = (ctypes.c_uint8 * _GET_BUF_INITIAL)()
        for _ in range(2):
            n = self._lib.dbeel_cli_multi_get(
                self._h,
                collection.encode(),
                kb,
                len(buf),
                len(keys),
                consistency,
                rf,
                self._buf,
                len(self._buf),
            )
            if n <= -10:
                needed = -int(n) - 10
                if needed > _GET_BUF_MAX:
                    raise DbeelError(self._err())
                self._buf = (ctypes.c_uint8 * needed)()
                continue
            break
        if n < 0:
            raise DbeelError(self._err())
        raw = bytes(self._buf[: int(n)])
        out: list = []
        off = 0
        for i in range(len(keys)):
            st = raw[off]
            vn = int.from_bytes(raw[off + 1 : off + 5], "little")
            payload = raw[off + 5 : off + 5 + vn]
            off += 5 + vn
            if st == 0:
                out.append(msgpack.unpackb(payload, raw=False))
            elif st == 1:
                out.append(None)
            else:
                try:
                    out.append(
                        self.get(collection, keys[i], consistency, rf)
                    )
                except KeyNotFound:
                    out.append(None)
        return out

    def cas(
        self,
        collection: str,
        key: Any,
        value: Any = None,
        delete: bool = False,
        expect_value: Any = _CAS_NO_EXPECT,
        expect_ts: Optional[int] = None,
        expect_absent: bool = False,
        consistency: int = 0,
        rf: int = 1,
    ) -> bool:
        """Conditional write through the C walk (atomic plane, ISSUE
        19): commit ``value`` (or a delete) only if the key's current
        state matches the armed expectation.  Returns True on commit,
        False on a CAS conflict (re-read, then retry with fresh
        expectations); raises on infrastructure errors.  Raises on a
        stale .so without the CAS ABI."""
        if not hasattr(self._lib, "dbeel_cli_cas"):
            raise DbeelError("native library predates dbeel_cli_cas")
        k = self._enc(key)
        v = None if delete else self._enc(value)
        ev = (
            None
            if expect_value is _CAS_NO_EXPECT
            else self._enc(expect_value)
        )
        rc = self._lib.dbeel_cli_cas(
            self._h,
            collection.encode(),
            (ctypes.c_uint8 * len(k)).from_buffer_copy(k),
            len(k),
            (ctypes.c_uint8 * len(v)).from_buffer_copy(v)
            if v is not None
            else None,
            len(v) if v is not None else 0,
            1 if delete else 0,
            (ctypes.c_uint8 * len(ev)).from_buffer_copy(ev)
            if ev is not None
            else None,
            len(ev) if ev is not None else 0,
            1 if expect_absent else 0,
            -1 if expect_ts is None else int(expect_ts),
            consistency,
            rf,
        )
        if rc == 0:
            return True
        if rc == -3:
            return False
        raise DbeelError(self._err())

    def delete(
        self,
        collection: str,
        key: Any,
        consistency: int = 0,
        rf: int = 1,
    ) -> None:
        k = self._enc(key)
        rc = self._lib.dbeel_cli_delete(
            self._h,
            collection.encode(),
            (ctypes.c_uint8 * len(k)).from_buffer_copy(k),
            len(k),
            consistency,
            rf,
        )
        if rc != 0:
            raise DbeelError(self._err())
