"""ctypes wrapper over the compiled C++ smart client
(native/src/dbeel_client.cpp) — the compiled analog of
/root/reference/dbeel_client (lib.rs:85-152, 336-417): metadata
bootstrap, client-side ring, replica walk with replica_index,
resync-and-retry on KeyNotOwnedByShard, persistent keepalive
connections.

This is also the serving-path latency yardstick: one Python→C call per
operation, everything else (framing, routing, socket IO) compiled.
"""

from __future__ import annotations

import ctypes
from typing import Any, Optional

import msgpack

from ..errors import DbeelError, KeyNotFound
from ..storage import native as native_mod

# The get buffer starts small and grows on demand (the C side reports
# the needed size); eager 16MB-per-client buffers measurably crowd the
# page cache when dozens of bench clients colocate with the server.
_GET_BUF_INITIAL = 256 << 10
_GET_BUF_MAX = 64 << 20


def _bind(lib) -> None:
    if getattr(lib, "_cli_bound", False):
        return
    u8p = ctypes.POINTER(ctypes.c_uint8)
    lib.dbeel_cli_new.restype = ctypes.c_void_p
    lib.dbeel_cli_new.argtypes = [ctypes.c_char_p, ctypes.c_uint16]
    lib.dbeel_cli_free.restype = None
    lib.dbeel_cli_free.argtypes = [ctypes.c_void_p]
    lib.dbeel_cli_sync.restype = ctypes.c_int
    lib.dbeel_cli_sync.argtypes = [ctypes.c_void_p]
    lib.dbeel_cli_ring_size.restype = ctypes.c_uint64
    lib.dbeel_cli_ring_size.argtypes = [ctypes.c_void_p]
    lib.dbeel_cli_last_error.restype = ctypes.c_char_p
    lib.dbeel_cli_last_error.argtypes = [ctypes.c_void_p]
    if hasattr(lib, "dbeel_cli_set_retry"):  # stale .so tolerance
        lib.dbeel_cli_set_retry.restype = None
        lib.dbeel_cli_set_retry.argtypes = [
            ctypes.c_void_p,
            ctypes.c_uint32,
            ctypes.c_uint32,
            ctypes.c_uint32,
        ]
    lib.dbeel_cli_create_collection.restype = ctypes.c_int
    lib.dbeel_cli_create_collection.argtypes = [
        ctypes.c_void_p,
        ctypes.c_char_p,
        ctypes.c_uint32,
    ]
    lib.dbeel_cli_set.restype = ctypes.c_int
    lib.dbeel_cli_set.argtypes = [
        ctypes.c_void_p,
        ctypes.c_char_p,
        u8p,
        ctypes.c_uint32,
        u8p,
        ctypes.c_uint32,
        ctypes.c_int,
        ctypes.c_uint32,
    ]
    lib.dbeel_cli_delete.restype = ctypes.c_int
    lib.dbeel_cli_delete.argtypes = [
        ctypes.c_void_p,
        ctypes.c_char_p,
        u8p,
        ctypes.c_uint32,
        ctypes.c_int,
        ctypes.c_uint32,
    ]
    lib.dbeel_cli_get.restype = ctypes.c_int64
    lib.dbeel_cli_get.argtypes = [
        ctypes.c_void_p,
        ctypes.c_char_p,
        u8p,
        ctypes.c_uint32,
        ctypes.c_int,
        ctypes.c_uint32,
        u8p,
        ctypes.c_uint64,
    ]
    lib._cli_bound = True


def available() -> bool:
    lib = native_mod.load_if_built()
    return lib is not None and hasattr(lib, "dbeel_cli_new")


class NativeDbeelClient:
    """Synchronous compiled client.  Blocking — use from scripts,
    benchmarks, and worker threads (never on a server event loop)."""

    def __init__(self, seed_ip: str, seed_port: int):
        lib = native_mod._load()
        if lib is None or not hasattr(lib, "dbeel_cli_new"):
            raise RuntimeError("native client library unavailable")
        _bind(lib)
        self._lib = lib
        self._h = lib.dbeel_cli_new(
            seed_ip.encode(), ctypes.c_uint16(seed_port)
        )
        if not self._h:
            raise ConnectionError(
                f"could not bootstrap from {seed_ip}:{seed_port}"
            )
        self._buf = None  # allocated lazily by the first get

    def close(self) -> None:
        if self._h:
            self._lib.dbeel_cli_free(self._h)
            self._h = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    # ------------------------------------------------------------------

    def _err(self) -> str:
        return self._lib.dbeel_cli_last_error(self._h).decode(
            "utf-8", "replace"
        )

    @property
    def ring_size(self) -> int:
        return int(self._lib.dbeel_cli_ring_size(self._h))

    def sync_metadata(self) -> None:
        if self._lib.dbeel_cli_sync(self._h) != 0:
            raise DbeelError(self._err())

    def set_retry(
        self,
        op_deadline_ms: int = 0,
        backoff_base_ms: int = 0,
        backoff_cap_ms: int = 0,
    ) -> bool:
        """Tune the C walk's failure budget (0 keeps a knob's current
        value: 10 s deadline, 20 ms backoff base, 500 ms cap).
        Returns False on a stale .so without the retry ABI — the C
        walk then still advances past dead coordinators, just with
        its single-round pre-deadline behavior."""
        if not hasattr(self._lib, "dbeel_cli_set_retry"):
            return False
        self._lib.dbeel_cli_set_retry(
            self._h, op_deadline_ms, backoff_base_ms, backoff_cap_ms
        )
        return True

    def create_collection(
        self, name: str, replication_factor: int = 1
    ) -> None:
        rc = self._lib.dbeel_cli_create_collection(
            self._h, name.encode(), replication_factor
        )
        if rc != 0:
            raise DbeelError(self._err())

    @staticmethod
    def _enc(obj: Any) -> bytes:
        return msgpack.packb(obj, use_bin_type=True)

    def set(
        self,
        collection: str,
        key: Any,
        value: Any,
        consistency: int = 0,
        rf: int = 1,
    ) -> None:
        k = self._enc(key)
        v = self._enc(value)
        rc = self._lib.dbeel_cli_set(
            self._h,
            collection.encode(),
            (ctypes.c_uint8 * len(k)).from_buffer_copy(k),
            len(k),
            (ctypes.c_uint8 * len(v)).from_buffer_copy(v),
            len(v),
            consistency,
            rf,
        )
        if rc != 0:
            raise DbeelError(self._err())

    def get(
        self,
        collection: str,
        key: Any,
        consistency: int = 0,
        rf: int = 1,
    ) -> Optional[Any]:
        k = self._enc(key)
        kb = (ctypes.c_uint8 * len(k)).from_buffer_copy(k)
        if self._buf is None:
            self._buf = (ctypes.c_uint8 * _GET_BUF_INITIAL)()
        for _ in range(2):
            n = self._lib.dbeel_cli_get(
                self._h,
                collection.encode(),
                kb,
                len(k),
                consistency,
                rf,
                self._buf,
                len(self._buf),
            )
            if n <= -10:
                # Buffer too small: the C side reports the needed
                # size; grow and retry once.
                needed = -int(n) - 10
                if needed > _GET_BUF_MAX:
                    raise DbeelError(self._err())
                self._buf = (ctypes.c_uint8 * needed)()
                continue
            break
        if n == -1:
            raise KeyNotFound(repr(key))
        if n < 0:
            raise DbeelError(self._err())
        return msgpack.unpackb(bytes(self._buf[: int(n)]), raw=False)

    def delete(
        self,
        collection: str,
        key: Any,
        consistency: int = 0,
        rf: int = 1,
    ) -> None:
        k = self._enc(key)
        rc = self._lib.dbeel_cli_delete(
            self._h,
            collection.encode(),
            (ctypes.c_uint8 * len(k)).from_buffer_copy(k),
            len(k),
            consistency,
            rf,
        )
        if rc != 0:
            raise DbeelError(self._err())
