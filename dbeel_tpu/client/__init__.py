"""Smart client for dbeel_tpu (and wire-compatible with dbeel servers).

Role parity with /root/reference/dbeel_client/src/lib.rs: bootstrap from
seed db addresses, pull cluster metadata, build the client-side hash
ring, route each key to the first ring shard at/after its hash, walk
replicas across distinct nodes injecting ``replica_index``, resync the
ring and retry on ``KeyNotOwnedByShard``, and offer per-op consistency
(fixed / quorum / all).
"""

from __future__ import annotations

import asyncio
import random
import struct
import time
from bisect import bisect_left
from collections import deque
from dataclasses import dataclass
from typing import Any, Dict, List, Optional, Sequence, Tuple

import msgpack

from ..errors import (
    ERROR_CLASS_OVERLOAD,
    ERROR_CLASS_PEER_DEAD,
    ERROR_CLASS_QUOTA,
    BadFieldType,
    CasConflict,
    ConnectionError_,
    DbeelError,
    KeyNotFound,
    KeyNotOwnedByShard,
    ProtocolError,
    Timeout,
    classify_error,
    from_wire,
    is_retryable_class,
)
from ..cluster.messages import ClusterMetadata
from ..cluster.messages import qos_class_of as _qos_class_of
from ..utils.murmur import hash_bytes, hash_string

RESPONSE_ERR = 0
RESPONSE_OK = 1
RESPONSE_BYTES = 2

# Ops that carry the membership-epoch write fence (ISSUE 18/19): every
# mutation routed by the client's ring view.  Conditional writes MUST
# stamp it — a CAS decided against a mid-migration stale view is
# exactly the lost-update the atomic plane exists to prevent.
# Lint-pinned (analysis/wire_parity.py) so the set cannot silently
# shrink.
_EPOCH_STAMPED_OPS = ("set", "delete", "cas", "atomic_batch")


class Consistency:
    """dbeel_client/src/lib.rs:465-480."""

    @staticmethod
    def fixed(n: int):
        return ("fixed", n)

    QUORUM = ("quorum", 0)
    ALL = ("all", 0)

    @staticmethod
    def resolve(c, replication_factor: int) -> int:
        kind, n = c
        if kind == "fixed":
            return n
        if kind == "quorum":
            return replication_factor // 2 + 1
        return replication_factor


@dataclass
class _RingShard:
    node_name: str
    hash: int
    ip: str
    db_port: int  # already shard-specific (base + id)


class _PipelinedConnection:
    """One keepalive connection multiplexing many in-flight requests.

    The server answers pipelined frames strictly in arrival order
    (db_server._DbProtocol), so response dispatch is a FIFO: the j-th
    response frame resolves the j-th outstanding future.  A semaphore
    caps the in-flight window; writes go out as one buffer append per
    frame (atomic on the loop), and a single reader task fans
    responses back out.  Any transport error fails EVERY outstanding
    future with ConnectionError_ — callers treat that as the usual
    replica-walk transport failure and retry elsewhere."""

    def __init__(self, host: str, port: int, window: int) -> None:
        self.host = host
        self.port = port
        self._window = max(1, window)
        self._sem = asyncio.Semaphore(self._window)
        self._fifo: deque = deque()  # futures awaiting responses
        self._reader_task = None
        self._reader = None
        self._writer = None
        self._connecting: Optional[asyncio.Future] = None
        self._broken: Optional[Exception] = None

    @property
    def usable(self) -> bool:
        return self._broken is None

    async def _ensure_connected(self) -> None:
        # Single-flight dial: concurrent first requests must share
        # ONE connection — a second open_connection would overwrite
        # the streams under the first reader task and split response
        # frames between two readexactly loops.
        while self._connecting is not None:
            await asyncio.shield(self._connecting)
        if self._writer is not None:
            return
        self._connecting = asyncio.get_event_loop().create_future()
        try:
            self._reader, self._writer = (
                await asyncio.open_connection(self.host, self.port)
            )
            self._reader_task = asyncio.ensure_future(
                self._read_loop()
            )
        finally:
            fut, self._connecting = self._connecting, None
            fut.set_result(None)

    async def request(self, request_buf: bytes) -> bytes:
        """One framed round trip through the pipeline; returns the
        raw response payload (length prefix stripped)."""
        # Frame BEFORE queueing the future: an oversized request's
        # struct.error must not leave an orphan FIFO slot that would
        # misalign every later response.
        framed = struct.pack("<H", len(request_buf)) + request_buf
        async with self._sem:
            if self._broken is not None:
                raise ConnectionError_(
                    f"pipelined connection to "
                    f"{self.host}:{self.port} broken: {self._broken!r}"
                )
            await self._ensure_connected()
            fut = asyncio.get_event_loop().create_future()
            self._fifo.append(fut)
            self._writer.write(framed)
            # Transport-buffer backpressure (the window bounds how
            # many writes can be outstanding before this drain).
            await self._writer.drain()
            return await fut

    async def _read_loop(self) -> None:
        try:
            while True:
                header = await self._reader.readexactly(4)
                (size,) = struct.unpack("<I", header)
                payload = await self._reader.readexactly(size)
                if not self._fifo:
                    raise ProtocolError(
                        "unsolicited pipelined response"
                    )
                fut = self._fifo.popleft()
                if not fut.done():
                    fut.set_result(payload)
        except BaseException as e:  # noqa: BLE001 — fail everything
            self._fail(e)

    def _fail(self, exc: BaseException) -> None:
        self._broken = exc if isinstance(
            exc, Exception
        ) else ConnectionError_(repr(exc))
        while self._fifo:
            fut = self._fifo.popleft()
            if not fut.done():
                fut.set_exception(
                    ConnectionError_(
                        f"pipelined connection to "
                        f"{self.host}:{self.port} lost: {exc!r}"
                    )
                )
        self.close()

    def close(self) -> None:
        if self._reader_task is not None:
            self._reader_task.cancel()
            self._reader_task = None
        if self._writer is not None:
            self._writer.close()
            self._writer = None
        if self._broken is None:
            self._broken = ConnectionError_("closed")


class DbeelClient:
    """``pooled=True`` (default) reuses connections via the keepalive
    protocol extension; pass False for strict reference behavior
    (connect per request).

    Failure-aware routing: every keyed op carries a per-op deadline
    budget (``op_deadline_s``).  Connection-class failures walk to the
    next ring replica; an exhausted walk resyncs the ring (churn moves
    ownership) and retries after capped exponential backoff with
    jitter, until the budget runs out.  Benign application outcomes
    (KeyNotFound et al.) are final immediately.

    ``pipeline_window=N`` (N >= 1) switches transport to PIPELINED
    connections: one keepalive connection per target multiplexes up
    to N concurrent requests (the server executes them concurrently
    and answers in arrival order), so M coroutines hitting one shard
    share one socket and overlap their round trips instead of
    serializing on a per-request pool checkout."""

    MAX_POOL_PER_TARGET = 8
    OP_DEADLINE_S = 10.0
    BACKOFF_BASE_S = 0.02
    BACKOFF_CAP_S = 0.5

    def __init__(
        self,
        seed_addresses: Sequence[Tuple[str, int]],
        pooled: bool = True,
        op_deadline_s: Optional[float] = None,
        pipeline_window: Optional[int] = None,
        qos_class: "str | int | None" = None,
        tenant: Optional[str] = None,
    ):
        # QoS plane (ISSUE 14): when set, every data-op frame this
        # client sends is stamped with the traffic class
        # ("interactive" > "standard" > "batch" — under server
        # overload batch sheds first and interactive last) and/or the
        # tenant id the server's per-collection token buckets key by.
        # A QuotaExceeded answer is retryable like an Overloaded shed
        # (the walk backs off; tokens refill).
        self._qos_class: Optional[int] = (
            None if qos_class is None else _qos_class_of(qos_class)
        )
        self._tenant = tenant if tenant else None
        self._seeds = list(seed_addresses)
        self._ring: List[_RingShard] = []
        self._ring_hashes: List[int] = []
        self._collections: dict = {}
        self._cluster_epoch = 0
        self._pooled = pooled
        self._pool: dict = {}  # (host, port) -> [(reader, writer)]
        self._pipeline_window = pipeline_window
        self._pipes: Dict[tuple, _PipelinedConnection] = {}
        self._op_deadline_s = (
            self.OP_DEADLINE_S if op_deadline_s is None else op_deadline_s
        )
        self._rng = random.Random()

    # -- bootstrap / metadata sync (lib.rs:85-152) ---------------------

    @classmethod
    async def from_seed_nodes(
        cls, addresses: Sequence[Tuple[str, int]], **kwargs
    ) -> "DbeelClient":
        client = cls(addresses, **kwargs)
        await client.sync_metadata()
        return client

    async def sync_metadata(self) -> None:
        # Failover: metadata can come from ANY live ring member, not
        # just the configured seeds — a client whose only seed is the
        # dead node would otherwise keep a stale ring forever and
        # bounce on KeyNotOwnedByShard through the whole churn window.
        candidates: List[Tuple[str, int]] = list(self._seeds)
        seen = set(candidates)
        for s in self._ring:
            addr = (s.ip, s.db_port)
            if addr not in seen:
                seen.add(addr)
                candidates.append(addr)
        last_error: Optional[Exception] = None
        for host, port in candidates:
            try:
                # Per-candidate bound: _send_to's bare open_connection
                # would otherwise ride the OS connect timeout
                # (~2 min) on a SYN-black-holed member.
                raw = await asyncio.wait_for(
                    self._send_to(
                        host, port, {"type": "get_cluster_metadata"}
                    ),
                    5.0,
                )
                metadata = ClusterMetadata.from_wire(
                    msgpack.unpackb(raw, raw=False)
                )
                self._apply_metadata(metadata)
                return
            except (
                DbeelError,
                OSError,
                asyncio.IncompleteReadError,
                asyncio.TimeoutError,
            ) as e:
                last_error = e
        raise ConnectionError_(
            f"no seed or ring member reachable: {last_error!r}"
        )

    def _apply_metadata(self, metadata: ClusterMetadata) -> None:
        ring: List[_RingShard] = []
        for node in metadata.nodes:
            for i, sid in enumerate(node.ids):
                # Vnode dialect (ISSUE 18): a node that advertises
                # per-shard token lists gets one ring entry per token;
                # nodes without the trailing element (old peers)
                # imply the legacy single-token derivation.
                if node.tokens is not None and i < len(node.tokens):
                    tokens = node.tokens[i]
                else:
                    tokens = [hash_string(f"{node.name}-{sid}")]
                for h in tokens:
                    ring.append(
                        _RingShard(
                            node_name=node.name,
                            hash=h,
                            ip=node.ip,
                            db_port=node.db_port + sid,
                        )
                    )
        ring.sort(key=lambda s: (s.hash, s.node_name))
        self._ring = ring
        self._ring_hashes = [s.hash for s in ring]
        self._collections = {
            name: rf for name, rf in metadata.collections
        }
        # Membership epoch of the view this ring came from: stamped on
        # writes so a server mid-migration can refuse (retryably) ops
        # routed with a stale ring instead of misplacing them.
        self._cluster_epoch = metadata.epoch

    # -- raw protocol --------------------------------------------------

    @staticmethod
    async def _round_trip(reader, writer, request: dict) -> bytes:
        buf = msgpack.packb(request, use_bin_type=True)
        writer.write(struct.pack("<H", len(buf)) + buf)
        await writer.drain()
        header = await reader.readexactly(4)
        (size,) = struct.unpack("<I", header)
        return await reader.readexactly(size)

    def _pipe_for(self, host: str, port: int) -> _PipelinedConnection:
        key = (host, port)
        pipe = self._pipes.get(key)
        if pipe is None or not pipe.usable:
            pipe = _PipelinedConnection(
                host, port, self._pipeline_window
            )
            self._pipes[key] = pipe
        return pipe

    async def _send_to(self, host: str, port: int, request: dict) -> bytes:
        """One request/response round trip (u16-len request; u32-len
        response + trailing type byte), over a pooled keepalive
        connection (or the target's pipelined connection) when
        enabled."""
        if self._pipeline_window:
            request = dict(request)
            request["keepalive"] = True
            try:
                payload = await self._pipe_for(host, port).request(
                    msgpack.packb(request, use_bin_type=True)
                )
            except (OSError, asyncio.IncompleteReadError) as e:
                raise ConnectionError_(
                    f"pipelined request to {host}:{port}: {e}"
                ) from e
            if not payload:
                raise ProtocolError("empty response")
            body, rtype = payload[:-1], payload[-1]
            if rtype == RESPONSE_ERR:
                raise from_wire(msgpack.unpackb(body, raw=False))
            return body
        payload = None
        if self._pooled:
            request = dict(request)
            request["keepalive"] = True
            key = (host, port)
            while payload is None and self._pool.get(key):
                reader, writer = self._pool[key].pop()
                try:
                    payload = await self._round_trip(
                        reader, writer, request
                    )
                except (OSError, asyncio.IncompleteReadError):
                    writer.close()  # stale pooled conn; try another
                except BaseException:
                    writer.close()  # cancellation etc: don't leak
                    raise
            if payload is not None:
                self._release(key, reader, writer)
        if payload is None:
            reader, writer = await asyncio.open_connection(host, port)
            try:
                payload = await self._round_trip(
                    reader, writer, request
                )
            except BaseException:
                writer.close()
                raise
            if self._pooled:
                self._release((host, port), reader, writer)
            else:
                writer.close()
        if not payload:
            raise ProtocolError("empty response")
        body, rtype = payload[:-1], payload[-1]
        if rtype == RESPONSE_ERR:
            raise from_wire(msgpack.unpackb(body, raw=False))
        return body

    def _release(self, key, reader, writer) -> None:
        pool = self._pool.setdefault(key, [])
        if len(pool) < self.MAX_POOL_PER_TARGET:
            pool.append((reader, writer))
        else:
            writer.close()

    def close(self) -> None:
        for conns in self._pool.values():
            for _r, w in conns:
                w.close()
        self._pool.clear()
        for pipe in self._pipes.values():
            pipe.close()
        self._pipes.clear()

    # -- routing (lib.rs:336-417) ---------------------------------------

    def _shards_for_key(self, key_hash: int, rf: int) -> List[_RingShard]:
        """First ring shard at/after the hash, then the next shards on
        distinct nodes — the replica walk."""
        if not self._ring:
            raise ConnectionError_("empty ring; sync_metadata first")
        start = bisect_left(self._ring_hashes, key_hash)
        if start == len(self._ring):
            start = 0
        out: List[_RingShard] = []
        seen_nodes: set = set()
        for off in range(len(self._ring)):
            s = self._ring[(start + off) % len(self._ring)]
            if s.node_name in seen_nodes:
                continue
            seen_nodes.add(s.node_name)
            out.append(s)
            if len(out) >= rf:
                break
        return out

    @classmethod
    def _backoff_s(
        cls, attempt: int, rng: random.Random
    ) -> float:
        """Capped exponential backoff with jitter: uniform in
        [d/2, d] for d = min(cap, base * 2^attempt) — bounded above
        by BACKOFF_CAP_S, never zero (no synchronized retry storms
        from many clients hitting one churn event)."""
        shift = min(attempt, 20)  # 1<<unbounded overflows float mult
        d = min(cls.BACKOFF_CAP_S, cls.BACKOFF_BASE_S * (1 << shift))
        return d * (0.5 + 0.5 * rng.random())

    def _retry_reserve_s(self) -> float:
        """Minimum budget a NEW attempt needs to be worth dialing.
        The backoff pause is capped at the remaining budget, so
        without this floor the last retry of every deadline-bounded
        sequence launches with ~0 ms left — guaranteed wasted work
        that the server's deadline check answers with a generic
        Timeout, downgrading the meaningful refusal (Overloaded,
        QuotaExceeded) the earlier rounds surfaced as last_error."""
        return min(0.1, self._op_deadline_s / 4)

    def _stamp_qos(self, request: dict) -> None:
        """QoS stamp (class + tenant) on a data-op frame — one place
        so every transport (walk, scan chunks, multi frames) stamps
        identically."""
        if self._qos_class is not None:
            request["qos"] = self._qos_class
        if self._tenant is not None:
            request["tenant"] = self._tenant

    async def _sharded_request(
        self, key: Any, request: dict, rf: int
    ) -> bytes:
        key_encoded = msgpack.packb(key, use_bin_type=True)
        key_hash = hash_bytes(key_encoded)
        request = dict(request)
        request["hash"] = key_hash
        self._stamp_qos(request)

        loop = asyncio.get_event_loop()
        deadline = loop.time() + self._op_deadline_s
        # Deadline propagation (overload plane): the op's absolute
        # wall-clock budget rides the request frame, so the server can
        # drop the work server-side (and replicas replica-side) once
        # we have given up — instead of computing a dead response.
        request["deadline_ms"] = int(
            (time.time() + self._op_deadline_s) * 1000
        )
        attempt = 0
        last_error: Optional[Exception] = None
        # Conditional writes are NOT blindly replayable: past the
        # decider's decide point a failed exchange may have committed,
        # and replaying the same expectations would either lose to the
        # op's own applied outcome (mis-reporting a committed write as
        # a definitive CasConflict) or double-apply it.  The server
        # keeps every PRE-decide refusal on distinguishable kinds
        # (KeyNotOwnedByShard, Overloaded, QuotaExceeded, PeerDead)
        # and folds every post-decide failure into plain Timeout — so
        # only those kinds (plus a connect-refused dial, provably
        # undelivered) walk on and retry; everything else surfaces
        # as-is, and the caller resolves ambiguity by re-reading (rmw
        # does; so does the chaos gate's ambiguous bucket).
        conditional = request.get("type") in (
            "cas",
            "atomic_batch",
        )
        while True:
            replicas = self._shards_for_key(key_hash, max(1, rf))
            # Epoch fence (ISSUE 18): writes carry the membership epoch
            # of the ring view that routed them, re-stamped every round
            # so the post-resync retry carries the refreshed epoch.  A
            # server mid-migration refuses (retryably) a write stamped
            # with an older epoch instead of placing it by a dead view.
            if (
                self._cluster_epoch
                and request.get("type") in _EPOCH_STAMPED_OPS
            ):
                request["epoch"] = self._cluster_epoch
            not_owned = False
            # Sticky per-round transport flag (C walk parity,
            # dbeel_client.cpp): once any replica was unreachable the
            # key's state is UNKNOWN — a later replica's KeyNotFound
            # must not downgrade the op to a final "not found".
            transport_error: Optional[Exception] = None
            for replica_index, shard in enumerate(replicas):
                budget = deadline - loop.time()
                if budget <= 0:
                    break
                request["replica_index"] = replica_index
                # Bound the coordinator's own quorum wait to what is
                # left of OUR budget, so a stalled quorum still
                # leaves room to walk to the next coordinator.
                request["timeout"] = max(
                    100, min(5000, int(budget * 1000))
                )
                try:
                    return await asyncio.wait_for(
                        self._send_to(
                            shard.ip, shard.db_port, request
                        ),
                        budget,
                    )
                except CasConflict:
                    # Atomic plane (ISSUE 19): a lost CAS race is a
                    # DECIDED outcome, not an infrastructure failure
                    # — no other replica can answer differently, and
                    # a blind replay of the same expectations would
                    # just lose again.  Surface it immediately; the
                    # rmw helper re-reads and retries with fresh
                    # expectations.
                    raise
                except KeyNotOwnedByShard as e:
                    # Stale ring: resync and retry (lib.rs:392-409).
                    last_error = e
                    not_owned = True
                    break
                except asyncio.TimeoutError:
                    # Our own budget expired mid-request: transport-
                    # class (state UNKNOWN) — it must never be
                    # downgraded by another replica's KeyNotFound.
                    if transport_error is None:
                        transport_error = Timeout(
                            f"op deadline ({self._op_deadline_s:.1f}s)"
                            " exhausted"
                        )
                    if conditional:
                        # The conditional may have been decided in
                        # flight: surface the ambiguity.
                        raise transport_error
                    break
                except (
                    DbeelError,
                    OSError,
                    asyncio.IncompleteReadError,
                ) as e:
                    # Reference walk semantics (lib.rs:368-383): record
                    # and advance — connect refused/reset, a dead
                    # coordinator's quorum-timeout, or an application
                    # error; the next replica may answer.
                    last_error = e
                    if conditional and not (
                        isinstance(e, ConnectionRefusedError)
                        or (
                            isinstance(e, DbeelError)
                            and classify_error(e)
                            in (
                                ERROR_CLASS_OVERLOAD,
                                ERROR_CLASS_QUOTA,
                                ERROR_CLASS_PEER_DEAD,
                            )
                        )
                    ):
                        # Possibly decided in flight (or a definitive
                        # refusal): no replay — see the contract above.
                        raise
                    if not isinstance(e, DbeelError) or (
                        is_retryable_class(classify_error(e))
                    ):
                        transport_error = e
                    continue
            if transport_error is not None:
                # Unknown state beats any benign outcome seen on OTHER
                # replicas this round — raise/retry the transport
                # error, never the downgraded KeyNotFound.
                last_error = transport_error
            # Walk exhausted.  Application outcomes are final; the
            # infrastructure classes retry after backoff while budget
            # remains — under churn the ring heals in well under an
            # op deadline.
            retryable = not_owned or is_retryable_class(
                classify_error(last_error)
                if last_error is not None
                else None
            )
            if (
                not retryable
                or loop.time() >= deadline - self._retry_reserve_s()
            ):
                break
            if not_owned or not isinstance(last_error, DbeelError):
                # Ring is stale (wrong owner) or nodes vanished
                # (transport errors): refresh the view before the
                # next round.  Best-effort — with every seed briefly
                # down we keep walking the last known ring.
                try:
                    await asyncio.wait_for(
                        self.sync_metadata(),
                        max(0.05, deadline - loop.time()),
                    )
                except (DbeelError, OSError, asyncio.TimeoutError):
                    pass
            backoff_attempt = attempt
            if last_error is not None and classify_error(
                last_error
            ) in (ERROR_CLASS_OVERLOAD, ERROR_CLASS_QUOTA):
                # The server is SHEDDING (or this tenant's bucket is
                # dry): retrying fast only feeds the overload / burns
                # the refill — skip ahead in the backoff schedule
                # (the jittered cap still bounds the pause).
                backoff_attempt += 2
            # Leave the retry reserve intact: a pause that drains the
            # budget just moves the wasted ~0-budget dial after the
            # sleep instead of skipping it.
            pause = min(
                self._backoff_s(backoff_attempt, self._rng),
                max(
                    0.0,
                    deadline - self._retry_reserve_s() - loop.time(),
                ),
            )
            if pause > 0:
                await asyncio.sleep(pause)
            attempt += 1
        raise last_error if last_error else ConnectionError_(
            "no replica reachable"
        )

    # -- streaming scans ----------------------------------------------

    async def _scan_chunk_request(self, request: dict) -> dict:
        """One scan/scan_next chunk with the full failure discipline:
        the chunk can run on ANY node (the cursor is self-contained),
        so a dead or Overloaded coordinator walks to the next ring
        member after capped backoff, resyncing the ring on transport
        errors — a scan survives a coordinator restart mid-stream."""
        loop = asyncio.get_event_loop()
        deadline = loop.time() + self._op_deadline_s
        request = dict(request)
        request["deadline_ms"] = int(
            (time.time() + self._op_deadline_s) * 1000
        )
        self._stamp_qos(request)
        attempt = 0
        last_error: Optional[Exception] = None
        while True:
            targets = [
                (s.ip, s.db_port) for s in self._ring
            ] or list(self._seeds)
            if len(targets) > 1:
                # Rotate for load spread: scans have no owning key,
                # any coordinator merges the same stream.
                rot = self._rng.randrange(len(targets))
                targets = targets[rot:] + targets[:rot]
            for host, port in targets:
                budget = deadline - loop.time()
                if budget <= 0:
                    break
                request["timeout"] = max(
                    100, min(5000, int(budget * 1000))
                )
                try:
                    raw = await asyncio.wait_for(
                        self._send_to(host, port, request), budget
                    )
                    return msgpack.unpackb(raw, raw=False)
                except asyncio.TimeoutError:
                    last_error = Timeout(
                        f"scan chunk deadline "
                        f"({self._op_deadline_s:.1f}s) exhausted"
                    )
                    break
                except (
                    DbeelError,
                    OSError,
                    asyncio.IncompleteReadError,
                ) as e:
                    last_error = e
                    if isinstance(
                        e, DbeelError
                    ) and not is_retryable_class(classify_error(e)):
                        raise  # benign/final (bad cursor, no such collection)
                    continue
            if loop.time() >= deadline - self._retry_reserve_s():
                break
            if not isinstance(last_error, DbeelError):
                try:
                    await asyncio.wait_for(
                        self.sync_metadata(),
                        max(0.05, deadline - loop.time()),
                    )
                except (DbeelError, OSError, asyncio.TimeoutError):
                    pass
            backoff_attempt = attempt
            if last_error is not None and classify_error(
                last_error
            ) in (ERROR_CLASS_OVERLOAD, ERROR_CLASS_QUOTA):
                # The shard shed the chunk (or the tenant's bucket is
                # dry): the cursor survives — back off harder before
                # resuming.
                backoff_attempt += 2
            # Leave the retry reserve intact (see _sharded_request).
            pause = min(
                self._backoff_s(backoff_attempt, self._rng),
                max(
                    0.0,
                    deadline - self._retry_reserve_s() - loop.time(),
                ),
            )
            if pause > 0:
                await asyncio.sleep(pause)
            attempt += 1
        raise last_error if last_error else ConnectionError_(
            "no node reachable for scan"
        )

    # -- watch/CDC streams --------------------------------------------

    async def _watch_chunk_request(self, request: dict) -> dict:
        """One watch/watch_next chunk with the scan plane's walk
        discipline — the cursor is self-contained, so the stream
        resumes on ANY node after a coordinator death or shed — plus
        the epoch-fence leg: a retryable ``KeyNotOwnedByShard``
        (cursor stamped before the current churn) resyncs metadata
        before retrying the SAME cursor, which the next coordinator
        re-stamps once its migration settles."""
        loop = asyncio.get_event_loop()
        deadline = loop.time() + self._op_deadline_s
        request = dict(request)
        request["deadline_ms"] = int(
            (time.time() + self._op_deadline_s) * 1000
        )
        self._stamp_qos(request)
        attempt = 0
        last_error: Optional[Exception] = None
        while True:
            targets = [
                (s.ip, s.db_port) for s in self._ring
            ] or list(self._seeds)
            if len(targets) > 1:
                rot = self._rng.randrange(len(targets))
                targets = targets[rot:] + targets[:rot]
            for host, port in targets:
                budget = deadline - loop.time()
                if budget <= 0:
                    break
                request["timeout"] = max(
                    100, min(5000, int(budget * 1000))
                )
                try:
                    raw = await asyncio.wait_for(
                        self._send_to(host, port, request), budget
                    )
                    return msgpack.unpackb(raw, raw=False)
                except asyncio.TimeoutError:
                    last_error = Timeout(
                        f"watch chunk deadline "
                        f"({self._op_deadline_s:.1f}s) exhausted"
                    )
                    break
                except (
                    DbeelError,
                    OSError,
                    asyncio.IncompleteReadError,
                ) as e:
                    last_error = e
                    if isinstance(
                        e, DbeelError
                    ) and not is_retryable_class(classify_error(e)):
                        raise  # benign/final (bad cursor, no such collection)
                    continue
            if loop.time() >= deadline - self._retry_reserve_s():
                break
            if not isinstance(
                last_error, DbeelError
            ) or isinstance(last_error, KeyNotOwnedByShard):
                # Transport loss OR the epoch fence: refresh the
                # ring/epoch view before the next walk.
                try:
                    await asyncio.wait_for(
                        self.sync_metadata(),
                        max(0.05, deadline - loop.time()),
                    )
                except (DbeelError, OSError, asyncio.TimeoutError):
                    pass
            backoff_attempt = attempt
            if last_error is not None and classify_error(
                last_error
            ) in (ERROR_CLASS_OVERLOAD, ERROR_CLASS_QUOTA):
                # Shed (slow-subscriber byte budget, subscriber cap,
                # or hard overload): the cursor survives — back off
                # harder before polling again.
                backoff_attempt += 2
            pause = min(
                self._backoff_s(backoff_attempt, self._rng),
                max(
                    0.0,
                    deadline - self._retry_reserve_s() - loop.time(),
                ),
            )
            if pause > 0:
                await asyncio.sleep(pause)
            attempt += 1
        raise last_error if last_error else ConnectionError_(
            "no node reachable for watch"
        )

    # -- batched multi-ops --------------------------------------------

    # Per-frame bounds: the request framing is u16-LE, so a batch
    # frame must stay comfortably under 64 KiB; the op count cap
    # bounds server-side allocation fan per frame.
    MULTI_MAX_OPS_PER_FRAME = 256
    MULTI_MAX_BYTES_PER_FRAME = 48 << 10

    async def _multi_request(
        self,
        collection: str,
        rf: int,
        is_set: bool,
        keys: list,
        values: list,
        consistency: Optional[int],
        trace_id: Optional[int] = None,
    ) -> list:
        """Group sub-ops by owning coordinator via the ring, send ONE
        multi frame per node (chunked under the u16 frame bound), and
        fail over per sub-op: any sub-op that comes back with a
        retryable/ownership error — or whose whole frame failed —
        re-runs through the single-op replica walk (full PR-1
        failover: walk, resync, backoff, deadline).  Returns outcomes
        aligned with ``keys``: ("ok", payload) or ("err", exc)."""
        n = len(keys)
        enc = [
            msgpack.packb(k, use_bin_type=True) for k in keys
        ]
        hashes = [hash_bytes(e) for e in enc]
        outcomes: list = [None] * n
        groups: Dict[tuple, list] = {}
        for i, h in enumerate(hashes):
            shard = self._shards_for_key(h, max(1, rf))[0]
            groups.setdefault((shard.ip, shard.db_port), []).append(i)

        rtype = "multi_set" if is_set else "multi_get"

        async def send_chunk(addr: tuple, idxs: list) -> None:
            ops = [
                [keys[i], hashes[i], values[i]]
                if is_set
                else [keys[i], hashes[i]]
                for i in idxs
            ]
            request: dict = {
                "type": rtype,
                "collection": collection,
                "ops": ops,
                "replica_index": 0,
                # Coordinator-side bound, mirroring _sharded_request:
                # the batch's quorum wait must not outlive our own
                # deadline budget.
                "timeout": max(
                    100, min(5000, int(self._op_deadline_s * 1000))
                ),
                # Deadline propagation for the whole batch frame.
                "deadline_ms": int(
                    (time.time() + self._op_deadline_s) * 1000
                ),
            }
            if consistency is not None:
                request["consistency"] = consistency
            if is_set and self._cluster_epoch:
                # Same epoch fence as the single-op path; fenced
                # sub-ops come back retryable and fall into the
                # single-op walk, which resyncs and re-stamps.
                request["epoch"] = self._cluster_epoch
            self._stamp_qos(request)
            if isinstance(trace_id, int) and trace_id > 0:
                # Tracing plane: the whole batch frame records one
                # per-stage span (replica spans piggyback on the
                # MULTI_* peer responses).
                request["trace"] = trace_id
            try:
                try:
                    # Deadline-bound like every single op (a black-
                    # holed coordinator must fail the chunk over to
                    # the per-sub-op walk, not hang the batch).
                    raw = await asyncio.wait_for(
                        self._send_to(addr[0], addr[1], request),
                        self._op_deadline_s,
                    )
                except struct.error:
                    # Frame overflowed the u16 bound (values are not
                    # pre-measured — serializing them twice just to
                    # size chunks would double client CPU on the hot
                    # batch path): split and retry.
                    if len(idxs) == 1:
                        outcomes[idxs[0]] = (
                            "err",
                            ProtocolError(
                                "sub-op exceeds the u16 frame bound"
                            ),
                        )
                        return
                    mid = len(idxs) // 2
                    await send_chunk(addr, idxs[:mid])
                    await send_chunk(addr, idxs[mid:])
                    return
                results = msgpack.unpackb(raw, raw=False)
                if (
                    not isinstance(results, list)
                    or len(results) != len(idxs)
                ):
                    raise ProtocolError("bad multi response shape")
            except (
                DbeelError,
                OSError,
                asyncio.IncompleteReadError,
                asyncio.TimeoutError,
            ) as e:
                # Whole-frame failure (dead coordinator, stale ring
                # collection, transport): every sub-op falls back to
                # the single-op walk.
                for i in idxs:
                    outcomes[i] = ("retry", e)
                return
            for i, res in zip(idxs, results):
                status, payload = res[0], res[1]
                if status == 0:
                    outcomes[i] = ("ok", payload)
                    continue
                e = from_wire(payload)
                if isinstance(e, KeyNotOwnedByShard) or (
                    is_retryable_class(classify_error(e))
                ):
                    outcomes[i] = ("retry", e)
                else:
                    outcomes[i] = ("err", e)

        # Chunk by op count and KEY bytes only — value sizes are not
        # pre-measured (that would serialize every value twice); a
        # chunk whose packed frame still overflows the u16 bound is
        # split on struct.error inside send_chunk.
        chunks: List[tuple] = []
        for addr, idxs in groups.items():
            cur: list = []
            cur_bytes = 0
            for i in idxs:
                op_bytes = len(enc[i]) + 16
                if cur and (
                    len(cur) >= self.MULTI_MAX_OPS_PER_FRAME
                    or cur_bytes + op_bytes
                    > self.MULTI_MAX_BYTES_PER_FRAME
                ):
                    chunks.append((addr, cur))
                    cur, cur_bytes = [], 0
                cur.append(i)
                cur_bytes += op_bytes
            if cur:
                chunks.append((addr, cur))
        await asyncio.gather(
            *(send_chunk(addr, idxs) for addr, idxs in chunks)
        )

        retries = [
            i for i in range(n) if outcomes[i][0] == "retry"
        ]
        if retries:
            async def walk_one(i: int) -> None:
                request: dict = {
                    "type": "set" if is_set else "get",
                    "collection": collection,
                    "key": keys[i],
                }
                if is_set:
                    request["value"] = values[i]
                if consistency is not None:
                    request["consistency"] = consistency
                try:
                    body = await self._sharded_request(
                        keys[i], request, rf
                    )
                    outcomes[i] = ("ok", None if is_set else body)
                except (
                    DbeelError,
                    OSError,
                    asyncio.IncompleteReadError,
                    asyncio.TimeoutError,
                ) as e:
                    # _sharded_request re-raises its LAST transport
                    # error raw (OSError et al.) when the walk
                    # exhausts — one dead sub-op must become an
                    # aligned outcome, not abort the whole batch.
                    outcomes[i] = ("err", e)

            await asyncio.gather(*(walk_one(i) for i in retries))
        return outcomes

    # -- public API (lib.rs:482-619) -------------------------------------

    async def create_collection(
        self,
        name: str,
        replication_factor: Optional[int] = None,
        ops_per_sec: Optional[int] = None,
        bytes_per_sec: Optional[int] = None,
        index: Optional[list] = None,
    ) -> "DbeelCollection":
        """``ops_per_sec``/``bytes_per_sec`` carry per-collection
        tenant-quota overrides on the DDL (ISSUE 15 satellite): they
        beat the server's ``--tenant-*`` flag defaults for this
        collection only (0 disables the limit), and round-trip
        through collection metadata (restart- and gossip-safe).

        ``index`` names value fields to maintain persisted secondary
        index runs for (ISSUE 17): flush/compaction emit per-SSTable
        fidx runs inline and indexed ``scan(filter=)`` / ``count``
        predicates on those fields skip the full scan.  Round-trips
        through metadata/gossip like quotas."""
        request = {"type": "create_collection", "name": name}
        if replication_factor is not None:
            request["replication_factor"] = replication_factor
        if ops_per_sec is not None:
            request["ops_per_sec"] = int(ops_per_sec)
        if bytes_per_sec is not None:
            request["bytes_per_sec"] = int(bytes_per_sec)
        if index:
            request["index"] = [str(f) for f in index]
        host, port = self._seeds[0]
        await self._send_to(host, port, request)
        await self.sync_metadata()
        return self.collection(name)

    async def drop_collection(self, name: str) -> None:
        host, port = self._seeds[0]
        await self._send_to(
            host, port, {"type": "drop_collection", "name": name}
        )
        await self.sync_metadata()

    def collection(self, name: str) -> "DbeelCollection":
        rf = self._collections.get(name, 1)
        return DbeelCollection(self, name, rf)

    async def get_cluster_metadata(self) -> ClusterMetadata:
        host, port = self._seeds[0]
        raw = await self._send_to(
            host, port, {"type": "get_cluster_metadata"}
        )
        return ClusterMetadata.from_wire(msgpack.unpackb(raw, raw=False))

    async def get_stats(
        self, host: Optional[str] = None, port: Optional[int] = None
    ) -> dict:
        """Per-shard observability snapshot from one server (the
        first seed by default): durability, scheduler, metrics and
        the ``convergence`` block (hints queued/replayed/expired,
        read repairs, anti-entropy rounds / keys healed)."""
        if host is None or port is None:
            host, port = self._seeds[0]
        raw = await self._send_to(host, port, {"type": "get_stats"})
        return msgpack.unpackb(raw, raw=False)

    async def trace_dump(
        self, host: Optional[str] = None, port: Optional[int] = None
    ) -> dict:
        """One shard's flight-recorder dump (tracing plane): sampled
        per-stage spans — coordinator stages plus per-replica RTT and
        piggybacked replica stage summaries — and a minimal record
        for every slow/error op.  Always served, even at hard
        overload (like get_stats)."""
        if host is None or port is None:
            host, port = self._seeds[0]
        raw = await self._send_to(host, port, {"type": "trace_dump"})
        return msgpack.unpackb(raw, raw=False)

    async def cluster_stats(
        self, host: Optional[str] = None, port: Optional[int] = None
    ) -> dict:
        """The gossip-aggregated cluster health view from one node
        (the first seed by default): per-node digests (level, ops/s,
        error/shed rates, degraded flag, hint backlog, watchdog
        finding kinds) under ``nodes``, plus ``missing`` for ring
        members not yet heard from.  Always served, even at hard
        overload — ask ANY node, see the whole cluster."""
        if host is None or port is None:
            host, port = self._seeds[0]
        raw = await self._send_to(
            host, port, {"type": "cluster_stats"}
        )
        return msgpack.unpackb(raw, raw=False)

    async def telemetry_dump(
        self, host: Optional[str] = None, port: Optional[int] = None
    ) -> dict:
        """One shard's full telemetry time-series ring (flattened
        get_stats samples stamped with seq/ts_ms/uptime_s), derived
        rates, and the health watchdog's verdict.  Always served,
        like get_stats/trace_dump."""
        if host is None or port is None:
            host, port = self._seeds[0]
        raw = await self._send_to(
            host, port, {"type": "telemetry_dump"}
        )
        return msgpack.unpackb(raw, raw=False)

    async def rearm(
        self, host: Optional[str] = None, port: Optional[int] = None
    ) -> None:
        """Admin: tell one node (the first seed by default) to exit
        sticky degraded read-only mode after disk replacement — the
        node re-runs its free-space/WAL-append pre-checks on every
        shard and re-registers the native write plane.  Raises the
        server's error (node stays degraded) when a pre-check still
        fails."""
        if host is None or port is None:
            host, port = self._seeds[0]
        await self._send_to(host, port, {"type": "rearm"})


class DbeelCollection:
    def __init__(self, client: DbeelClient, name: str, rf: int):
        self.client = client
        self.name = name
        self.replication_factor = rf

    async def set(
        self, key: Any, value: Any, consistency=None,
        trace_id: Optional[int] = None,
    ) -> None:
        """``trace_id`` (tracing plane): stamp the request so the
        server records a full per-stage span for this op, queryable
        via trace_dump."""
        request = {
            "type": "set",
            "collection": self.name,
            "key": key,
            "value": value,
        }
        if consistency is not None:
            request["consistency"] = Consistency.resolve(
                consistency, self.replication_factor
            )
        if isinstance(trace_id, int) and trace_id > 0:
            request["trace"] = trace_id
        await self.client._sharded_request(
            key, request, self.replication_factor
        )

    async def get(
        self, key: Any, consistency=None,
        trace_id: Optional[int] = None,
    ) -> Any:
        request = {
            "type": "get",
            "collection": self.name,
            "key": key,
        }
        if consistency is not None:
            request["consistency"] = Consistency.resolve(
                consistency, self.replication_factor
            )
        if isinstance(trace_id, int) and trace_id > 0:
            request["trace"] = trace_id
        raw = await self.client._sharded_request(
            key, request, self.replication_factor
        )
        return msgpack.unpackb(raw, raw=False)

    async def multi_set(
        self, items, consistency=None,
        trace_id: Optional[int] = None,
    ) -> None:
        """Batched set: ``items`` is a dict or an iterable of
        (key, value) pairs.  Keys are grouped by owning coordinator
        and travel one frame per node (multi_set); failed sub-ops
        fall back to the single-op replica walk.  Raises the first
        sub-op error (all other sub-ops still complete)."""
        pairs = (
            list(items.items())
            if isinstance(items, dict)
            else list(items)
        )
        if not pairs:
            return
        resolved = (
            Consistency.resolve(consistency, self.replication_factor)
            if consistency is not None
            else None
        )
        outcomes = await self.client._multi_request(
            self.name,
            self.replication_factor,
            True,
            [k for k, _v in pairs],
            [v for _k, v in pairs],
            resolved,
            trace_id=trace_id,
        )
        for kind, payload in outcomes:
            if kind == "err":
                raise payload

    async def multi_get(
        self, keys: Sequence[Any], consistency=None,
        trace_id: Optional[int] = None,
    ) -> list:
        """Batched get: returns values aligned with ``keys`` (None
        for missing keys).  One frame per owning node; failed sub-ops
        fall back to the single-op replica walk.  Raises the first
        non-KeyNotFound sub-op error."""
        keys = list(keys)
        if not keys:
            return []
        resolved = (
            Consistency.resolve(consistency, self.replication_factor)
            if consistency is not None
            else None
        )
        outcomes = await self.client._multi_request(
            self.name,
            self.replication_factor,
            False,
            keys,
            [None] * len(keys),
            resolved,
            trace_id=trace_id,
        )
        out = []
        for kind, payload in outcomes:
            if kind == "ok":
                out.append(msgpack.unpackb(payload, raw=False))
            elif isinstance(payload, KeyNotFound):
                out.append(None)
            else:
                raise payload
        return out

    async def scan(
        self,
        prefix: Optional[bytes] = None,
        limit: Optional[int] = None,
        max_bytes: Optional[int] = None,
        trace_id: Optional[int] = None,
        filter: Optional[Any] = None,
    ):
        """Streaming full/range scan (scan plane, PR 12): an async
        generator yielding (key, value) pairs — decoded documents —
        in raw encoded-key byte order (the storage order), merged
        newest-wins across every replica of every ring arc with
        tombstones excluded.  One governor-paced chunk per server
        round trip; the resumable cursor rides inside, so the stream
        survives Overloaded sheds and coordinator restarts.

        ``prefix`` filters on the msgpack-ENCODED key bytes (pushed
        down to the vectorized storage stage).  ``limit`` caps total
        yielded entries; ``max_bytes`` lowers the per-chunk byte
        budget below the server's ``--scan-bytes-per-slice``.

        ``filter`` (query compute plane, PR 13) is a predicate tree
        — see dbeel_tpu.query (e.g. ``["and", ["cmp", "temp", ">=",
        20], ["prefix", "city", "san"]]``) — evaluated VECTORIZED on
        the replicas over their staged columns: non-matching values
        never cross any wire, and the per-chunk budget bills bytes
        scanned, so a selective scan returns in the same bounded
        chunks with ~none of the bytes."""
        request: dict = {"type": "scan", "collection": self.name}
        if prefix:
            request["prefix"] = bytes(prefix)
        if limit:
            request["limit"] = int(limit)
        if max_bytes:
            request["max_bytes"] = int(max_bytes)
        if filter is not None:
            from .. import query as _query

            w, _ = _query.build_spec(filter, None)
            request["spec"] = _query.pack_spec(w, None)
        if isinstance(trace_id, int) and trace_id > 0:
            request["trace"] = trace_id
        while True:
            chunk = await self.client._scan_chunk_request(request)
            # Entries arrive as DECODED (key, value) documents: the
            # server splices the stored encodings into the chunk
            # payload, so the chunk's one unpack decoded everything.
            for key, value in chunk.get("entries") or ():
                yield key, value
            cursor = chunk.get("cursor")
            if not cursor:
                return
            request = {"type": "scan_next", "cursor": cursor}
            if isinstance(trace_id, int) and trace_id > 0:
                request["trace"] = trace_id

    def watcher(
        self,
        filter: Optional[Any] = None,
        wait_ms: int = 1000,
        sub_id: Optional[str] = None,
        cursor: Optional[bytes] = None,
    ) -> "Watcher":
        """Chunk-level handle on a change stream (watch/CDC plane,
        ISSUE 20): ``await w.next_events()`` returns one chunk of
        events, ``w.cursor`` is the resumable token after every
        chunk — persist it and pass it back as ``cursor=`` to resume
        the exact stream (on ANY node) after a client restart."""
        return Watcher(
            self.client,
            self.name,
            filter=filter,
            wait_ms=wait_ms,
            sub_id=sub_id,
            cursor=cursor,
        )

    async def watch(
        self,
        filter: Optional[Any] = None,
        wait_ms: int = 1000,
        sub_id: Optional[str] = None,
        cursor: Optional[bytes] = None,
    ):
        """Change stream (watch/CDC plane, ISSUE 20): an async
        generator yielding ``(key, value, ts, flags)`` for every
        acked mutation from NOW on (or from ``cursor`` when
        resuming) — ``value is None`` is a delete, ``flags & 1`` an
        explicitly flagged possible duplicate (catch-up/handoff
        replay; never silent).  Delivery is state-compacting
        (newest version per key per chunk) and loss-free across
        coordinator death, partitions, and membership churn; the
        stream long-polls ``wait_ms`` per empty round and backs off
        adaptively between empty chunks.

        ``filter`` is the PR 13 predicate dialect, evaluated
        replica-side; a filtered stream delivers matching live
        versions only (no deletes)."""
        w = self.watcher(
            filter=filter,
            wait_ms=wait_ms,
            sub_id=sub_id,
            cursor=cursor,
        )
        streak = 0
        while True:
            events = await w.next_events()
            if events:
                streak = 0
                for ev in events:
                    yield ev
            else:
                # The server already parked wait_ms on its LOCAL
                # ring; this client-side backoff only paces polls
                # when events live on remote arcs or the stream is
                # idle.
                streak = min(streak + 1, 6)
                await asyncio.sleep(
                    min(1.0, 0.05 * (2 ** streak))
                )

    async def count(
        self,
        prefix: Optional[bytes] = None,
        limit: Optional[int] = None,
        filter: Optional[Any] = None,
        aggregate: Optional[dict] = None,
    ) -> Any:
        """Count live documents (optionally under an encoded-key
        prefix) WITHOUT materializing a single value: replicas stream
        keys-only pages (vectorized count pushdown), the coordinator
        merge dedups/count them, and only the running total crosses
        back per chunk.

        ``filter`` (query compute plane, PR 13) counts only matching
        documents.  ``aggregate`` (e.g. ``{"op": "sum", "field":
        "qty"}``, optionally ``{"group": L}`` for a group-by on the
        first L encoded-key bytes) returns the aggregate instead of
        the count — computed replica-side from the staged columns
        where possible, combined as exact per-arc partials, with the
        running state riding the resumable cursor.  Grouped results
        come back as {key_prefix_bytes: value}."""
        request: dict = {
            "type": "scan",
            "collection": self.name,
        }
        if aggregate is not None:
            from .. import query as _query

            w, a = _query.build_spec(filter, aggregate)
            request["spec"] = _query.pack_spec(w, a)
            if limit:
                raise BadFieldType(
                    "limit is not supported with an aggregate"
                )
        else:
            request["count"] = True
            if filter is not None:
                from .. import query as _query

                w, _ = _query.build_spec(filter, None)
                request["spec"] = _query.pack_spec(w, None)
        if prefix:
            request["prefix"] = bytes(prefix)
        if limit:
            request["limit"] = int(limit)
        total = 0
        while True:
            chunk = await self.client._scan_chunk_request(request)
            total = int(chunk.get("count") or 0)
            cursor = chunk.get("cursor")
            if not cursor:
                if aggregate is not None:
                    return chunk.get("agg")
                return total
            request = {"type": "scan_next", "cursor": cursor}

    async def delete(
        self, key: Any, consistency=None,
        trace_id: Optional[int] = None,
    ) -> None:
        request = {
            "type": "delete",
            "collection": self.name,
            "key": key,
        }
        if isinstance(trace_id, int) and trace_id > 0:
            request["trace"] = trace_id
        if consistency is not None:
            request["consistency"] = Consistency.resolve(
                consistency, self.replication_factor
            )
        await self.client._sharded_request(
            key, request, self.replication_factor
        )

    # -- atomic conditional writes (ISSUE 19) -------------------------

    _NO_EXPECT = object()

    async def cas(
        self,
        key: Any,
        value: Any = None,
        *,
        delete: bool = False,
        expect_ts: Optional[int] = None,
        expect_value: Any = _NO_EXPECT,
        expect_absent: bool = False,
        consistency=None,
        trace_id: Optional[int] = None,
    ) -> int:
        """Conditional write: set ``key`` to ``value`` (or tombstone
        it with ``delete=True``) only if the key's current state at
        its arc owner matches EVERY expectation given — ``expect_ts``
        (the exact current server timestamp), ``expect_value`` (the
        exact current decoded value), ``expect_absent`` (no live
        entry).  At least one expectation is required.  Returns the
        decided server timestamp on success; raises ``CasConflict``
        when an expectation mismatched (the decided state is intact —
        re-read and retry with fresh expectations, or use ``rmw``).

        The op is serialized at the key's arc owner, fenced by the
        membership epoch (a mid-migration stale view refuses
        retryably and this client resyncs + retries), and the decided
        outcome replicates as an ordinary LWW write.  Guarantees
        require quorum consistency (the default) and break if raw
        ``set``/``delete`` races the same key."""
        request: dict = {
            "type": "cas",
            "collection": self.name,
            "key": key,
        }
        if delete:
            request["delete"] = True
        else:
            request["value"] = value
        if expect_absent:
            request["expect_absent"] = True
        if expect_ts is not None:
            request["expect_ts"] = int(expect_ts)
        if expect_value is not DbeelCollection._NO_EXPECT:
            request["expect_value"] = expect_value
        if consistency is not None:
            request["consistency"] = Consistency.resolve(
                consistency, self.replication_factor
            )
        if isinstance(trace_id, int) and trace_id > 0:
            request["trace"] = trace_id
        raw = await self.client._sharded_request(
            key, request, self.replication_factor
        )
        decided = msgpack.unpackb(raw, raw=False)
        return int(decided["ts"])

    async def rmw(
        self,
        key: Any,
        fn,
        *,
        max_retries: int = 64,
        consistency=None,
    ) -> Any:
        """Read-modify-write retry loop over ``cas``: read the
        current value (None when absent), apply ``fn(current) ->
        new_value``, and commit conditionally on the state read —
        ``expect_absent`` for absent keys, ``expect_value`` for live
        ones.  On ``CasConflict`` (a concurrent writer won the race)
        re-read and re-apply, up to ``max_retries`` times.  Returns
        the committed new value.

        ``expect_value`` carries the usual ABA caveat: ``fn`` should
        produce values that never repeat a previous state (counters,
        version-stamped documents) for exactly-once semantics."""
        last: Optional[Exception] = None
        for _attempt in range(max_retries):
            try:
                current = await self.get(
                    key, consistency=consistency
                )
            except KeyNotFound:
                current = None
            new_value = fn(current)
            try:
                if current is None:
                    await self.cas(
                        key,
                        new_value,
                        expect_absent=True,
                        consistency=consistency,
                    )
                else:
                    await self.cas(
                        key,
                        new_value,
                        expect_value=current,
                        consistency=consistency,
                    )
                return new_value
            except CasConflict as e:
                last = e
                continue
        raise last if last is not None else Timeout("rmw")

    async def atomic_batch(
        self,
        ops: Sequence[dict],
        consistency=None,
        trace_id: Optional[int] = None,
    ) -> int:
        """All-or-nothing conditional multi-key batch on ONE ring
        arc.  Each op is a dict: ``{"key": k}`` plus either
        ``"value"`` or ``"delete": True``, plus any of the cas
        expectation fields (``expect_ts`` / ``expect_value`` /
        ``expect_absent``; an op with none is unconditional within
        the batch).  Every key must hash to the same ring arc —
        batches spanning arcs are refused as a client error.  All
        conditions are evaluated against a consistent read under the
        arc's decider lock; on success the whole batch commits
        through one WAL group-commit ticket with one decided
        timestamp (returned), on any mismatch the whole batch refuses
        with ``CasConflict``."""
        ops = [dict(op) for op in ops]
        if not ops:
            raise BadFieldType("ops: empty atomic batch")
        for op in ops:
            if "key" not in op:
                raise BadFieldType("ops: op without a key")
        request: dict = {
            "type": "atomic_batch",
            "collection": self.name,
            "ops": ops,
        }
        if consistency is not None:
            request["consistency"] = Consistency.resolve(
                consistency, self.replication_factor
            )
        if isinstance(trace_id, int) and trace_id > 0:
            request["trace"] = trace_id
        # Routed by the FIRST key: the server verifies all keys share
        # its arc, and a stale-ring miss walks/resyncs as usual.
        raw = await self.client._sharded_request(
            ops[0]["key"], request, self.replication_factor
        )
        decided = msgpack.unpackb(raw, raw=False)
        return int(decided["ts"])


class Watcher:
    """Client half of one watch subscription: issues watch /
    watch_next chunks through the any-node walk, tracks the
    resumable cursor, decodes events, and audits the server's
    per-replica ``(boot_epoch, seq)`` positions for monotonicity
    (``monotonicity_violations`` stays 0 on a correct stream — the
    chaos gate's ledger leans on this)."""

    def __init__(
        self,
        client: "DbeelClient",
        collection: str,
        filter: Optional[Any] = None,
        wait_ms: int = 1000,
        sub_id: Optional[str] = None,
        cursor: Optional[bytes] = None,
    ):
        self._client = client
        self._wait_ms = int(wait_ms)
        self.cursor: Optional[bytes] = cursor
        self.chunks = 0
        self.events_seen = 0
        self.dup_flagged = 0
        self.monotonicity_violations = 0
        self._positions: dict = {}
        if cursor is not None:
            self._request = {
                "type": "watch_next",
                "cursor": bytes(cursor),
            }
        else:
            self._request = {
                "type": "watch",
                "collection": collection,
            }
            if filter is not None:
                from .. import query as _query

                w, _ = _query.build_spec(filter, None)
                self._request["spec"] = _query.pack_spec(w, None)
            if sub_id:
                self._request["sub_id"] = str(sub_id)
        if self._wait_ms > 0:
            self._request["wait_ms"] = self._wait_ms

    @staticmethod
    def _cursor_positions(raw) -> dict:
        """Per-replica (boot_epoch, seq) positions out of the opaque
        w1 cursor — a READ-ONLY peek for auditing; the token itself
        stays opaque client state."""
        try:
            w = msgpack.unpackb(bytes(raw), raw=False)
            if (
                not isinstance(w, list)
                or len(w) != 6
                or w[0] != "w1"
            ):
                return {}
            return {
                g[0]: (int(g[2]), int(g[3]))
                for g in w[5]
                if int(g[2]) >= 0
            }
        except Exception:
            return {}

    async def next_events(self) -> list:
        """One chunk: a list of (key, value, ts, flags) with decoded
        documents (value None = delete, flags bit 0 = dup-flagged).
        Empty list = no new events this round (the server long-polled
        ``wait_ms`` on its local ring first)."""
        chunk = await self._client._watch_chunk_request(
            self._request
        )
        cursor = chunk.get("cursor")
        events = []
        for key, value, ts, flags in chunk.get("events") or ():
            self.events_seen += 1
            if flags & 1:
                self.dup_flagged += 1
            events.append(
                (
                    msgpack.unpackb(key, raw=False),
                    msgpack.unpackb(value, raw=False)
                    if value
                    else None,
                    ts,
                    flags,
                )
            )
        if cursor:
            pos = self._cursor_positions(cursor)
            for name, p in pos.items():
                old = self._positions.get(name)
                if old is not None and p < old:
                    self.monotonicity_violations += 1
            self._positions.update(pos)
            self.cursor = bytes(cursor)
            self._request = {
                "type": "watch_next",
                "cursor": self.cursor,
            }
            if self._wait_ms > 0:
                self._request["wait_ms"] = self._wait_ms
        self.chunks += 1
        return events


class DbeelClientSync:
    """Blocking convenience wrapper (the reference ships a 49-line
    synchronous python client, /root/reference/dbeel.py — this is its
    batteries-included equivalent)."""

    def __init__(self, seed_addresses: Sequence[Tuple[str, int]]):
        import asyncio as _asyncio

        self._loop = _asyncio.new_event_loop()
        self._client = self._run(
            DbeelClient.from_seed_nodes(seed_addresses)
        )

    def _run(self, coro):
        return self._loop.run_until_complete(coro)

    def create_collection(self, name, replication_factor=None):
        self._run(
            self._client.create_collection(name, replication_factor)
        )
        return SyncCollection(self, self._client.collection(name))

    def drop_collection(self, name):
        self._run(self._client.drop_collection(name))

    def collection(self, name):
        return SyncCollection(self, self._client.collection(name))

    def get_stats(self, host=None, port=None):
        return self._run(self._client.get_stats(host, port))

    def cluster_stats(self, host=None, port=None):
        return self._run(self._client.cluster_stats(host, port))

    def telemetry_dump(self, host=None, port=None):
        return self._run(self._client.telemetry_dump(host, port))

    def rearm(self, host=None, port=None):
        self._run(self._client.rearm(host, port))

    def close(self):
        self._loop.close()


class SyncCollection:
    def __init__(self, sync_client, collection):
        self._c = sync_client
        self._col = collection

    def set(self, key, value, consistency=None):
        self._c._run(self._col.set(key, value, consistency))

    def get(self, key, consistency=None):
        return self._c._run(self._col.get(key, consistency))

    def cas(self, key, value=None, **kw):
        return self._c._run(self._col.cas(key, value, **kw))

    def rmw(self, key, fn, **kw):
        return self._c._run(self._col.rmw(key, fn, **kw))

    def atomic_batch(self, ops, consistency=None):
        return self._c._run(
            self._col.atomic_batch(ops, consistency)
        )

    def scan(
        self, prefix=None, limit=None, max_bytes=None, filter=None
    ):
        async def collect():
            out = []
            async for kv in self._col.scan(
                prefix, limit, max_bytes, filter=filter
            ):
                out.append(kv)
            return out

        return self._c._run(collect())

    def count(
        self, prefix=None, limit=None, filter=None, aggregate=None
    ):
        return self._c._run(
            self._col.count(
                prefix, limit, filter=filter, aggregate=aggregate
            )
        )

    def delete(self, key, consistency=None):
        self._c._run(self._col.delete(key, consistency))

    def watcher(
        self, filter=None, wait_ms=1000, sub_id=None, cursor=None
    ):
        return SyncWatcher(
            self._c,
            self._col.watcher(
                filter=filter,
                wait_ms=wait_ms,
                sub_id=sub_id,
                cursor=cursor,
            ),
        )


class SyncWatcher:
    """Blocking wrapper over Watcher: each ``next_events()`` call
    pulls one chunk (possibly empty after the server's long-poll)."""

    def __init__(self, sync_client, watcher):
        self._c = sync_client
        self._w = watcher

    def next_events(self):
        return self._c._run(self._w.next_events())

    @property
    def cursor(self):
        return self._w.cursor

    @property
    def monotonicity_violations(self):
        return self._w.monotonicity_violations

    @property
    def dup_flagged(self):
        return self._w.dup_flagged

    @property
    def events_seen(self):
        return self._w.events_seen
