"""Smart client for dbeel_tpu (and wire-compatible with dbeel servers).

Role parity with /root/reference/dbeel_client/src/lib.rs: bootstrap from
seed db addresses, pull cluster metadata, build the client-side hash
ring, route each key to the first ring shard at/after its hash, walk
replicas across distinct nodes injecting ``replica_index``, resync the
ring and retry on ``KeyNotOwnedByShard``, and offer per-op consistency
(fixed / quorum / all).
"""

from __future__ import annotations

import asyncio
import struct
from bisect import bisect_left
from dataclasses import dataclass
from typing import Any, List, Optional, Sequence, Tuple

import msgpack

from ..errors import (
    ConnectionError_,
    DbeelError,
    KeyNotOwnedByShard,
    ProtocolError,
    from_wire,
)
from ..cluster.messages import ClusterMetadata
from ..utils.murmur import hash_bytes, hash_string

RESPONSE_ERR = 0
RESPONSE_OK = 1
RESPONSE_BYTES = 2


class Consistency:
    """dbeel_client/src/lib.rs:465-480."""

    @staticmethod
    def fixed(n: int):
        return ("fixed", n)

    QUORUM = ("quorum", 0)
    ALL = ("all", 0)

    @staticmethod
    def resolve(c, replication_factor: int) -> int:
        kind, n = c
        if kind == "fixed":
            return n
        if kind == "quorum":
            return replication_factor // 2 + 1
        return replication_factor


@dataclass
class _RingShard:
    node_name: str
    hash: int
    ip: str
    db_port: int  # already shard-specific (base + id)


class DbeelClient:
    """``pooled=True`` (default) reuses connections via the keepalive
    protocol extension; pass False for strict reference behavior
    (connect per request)."""

    MAX_POOL_PER_TARGET = 8

    def __init__(
        self,
        seed_addresses: Sequence[Tuple[str, int]],
        pooled: bool = True,
    ):
        self._seeds = list(seed_addresses)
        self._ring: List[_RingShard] = []
        self._ring_hashes: List[int] = []
        self._collections: dict = {}
        self._pooled = pooled
        self._pool: dict = {}  # (host, port) -> [(reader, writer)]

    # -- bootstrap / metadata sync (lib.rs:85-152) ---------------------

    @classmethod
    async def from_seed_nodes(
        cls, addresses: Sequence[Tuple[str, int]]
    ) -> "DbeelClient":
        client = cls(addresses)
        await client.sync_metadata()
        return client

    async def sync_metadata(self) -> None:
        last_error: Optional[Exception] = None
        for host, port in self._seeds:
            try:
                raw = await self._send_to(
                    host, port, {"type": "get_cluster_metadata"}
                )
                metadata = ClusterMetadata.from_wire(
                    msgpack.unpackb(raw, raw=False)
                )
                self._apply_metadata(metadata)
                return
            except (DbeelError, OSError) as e:
                last_error = e
        raise ConnectionError_(
            f"no seed reachable: {last_error!r}"
        )

    def _apply_metadata(self, metadata: ClusterMetadata) -> None:
        ring: List[_RingShard] = []
        for node in metadata.nodes:
            for sid in node.ids:
                ring.append(
                    _RingShard(
                        node_name=node.name,
                        hash=hash_string(f"{node.name}-{sid}"),
                        ip=node.ip,
                        db_port=node.db_port + sid,
                    )
                )
        ring.sort(key=lambda s: s.hash)
        self._ring = ring
        self._ring_hashes = [s.hash for s in ring]
        self._collections = {
            name: rf for name, rf in metadata.collections
        }

    # -- raw protocol --------------------------------------------------

    @staticmethod
    async def _round_trip(reader, writer, request: dict) -> bytes:
        buf = msgpack.packb(request, use_bin_type=True)
        writer.write(struct.pack("<H", len(buf)) + buf)
        await writer.drain()
        header = await reader.readexactly(4)
        (size,) = struct.unpack("<I", header)
        return await reader.readexactly(size)

    async def _send_to(self, host: str, port: int, request: dict) -> bytes:
        """One request/response round trip (u16-len request; u32-len
        response + trailing type byte), over a pooled keepalive
        connection when enabled."""
        payload = None
        if self._pooled:
            request = dict(request)
            request["keepalive"] = True
            key = (host, port)
            while payload is None and self._pool.get(key):
                reader, writer = self._pool[key].pop()
                try:
                    payload = await self._round_trip(
                        reader, writer, request
                    )
                except (OSError, asyncio.IncompleteReadError):
                    writer.close()  # stale pooled conn; try another
                except BaseException:
                    writer.close()  # cancellation etc: don't leak
                    raise
            if payload is not None:
                self._release(key, reader, writer)
        if payload is None:
            reader, writer = await asyncio.open_connection(host, port)
            try:
                payload = await self._round_trip(
                    reader, writer, request
                )
            except BaseException:
                writer.close()
                raise
            if self._pooled:
                self._release((host, port), reader, writer)
            else:
                writer.close()
        if not payload:
            raise ProtocolError("empty response")
        body, rtype = payload[:-1], payload[-1]
        if rtype == RESPONSE_ERR:
            raise from_wire(msgpack.unpackb(body, raw=False))
        return body

    def _release(self, key, reader, writer) -> None:
        pool = self._pool.setdefault(key, [])
        if len(pool) < self.MAX_POOL_PER_TARGET:
            pool.append((reader, writer))
        else:
            writer.close()

    def close(self) -> None:
        for conns in self._pool.values():
            for _r, w in conns:
                w.close()
        self._pool.clear()

    # -- routing (lib.rs:336-417) ---------------------------------------

    def _shards_for_key(self, key_hash: int, rf: int) -> List[_RingShard]:
        """First ring shard at/after the hash, then the next shards on
        distinct nodes — the replica walk."""
        if not self._ring:
            raise ConnectionError_("empty ring; sync_metadata first")
        start = bisect_left(self._ring_hashes, key_hash)
        if start == len(self._ring):
            start = 0
        out: List[_RingShard] = []
        seen_nodes: set = set()
        for off in range(len(self._ring)):
            s = self._ring[(start + off) % len(self._ring)]
            if s.node_name in seen_nodes:
                continue
            seen_nodes.add(s.node_name)
            out.append(s)
            if len(out) >= rf:
                break
        return out

    async def _sharded_request(
        self, key: Any, request: dict, rf: int
    ) -> bytes:
        key_encoded = msgpack.packb(key, use_bin_type=True)
        key_hash = hash_bytes(key_encoded)
        request = dict(request)
        request["hash"] = key_hash

        for attempt in (0, 1):
            replicas = self._shards_for_key(key_hash, max(1, rf))
            last_error: Optional[Exception] = None
            for replica_index, shard in enumerate(replicas):
                request["replica_index"] = replica_index
                try:
                    return await self._send_to(
                        shard.ip, shard.db_port, request
                    )
                except KeyNotOwnedByShard as e:
                    # Stale ring: resync and retry (lib.rs:392-409).
                    last_error = e
                    break
                except (DbeelError, OSError) as e:
                    last_error = e
                    continue
            if attempt == 0 and isinstance(
                last_error, KeyNotOwnedByShard
            ):
                await self.sync_metadata()
                continue
            raise last_error if last_error else ConnectionError_(
                "no replica reachable"
            )
        raise ConnectionError_("unreachable")

    # -- public API (lib.rs:482-619) -------------------------------------

    async def create_collection(
        self, name: str, replication_factor: Optional[int] = None
    ) -> "DbeelCollection":
        request = {"type": "create_collection", "name": name}
        if replication_factor is not None:
            request["replication_factor"] = replication_factor
        host, port = self._seeds[0]
        await self._send_to(host, port, request)
        await self.sync_metadata()
        return self.collection(name)

    async def drop_collection(self, name: str) -> None:
        host, port = self._seeds[0]
        await self._send_to(
            host, port, {"type": "drop_collection", "name": name}
        )
        await self.sync_metadata()

    def collection(self, name: str) -> "DbeelCollection":
        rf = self._collections.get(name, 1)
        return DbeelCollection(self, name, rf)

    async def get_cluster_metadata(self) -> ClusterMetadata:
        host, port = self._seeds[0]
        raw = await self._send_to(
            host, port, {"type": "get_cluster_metadata"}
        )
        return ClusterMetadata.from_wire(msgpack.unpackb(raw, raw=False))


class DbeelCollection:
    def __init__(self, client: DbeelClient, name: str, rf: int):
        self.client = client
        self.name = name
        self.replication_factor = rf

    async def set(
        self, key: Any, value: Any, consistency=None
    ) -> None:
        request = {
            "type": "set",
            "collection": self.name,
            "key": key,
            "value": value,
        }
        if consistency is not None:
            request["consistency"] = Consistency.resolve(
                consistency, self.replication_factor
            )
        await self.client._sharded_request(
            key, request, self.replication_factor
        )

    async def get(self, key: Any, consistency=None) -> Any:
        request = {
            "type": "get",
            "collection": self.name,
            "key": key,
        }
        if consistency is not None:
            request["consistency"] = Consistency.resolve(
                consistency, self.replication_factor
            )
        raw = await self.client._sharded_request(
            key, request, self.replication_factor
        )
        return msgpack.unpackb(raw, raw=False)

    async def delete(self, key: Any, consistency=None) -> None:
        request = {
            "type": "delete",
            "collection": self.name,
            "key": key,
        }
        if consistency is not None:
            request["consistency"] = Consistency.resolve(
                consistency, self.replication_factor
            )
        await self.client._sharded_request(
            key, request, self.replication_factor
        )


class DbeelClientSync:
    """Blocking convenience wrapper (the reference ships a 49-line
    synchronous python client, /root/reference/dbeel.py — this is its
    batteries-included equivalent)."""

    def __init__(self, seed_addresses: Sequence[Tuple[str, int]]):
        import asyncio as _asyncio

        self._loop = _asyncio.new_event_loop()
        self._client = self._run(
            DbeelClient.from_seed_nodes(seed_addresses)
        )

    def _run(self, coro):
        return self._loop.run_until_complete(coro)

    def create_collection(self, name, replication_factor=None):
        self._run(
            self._client.create_collection(name, replication_factor)
        )
        return SyncCollection(self, self._client.collection(name))

    def drop_collection(self, name):
        self._run(self._client.drop_collection(name))

    def collection(self, name):
        return SyncCollection(self, self._client.collection(name))

    def close(self):
        self._loop.close()


class SyncCollection:
    def __init__(self, sync_client, collection):
        self._c = sync_client
        self._col = collection

    def set(self, key, value, consistency=None):
        self._c._run(self._col.set(key, value, consistency))

    def get(self, key, consistency=None):
        return self._c._run(self._col.get(key, consistency))

    def delete(self, key, consistency=None):
        self._c._run(self._col.delete(key, consistency))
