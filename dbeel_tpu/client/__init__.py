"""Smart client for dbeel_tpu (and wire-compatible with dbeel servers).

Role parity with /root/reference/dbeel_client/src/lib.rs: bootstrap from
seed db addresses, pull cluster metadata, build the client-side hash
ring, route each key to the first ring shard at/after its hash, walk
replicas across distinct nodes injecting ``replica_index``, resync the
ring and retry on ``KeyNotOwnedByShard``, and offer per-op consistency
(fixed / quorum / all).
"""

from __future__ import annotations

import asyncio
import random
import struct
from bisect import bisect_left
from dataclasses import dataclass
from typing import Any, List, Optional, Sequence, Tuple

import msgpack

from ..errors import (
    ConnectionError_,
    DbeelError,
    KeyNotOwnedByShard,
    ProtocolError,
    Timeout,
    classify_error,
    from_wire,
    is_retryable_class,
)
from ..cluster.messages import ClusterMetadata
from ..utils.murmur import hash_bytes, hash_string

RESPONSE_ERR = 0
RESPONSE_OK = 1
RESPONSE_BYTES = 2


class Consistency:
    """dbeel_client/src/lib.rs:465-480."""

    @staticmethod
    def fixed(n: int):
        return ("fixed", n)

    QUORUM = ("quorum", 0)
    ALL = ("all", 0)

    @staticmethod
    def resolve(c, replication_factor: int) -> int:
        kind, n = c
        if kind == "fixed":
            return n
        if kind == "quorum":
            return replication_factor // 2 + 1
        return replication_factor


@dataclass
class _RingShard:
    node_name: str
    hash: int
    ip: str
    db_port: int  # already shard-specific (base + id)


class DbeelClient:
    """``pooled=True`` (default) reuses connections via the keepalive
    protocol extension; pass False for strict reference behavior
    (connect per request).

    Failure-aware routing: every keyed op carries a per-op deadline
    budget (``op_deadline_s``).  Connection-class failures walk to the
    next ring replica; an exhausted walk resyncs the ring (churn moves
    ownership) and retries after capped exponential backoff with
    jitter, until the budget runs out.  Benign application outcomes
    (KeyNotFound et al.) are final immediately."""

    MAX_POOL_PER_TARGET = 8
    OP_DEADLINE_S = 10.0
    BACKOFF_BASE_S = 0.02
    BACKOFF_CAP_S = 0.5

    def __init__(
        self,
        seed_addresses: Sequence[Tuple[str, int]],
        pooled: bool = True,
        op_deadline_s: Optional[float] = None,
    ):
        self._seeds = list(seed_addresses)
        self._ring: List[_RingShard] = []
        self._ring_hashes: List[int] = []
        self._collections: dict = {}
        self._pooled = pooled
        self._pool: dict = {}  # (host, port) -> [(reader, writer)]
        self._op_deadline_s = (
            self.OP_DEADLINE_S if op_deadline_s is None else op_deadline_s
        )
        self._rng = random.Random()

    # -- bootstrap / metadata sync (lib.rs:85-152) ---------------------

    @classmethod
    async def from_seed_nodes(
        cls, addresses: Sequence[Tuple[str, int]], **kwargs
    ) -> "DbeelClient":
        client = cls(addresses, **kwargs)
        await client.sync_metadata()
        return client

    async def sync_metadata(self) -> None:
        # Failover: metadata can come from ANY live ring member, not
        # just the configured seeds — a client whose only seed is the
        # dead node would otherwise keep a stale ring forever and
        # bounce on KeyNotOwnedByShard through the whole churn window.
        candidates: List[Tuple[str, int]] = list(self._seeds)
        seen = set(candidates)
        for s in self._ring:
            addr = (s.ip, s.db_port)
            if addr not in seen:
                seen.add(addr)
                candidates.append(addr)
        last_error: Optional[Exception] = None
        for host, port in candidates:
            try:
                # Per-candidate bound: _send_to's bare open_connection
                # would otherwise ride the OS connect timeout
                # (~2 min) on a SYN-black-holed member.
                raw = await asyncio.wait_for(
                    self._send_to(
                        host, port, {"type": "get_cluster_metadata"}
                    ),
                    5.0,
                )
                metadata = ClusterMetadata.from_wire(
                    msgpack.unpackb(raw, raw=False)
                )
                self._apply_metadata(metadata)
                return
            except (
                DbeelError,
                OSError,
                asyncio.IncompleteReadError,
                asyncio.TimeoutError,
            ) as e:
                last_error = e
        raise ConnectionError_(
            f"no seed or ring member reachable: {last_error!r}"
        )

    def _apply_metadata(self, metadata: ClusterMetadata) -> None:
        ring: List[_RingShard] = []
        for node in metadata.nodes:
            for sid in node.ids:
                ring.append(
                    _RingShard(
                        node_name=node.name,
                        hash=hash_string(f"{node.name}-{sid}"),
                        ip=node.ip,
                        db_port=node.db_port + sid,
                    )
                )
        ring.sort(key=lambda s: s.hash)
        self._ring = ring
        self._ring_hashes = [s.hash for s in ring]
        self._collections = {
            name: rf for name, rf in metadata.collections
        }

    # -- raw protocol --------------------------------------------------

    @staticmethod
    async def _round_trip(reader, writer, request: dict) -> bytes:
        buf = msgpack.packb(request, use_bin_type=True)
        writer.write(struct.pack("<H", len(buf)) + buf)
        await writer.drain()
        header = await reader.readexactly(4)
        (size,) = struct.unpack("<I", header)
        return await reader.readexactly(size)

    async def _send_to(self, host: str, port: int, request: dict) -> bytes:
        """One request/response round trip (u16-len request; u32-len
        response + trailing type byte), over a pooled keepalive
        connection when enabled."""
        payload = None
        if self._pooled:
            request = dict(request)
            request["keepalive"] = True
            key = (host, port)
            while payload is None and self._pool.get(key):
                reader, writer = self._pool[key].pop()
                try:
                    payload = await self._round_trip(
                        reader, writer, request
                    )
                except (OSError, asyncio.IncompleteReadError):
                    writer.close()  # stale pooled conn; try another
                except BaseException:
                    writer.close()  # cancellation etc: don't leak
                    raise
            if payload is not None:
                self._release(key, reader, writer)
        if payload is None:
            reader, writer = await asyncio.open_connection(host, port)
            try:
                payload = await self._round_trip(
                    reader, writer, request
                )
            except BaseException:
                writer.close()
                raise
            if self._pooled:
                self._release((host, port), reader, writer)
            else:
                writer.close()
        if not payload:
            raise ProtocolError("empty response")
        body, rtype = payload[:-1], payload[-1]
        if rtype == RESPONSE_ERR:
            raise from_wire(msgpack.unpackb(body, raw=False))
        return body

    def _release(self, key, reader, writer) -> None:
        pool = self._pool.setdefault(key, [])
        if len(pool) < self.MAX_POOL_PER_TARGET:
            pool.append((reader, writer))
        else:
            writer.close()

    def close(self) -> None:
        for conns in self._pool.values():
            for _r, w in conns:
                w.close()
        self._pool.clear()

    # -- routing (lib.rs:336-417) ---------------------------------------

    def _shards_for_key(self, key_hash: int, rf: int) -> List[_RingShard]:
        """First ring shard at/after the hash, then the next shards on
        distinct nodes — the replica walk."""
        if not self._ring:
            raise ConnectionError_("empty ring; sync_metadata first")
        start = bisect_left(self._ring_hashes, key_hash)
        if start == len(self._ring):
            start = 0
        out: List[_RingShard] = []
        seen_nodes: set = set()
        for off in range(len(self._ring)):
            s = self._ring[(start + off) % len(self._ring)]
            if s.node_name in seen_nodes:
                continue
            seen_nodes.add(s.node_name)
            out.append(s)
            if len(out) >= rf:
                break
        return out

    @classmethod
    def _backoff_s(
        cls, attempt: int, rng: random.Random
    ) -> float:
        """Capped exponential backoff with jitter: uniform in
        [d/2, d] for d = min(cap, base * 2^attempt) — bounded above
        by BACKOFF_CAP_S, never zero (no synchronized retry storms
        from many clients hitting one churn event)."""
        shift = min(attempt, 20)  # 1<<unbounded overflows float mult
        d = min(cls.BACKOFF_CAP_S, cls.BACKOFF_BASE_S * (1 << shift))
        return d * (0.5 + 0.5 * rng.random())

    async def _sharded_request(
        self, key: Any, request: dict, rf: int
    ) -> bytes:
        key_encoded = msgpack.packb(key, use_bin_type=True)
        key_hash = hash_bytes(key_encoded)
        request = dict(request)
        request["hash"] = key_hash

        loop = asyncio.get_event_loop()
        deadline = loop.time() + self._op_deadline_s
        attempt = 0
        last_error: Optional[Exception] = None
        while True:
            replicas = self._shards_for_key(key_hash, max(1, rf))
            not_owned = False
            # Sticky per-round transport flag (C walk parity,
            # dbeel_client.cpp): once any replica was unreachable the
            # key's state is UNKNOWN — a later replica's KeyNotFound
            # must not downgrade the op to a final "not found".
            transport_error: Optional[Exception] = None
            for replica_index, shard in enumerate(replicas):
                budget = deadline - loop.time()
                if budget <= 0:
                    break
                request["replica_index"] = replica_index
                # Bound the coordinator's own quorum wait to what is
                # left of OUR budget, so a stalled quorum still
                # leaves room to walk to the next coordinator.
                request["timeout"] = max(
                    100, min(5000, int(budget * 1000))
                )
                try:
                    return await asyncio.wait_for(
                        self._send_to(
                            shard.ip, shard.db_port, request
                        ),
                        budget,
                    )
                except KeyNotOwnedByShard as e:
                    # Stale ring: resync and retry (lib.rs:392-409).
                    last_error = e
                    not_owned = True
                    break
                except asyncio.TimeoutError:
                    # Our own budget expired mid-request: transport-
                    # class (state UNKNOWN) — it must never be
                    # downgraded by another replica's KeyNotFound.
                    if transport_error is None:
                        transport_error = Timeout(
                            f"op deadline ({self._op_deadline_s:.1f}s)"
                            " exhausted"
                        )
                    break
                except (
                    DbeelError,
                    OSError,
                    asyncio.IncompleteReadError,
                ) as e:
                    # Reference walk semantics (lib.rs:368-383): record
                    # and advance — connect refused/reset, a dead
                    # coordinator's quorum-timeout, or an application
                    # error; the next replica may answer.
                    last_error = e
                    if not isinstance(e, DbeelError) or (
                        is_retryable_class(classify_error(e))
                    ):
                        transport_error = e
                    continue
            if transport_error is not None:
                # Unknown state beats any benign outcome seen on OTHER
                # replicas this round — raise/retry the transport
                # error, never the downgraded KeyNotFound.
                last_error = transport_error
            # Walk exhausted.  Application outcomes are final; the
            # infrastructure classes retry after backoff while budget
            # remains — under churn the ring heals in well under an
            # op deadline.
            retryable = not_owned or is_retryable_class(
                classify_error(last_error)
                if last_error is not None
                else None
            )
            if not retryable or loop.time() >= deadline:
                break
            if not_owned or not isinstance(last_error, DbeelError):
                # Ring is stale (wrong owner) or nodes vanished
                # (transport errors): refresh the view before the
                # next round.  Best-effort — with every seed briefly
                # down we keep walking the last known ring.
                try:
                    await asyncio.wait_for(
                        self.sync_metadata(),
                        max(0.05, deadline - loop.time()),
                    )
                except (DbeelError, OSError, asyncio.TimeoutError):
                    pass
            pause = min(
                self._backoff_s(attempt, self._rng),
                max(0.0, deadline - loop.time()),
            )
            if pause > 0:
                await asyncio.sleep(pause)
            attempt += 1
        raise last_error if last_error else ConnectionError_(
            "no replica reachable"
        )

    # -- public API (lib.rs:482-619) -------------------------------------

    async def create_collection(
        self, name: str, replication_factor: Optional[int] = None
    ) -> "DbeelCollection":
        request = {"type": "create_collection", "name": name}
        if replication_factor is not None:
            request["replication_factor"] = replication_factor
        host, port = self._seeds[0]
        await self._send_to(host, port, request)
        await self.sync_metadata()
        return self.collection(name)

    async def drop_collection(self, name: str) -> None:
        host, port = self._seeds[0]
        await self._send_to(
            host, port, {"type": "drop_collection", "name": name}
        )
        await self.sync_metadata()

    def collection(self, name: str) -> "DbeelCollection":
        rf = self._collections.get(name, 1)
        return DbeelCollection(self, name, rf)

    async def get_cluster_metadata(self) -> ClusterMetadata:
        host, port = self._seeds[0]
        raw = await self._send_to(
            host, port, {"type": "get_cluster_metadata"}
        )
        return ClusterMetadata.from_wire(msgpack.unpackb(raw, raw=False))


class DbeelCollection:
    def __init__(self, client: DbeelClient, name: str, rf: int):
        self.client = client
        self.name = name
        self.replication_factor = rf

    async def set(
        self, key: Any, value: Any, consistency=None
    ) -> None:
        request = {
            "type": "set",
            "collection": self.name,
            "key": key,
            "value": value,
        }
        if consistency is not None:
            request["consistency"] = Consistency.resolve(
                consistency, self.replication_factor
            )
        await self.client._sharded_request(
            key, request, self.replication_factor
        )

    async def get(self, key: Any, consistency=None) -> Any:
        request = {
            "type": "get",
            "collection": self.name,
            "key": key,
        }
        if consistency is not None:
            request["consistency"] = Consistency.resolve(
                consistency, self.replication_factor
            )
        raw = await self.client._sharded_request(
            key, request, self.replication_factor
        )
        return msgpack.unpackb(raw, raw=False)

    async def delete(self, key: Any, consistency=None) -> None:
        request = {
            "type": "delete",
            "collection": self.name,
            "key": key,
        }
        if consistency is not None:
            request["consistency"] = Consistency.resolve(
                consistency, self.replication_factor
            )
        await self.client._sharded_request(
            key, request, self.replication_factor
        )


class DbeelClientSync:
    """Blocking convenience wrapper (the reference ships a 49-line
    synchronous python client, /root/reference/dbeel.py — this is its
    batteries-included equivalent)."""

    def __init__(self, seed_addresses: Sequence[Tuple[str, int]]):
        import asyncio as _asyncio

        self._loop = _asyncio.new_event_loop()
        self._client = self._run(
            DbeelClient.from_seed_nodes(seed_addresses)
        )

    def _run(self, coro):
        return self._loop.run_until_complete(coro)

    def create_collection(self, name, replication_factor=None):
        self._run(
            self._client.create_collection(name, replication_factor)
        )
        return SyncCollection(self, self._client.collection(name))

    def drop_collection(self, name):
        self._run(self._client.drop_collection(name))

    def collection(self, name):
        return SyncCollection(self, self._client.collection(name))

    def close(self):
        self._loop.close()


class SyncCollection:
    def __init__(self, sync_client, collection):
        self._c = sync_client
        self._col = collection

    def set(self, key, value, consistency=None):
        self._c._run(self._col.set(key, value, consistency))

    def get(self, key, consistency=None):
        return self._c._run(self._col.get(key, consistency))

    def delete(self, key, consistency=None):
        self._c._run(self._col.delete(key, consistency))
