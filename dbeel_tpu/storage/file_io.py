"""Page-aligned file I/O: cached reader and page-mirroring writer.

Role parity with /root/reference/src/storage_engine/cached_file_reader.rs
:13-89 (page-granular read-through cache over DmaFile) and the write side
of entry_writer.rs (every completed page mirrored into the cache so fresh
SSTables are warm).

The reference reads through glommio ``DmaFile`` (O_DIRECT + io_uring); the
host-runtime equivalent here is positional ``os.pread``/``os.pwrite`` on
page boundaries — the access pattern (aligned whole pages, read-through
cache) is identical, and the native C++ runtime can swap in O_DIRECT
without changing callers.
"""

from __future__ import annotations

import asyncio
import errno
import os
import shutil
import zlib
from typing import Optional, Sequence, Tuple

from ..errors import CorruptedFile
from .entry import PAGE_SIZE
from .page_cache import PartitionPageCache, align_down

# ---------------------------------------------------------------------
# Disk-fault injection seam — the storage-plane twin of
# remote_comm.set_fault: tests arm a fault for every file whose path
# starts with a prefix, and the affected I/O paths (page preads,
# page-mirroring writes, WAL appends/fsyncs, free-space probes)
# misbehave deterministically — bit rot, short reads, EIO, ENOSPC and
# torn closes with no real hardware tricks.  Production never touches
# this: the dict stays empty and the per-call check is one truthiness
# test.  ``DBEEL_DISK_FAULTS="<prefix>=<mode>[,...]"`` pre-arms faults
# at import for subprocess harnesses (chaos_soak --disk-faults).
# ---------------------------------------------------------------------

FAULT_BITFLIP = "bitflip"  # flip one bit in every page read
FAULT_SHORT_READ = "short_read"  # preads return half the bytes
FAULT_EIO_READ = "eio_read"  # preads raise EIO
FAULT_EIO_WRITE = "eio_write"  # writes raise EIO
FAULT_ENOSPC = "enospc"  # writes raise ENOSPC
FAULT_TORN_CLOSE = "torn_close"  # writer close drops the tail page
FAULT_NO_SPACE = "no_space"  # free-space probe reports 0 bytes

_faults: dict = {}  # path prefix -> mode


def set_fault(path_prefix: str, mode: Optional[str]) -> None:
    """Arm ``mode`` for every path under ``path_prefix`` (None
    disarms)."""
    if mode is None:
        _faults.pop(path_prefix, None)
    else:
        _faults[path_prefix] = mode


def clear_faults() -> None:
    _faults.clear()


def fault_for(path: str) -> Optional[str]:
    if not _faults:
        return None
    for prefix, mode in _faults.items():
        if path.startswith(prefix):
            return mode
    return None


def _arm_from_env() -> None:
    spec = os.environ.get("DBEEL_DISK_FAULTS", "")
    for part in spec.split(","):
        if "=" in part:
            prefix, mode = part.rsplit("=", 1)
            if prefix and mode:
                set_fault(prefix, mode)


_arm_from_env()


def check_write_fault(path: str) -> None:
    """Raise the armed write-side fault for ``path``, if any — called
    by the WAL append path and the page-mirroring writer so EIO/ENOSPC
    scenarios inject identically across the Python and native write
    backends."""
    mode = fault_for(path)
    if mode == FAULT_EIO_WRITE:
        raise OSError(errno.EIO, f"[fault] write EIO: {path}")
    if mode == FAULT_ENOSPC:
        raise OSError(
            errno.ENOSPC, f"[fault] no space left on device: {path}"
        )


def free_disk_space(path: str) -> int:
    """Free bytes on the filesystem holding ``path`` (seam-aware:
    FAULT_NO_SPACE reports zero so ENOSPC back-off paths are testable
    without filling a disk)."""
    if fault_for(path) == FAULT_NO_SPACE:
        return 0
    try:
        return shutil.disk_usage(os.path.dirname(path) or ".").free
    except OSError:
        return 1 << 62  # unknown filesystem: never back off on it


def _apply_read_fault(path: str, raw: bytes) -> bytes:
    mode = fault_for(path)
    if mode is None:
        return raw
    if mode == FAULT_EIO_READ:
        raise OSError(errno.EIO, f"[fault] read EIO: {path}")
    if mode == FAULT_SHORT_READ:
        return raw[: len(raw) // 2]
    if mode == FAULT_BITFLIP and raw:
        i = min(len(raw) - 1, PAGE_SIZE // 2)
        flipped = bytearray(raw)
        flipped[i] ^= 0x01
        return bytes(flipped)
    return raw


class CachedFileReader:
    """Read-through page cache over one immutable file.

    ``crcs`` (one CRC32 per 4 KiB page, storage/checksums.py) arms
    verification: every page is checked right after the pread — BEFORE
    it can enter the page cache or reach a caller — and a mismatch
    raises ``CorruptedFile`` (with ``.path`` set for quarantine
    attribution).  Without crcs the reader serves legacy-unverified,
    exactly as before."""

    def __init__(
        self,
        path: str,
        file_id: Tuple[str, int],
        cache: Optional[PartitionPageCache],
        crcs: Optional[Sequence[int]] = None,
    ) -> None:
        self.path = path
        self.file_id = file_id
        self._cache = cache
        self._fd = os.open(path, os.O_RDONLY)
        self.size = os.fstat(self._fd).st_size
        from . import checksums as _ck

        # Held by reference (TableSums owns the array('I')): a large
        # table's CRC arrays must not be duplicated per reader.
        self._crcs = (
            crcs
            if crcs is not None and _ck.verification_enabled()
            else None
        )

    def close(self) -> None:
        if self._fd >= 0:
            os.close(self._fd)
            self._fd = -1

    def __del__(self):  # best-effort fd hygiene
        try:
            self.close()
        except Exception:
            pass

    def _verify_page(self, address: int, raw: bytes) -> None:
        crcs = self._crcs
        if crcs is None:
            return
        i = address // PAGE_SIZE
        if i >= len(crcs) or zlib.crc32(raw) != crcs[i]:
            exc = CorruptedFile(
                f"{self.path}: page at {address} failed its CRC"
            )
            exc.path = self.path
            raise exc

    def read_at(self, pos: int, size: int) -> bytes:
        """cached_file_reader.rs:28-79: walk the range page by page, cache
        hit or aligned read + fill."""
        if size <= 0:
            return b""
        end = min(pos + size, self.size)
        out = bytearray()
        address = align_down(pos)
        while address < end:
            page = self._page(address)
            lo = pos - address if address <= pos else 0
            hi = min(PAGE_SIZE, end - address)
            out += page[lo:hi]
            address += PAGE_SIZE
        return bytes(out)

    def read_all(self) -> bytes:
        return self.read_at(0, self.size)

    def _page(self, address: int) -> bytes:
        if self._cache is not None:
            page = self._cache.get_copied(self.file_id, address)
            if page is not None:
                return page
        raw = self._pread_page(address)
        if self._cache is not None:
            self._cache.set(self.file_id, address, raw)
        return raw

    def _pread_page(self, address: int) -> bytes:
        raw = os.pread(self._fd, PAGE_SIZE, address)
        if _faults:
            raw = _apply_read_fault(self.path, raw)
        if len(raw) < PAGE_SIZE:
            raw = raw + b"\x00" * (PAGE_SIZE - len(raw))
        self._verify_page(address, raw)
        return raw

    def _pread_pages(self, addresses) -> list:
        return [self._pread_page(a) for a in addresses]

    async def _read_pages_async(self, addresses) -> list:
        """Whole pages for every address: io_uring submissions when
        available (each a zero-thread async read), executor preads
        otherwise; partial trailing pages are zero-padded either way."""
        from . import uring

        ur = uring.get_for_loop()
        if ur is not None:
            futs = []  # (address, future)
            fallback = []
            for a in addresses:
                f = ur.queue_pread(self._fd, PAGE_SIZE, a)
                if f is None:  # ring at capacity: executor for these
                    fallback.append(a)
                else:
                    futs.append((a, f))
            if futs and not ur.flush():
                # Kernel rejected the batch: those futures will never
                # complete — cancel them and take the executor path.
                for _a, f in futs:
                    f.cancel()
                fallback.extend(a for a, _f in futs)
                futs = []
            by_addr = {}
            if futs:
                done = await asyncio.gather(*[f for _a, f in futs])
                for (a, _f), r in zip(futs, done):
                    by_addr[a] = r
            if fallback:
                for a, r in zip(
                    fallback,
                    await asyncio.get_event_loop().run_in_executor(
                        None, self._pread_pages, fallback
                    ),
                ):
                    by_addr[a] = r
            out = []
            for a in addresses:
                r = by_addr[a]
                if _faults:
                    r = _apply_read_fault(self.path, r)
                if len(r) < PAGE_SIZE:
                    r = r + b"\x00" * (PAGE_SIZE - len(r))
                # Verify BEFORE the caller can cache or decode it —
                # uring completions bypass _pread_page.
                self._verify_page(a, r)
                out.append(r)
            return out
        return await asyncio.get_event_loop().run_in_executor(
            None, self._pread_pages, addresses
        )

    def read_at_cached(self, pos: int, size: int) -> Optional[bytes]:
        """Cache-only read: the bytes if EVERY page of the range is
        already cached, else None (no disk access, no awaits) — the
        warm-path shortcut that keeps a fully-cached probe synchronous."""
        if size <= 0:
            return b""
        if self._cache is None:
            return None
        end = min(pos + size, self.size)
        out = bytearray()
        address = align_down(pos)
        while address < end:
            page = self._cache.get_copied(self.file_id, address)
            if page is None:
                return None
            lo = pos - address if address <= pos else 0
            hi = min(PAGE_SIZE, end - address)
            out += page[lo:hi]
            address += PAGE_SIZE
        return bytes(out)

    async def read_at_async(self, pos: int, size: int) -> bytes:
        """read_at that never blocks the event loop on disk: cached
        pages are served inline; missing pages are SUBMITTED to the
        loop's io_uring reader (storage/uring.py — true async reads
        with no thread hop, the reference's DmaFile-over-io_uring
        shape, cached_file_reader.rs:28-88) or, when io_uring is
        unavailable, pread in one executor hop.  Cache insertion
        happens back on the loop — cache mutation stays
        loop-confined."""
        if size <= 0:
            return b""
        end = min(pos + size, self.size)
        start = align_down(pos)
        pages = {}
        missing = []
        address = start
        while address < end:
            page = (
                self._cache.get_copied(self.file_id, address)
                if self._cache is not None
                else None
            )
            if page is None:
                missing.append(address)
            else:
                pages[address] = page
            address += PAGE_SIZE
        if missing:
            raws = await self._read_pages_async(missing)
            for address, raw in zip(missing, raws):
                if self._cache is not None:
                    self._cache.set(self.file_id, address, raw)
                pages[address] = raw
        out = bytearray()
        address = start
        while address < end:
            page = pages[address]
            lo = pos - address if address <= pos else 0
            hi = min(PAGE_SIZE, end - address)
            out += page[lo:hi]
            address += PAGE_SIZE
        return bytes(out)


class PageMirroringWriter:
    """Append-only writer that mirrors every completed page into the page
    cache (entry_writer.rs:94-138) and pads the final partial page with
    zeros at close (so files are whole-page sized, as DMA writes are)."""

    def __init__(
        self,
        path: str,
        file_id: Tuple[str, int],
        cache: Optional[PartitionPageCache],
    ) -> None:
        self.path = path
        self.file_id = file_id
        self._cache = cache
        self._fd = os.open(
            path, os.O_WRONLY | os.O_CREAT | os.O_TRUNC, 0o644
        )
        self._buf = bytearray()
        self._flushed = 0  # bytes written to the OS so far (page multiple)
        self.written = 0  # logical bytes appended
        # CRC32 per completed page, accumulated as pages are emitted —
        # the write-side half of the checksum plane (zero extra I/O;
        # the sums sidecar is assembled from these at close by the
        # sstable-writing call sites).
        self.page_crcs: list = []

    def write(self, data: bytes) -> None:
        self._buf += data
        self.written += len(data)
        if len(self._buf) >= PAGE_SIZE:
            whole = len(self._buf) & ~(PAGE_SIZE - 1)
            self._emit(bytes(self._buf[:whole]))
            del self._buf[:whole]

    def _emit(self, chunk: bytes) -> None:
        if _faults:
            check_write_fault(self.path)
        os.pwrite(self._fd, chunk, self._flushed)
        for off in range(0, len(chunk), PAGE_SIZE):
            self.page_crcs.append(
                zlib.crc32(chunk[off : off + PAGE_SIZE])
            )
        if self._cache is not None:
            for off in range(0, len(chunk), PAGE_SIZE):
                self._cache.set(
                    self.file_id,
                    self._flushed + off,
                    chunk[off : off + PAGE_SIZE],
                )
        self._flushed += len(chunk)

    def close(self, sync: bool = True) -> int:
        """Flush the zero-padded tail, truncate to logical size; returns
        logical size."""
        if self._fd < 0:
            return self.written
        if self._buf:
            tail = bytes(self._buf) + b"\x00" * (
                PAGE_SIZE - len(self._buf) % PAGE_SIZE
            ) if len(self._buf) % PAGE_SIZE else bytes(self._buf)
            self._emit(tail)
            self._buf.clear()
        # Pages are written whole (cache mirroring needs that), but the
        # file's logical length is exact so entry counts derive from size.
        os.ftruncate(self._fd, self.written)
        if _faults and fault_for(self.path) == FAULT_TORN_CLOSE:
            # Torn write: the final page vanishes, as if power died
            # between the tail write and the fsync below.
            os.ftruncate(
                self._fd, align_down(max(0, self.written - 1))
            )
        if sync:
            os.fsync(self._fd)
        os.close(self._fd)
        self._fd = -1
        return self.written

    def abort(self) -> None:
        if self._fd >= 0:
            os.close(self._fd)
            self._fd = -1
        try:
            os.unlink(self.path)
        except OSError:
            pass
