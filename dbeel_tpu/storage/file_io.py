"""Page-aligned file I/O: cached reader and page-mirroring writer.

Role parity with /root/reference/src/storage_engine/cached_file_reader.rs
:13-89 (page-granular read-through cache over DmaFile) and the write side
of entry_writer.rs (every completed page mirrored into the cache so fresh
SSTables are warm).

The reference reads through glommio ``DmaFile`` (O_DIRECT + io_uring); the
host-runtime equivalent here is positional ``os.pread``/``os.pwrite`` on
page boundaries — the access pattern (aligned whole pages, read-through
cache) is identical, and the native C++ runtime can swap in O_DIRECT
without changing callers.
"""

from __future__ import annotations

import asyncio
import os
from typing import Optional, Tuple

from .entry import PAGE_SIZE
from .page_cache import PartitionPageCache, align_down


class CachedFileReader:
    """Read-through page cache over one immutable file."""

    def __init__(
        self,
        path: str,
        file_id: Tuple[str, int],
        cache: Optional[PartitionPageCache],
    ) -> None:
        self.path = path
        self.file_id = file_id
        self._cache = cache
        self._fd = os.open(path, os.O_RDONLY)
        self.size = os.fstat(self._fd).st_size

    def close(self) -> None:
        if self._fd >= 0:
            os.close(self._fd)
            self._fd = -1

    def __del__(self):  # best-effort fd hygiene
        try:
            self.close()
        except Exception:
            pass

    def read_at(self, pos: int, size: int) -> bytes:
        """cached_file_reader.rs:28-79: walk the range page by page, cache
        hit or aligned read + fill."""
        if size <= 0:
            return b""
        end = min(pos + size, self.size)
        out = bytearray()
        address = align_down(pos)
        while address < end:
            page = self._page(address)
            lo = pos - address if address <= pos else 0
            hi = min(PAGE_SIZE, end - address)
            out += page[lo:hi]
            address += PAGE_SIZE
        return bytes(out)

    def read_all(self) -> bytes:
        return self.read_at(0, self.size)

    def _page(self, address: int) -> bytes:
        if self._cache is not None:
            page = self._cache.get_copied(self.file_id, address)
            if page is not None:
                return page
        raw = self._pread_page(address)
        if self._cache is not None:
            self._cache.set(self.file_id, address, raw)
        return raw

    def _pread_page(self, address: int) -> bytes:
        raw = os.pread(self._fd, PAGE_SIZE, address)
        if len(raw) < PAGE_SIZE:
            raw = raw + b"\x00" * (PAGE_SIZE - len(raw))
        return raw

    def _pread_pages(self, addresses) -> list:
        return [self._pread_page(a) for a in addresses]

    async def _read_pages_async(self, addresses) -> list:
        """Whole pages for every address: io_uring submissions when
        available (each a zero-thread async read), executor preads
        otherwise; partial trailing pages are zero-padded either way."""
        from . import uring

        ur = uring.get_for_loop()
        if ur is not None:
            futs = []  # (address, future)
            fallback = []
            for a in addresses:
                f = ur.queue_pread(self._fd, PAGE_SIZE, a)
                if f is None:  # ring at capacity: executor for these
                    fallback.append(a)
                else:
                    futs.append((a, f))
            if futs and not ur.flush():
                # Kernel rejected the batch: those futures will never
                # complete — cancel them and take the executor path.
                for _a, f in futs:
                    f.cancel()
                fallback.extend(a for a, _f in futs)
                futs = []
            by_addr = {}
            if futs:
                done = await asyncio.gather(*[f for _a, f in futs])
                for (a, _f), r in zip(futs, done):
                    by_addr[a] = r
            if fallback:
                for a, r in zip(
                    fallback,
                    await asyncio.get_event_loop().run_in_executor(
                        None, self._pread_pages, fallback
                    ),
                ):
                    by_addr[a] = r
            return [
                (
                    r + b"\x00" * (PAGE_SIZE - len(r))
                    if len(r) < PAGE_SIZE
                    else r
                )
                for r in (by_addr[a] for a in addresses)
            ]
        return await asyncio.get_event_loop().run_in_executor(
            None, self._pread_pages, addresses
        )

    def read_at_cached(self, pos: int, size: int) -> Optional[bytes]:
        """Cache-only read: the bytes if EVERY page of the range is
        already cached, else None (no disk access, no awaits) — the
        warm-path shortcut that keeps a fully-cached probe synchronous."""
        if size <= 0:
            return b""
        if self._cache is None:
            return None
        end = min(pos + size, self.size)
        out = bytearray()
        address = align_down(pos)
        while address < end:
            page = self._cache.get_copied(self.file_id, address)
            if page is None:
                return None
            lo = pos - address if address <= pos else 0
            hi = min(PAGE_SIZE, end - address)
            out += page[lo:hi]
            address += PAGE_SIZE
        return bytes(out)

    async def read_at_async(self, pos: int, size: int) -> bytes:
        """read_at that never blocks the event loop on disk: cached
        pages are served inline; missing pages are SUBMITTED to the
        loop's io_uring reader (storage/uring.py — true async reads
        with no thread hop, the reference's DmaFile-over-io_uring
        shape, cached_file_reader.rs:28-88) or, when io_uring is
        unavailable, pread in one executor hop.  Cache insertion
        happens back on the loop — cache mutation stays
        loop-confined."""
        if size <= 0:
            return b""
        end = min(pos + size, self.size)
        start = align_down(pos)
        pages = {}
        missing = []
        address = start
        while address < end:
            page = (
                self._cache.get_copied(self.file_id, address)
                if self._cache is not None
                else None
            )
            if page is None:
                missing.append(address)
            else:
                pages[address] = page
            address += PAGE_SIZE
        if missing:
            raws = await self._read_pages_async(missing)
            for address, raw in zip(missing, raws):
                if self._cache is not None:
                    self._cache.set(self.file_id, address, raw)
                pages[address] = raw
        out = bytearray()
        address = start
        while address < end:
            page = pages[address]
            lo = pos - address if address <= pos else 0
            hi = min(PAGE_SIZE, end - address)
            out += page[lo:hi]
            address += PAGE_SIZE
        return bytes(out)


class PageMirroringWriter:
    """Append-only writer that mirrors every completed page into the page
    cache (entry_writer.rs:94-138) and pads the final partial page with
    zeros at close (so files are whole-page sized, as DMA writes are)."""

    def __init__(
        self,
        path: str,
        file_id: Tuple[str, int],
        cache: Optional[PartitionPageCache],
    ) -> None:
        self.path = path
        self.file_id = file_id
        self._cache = cache
        self._fd = os.open(
            path, os.O_WRONLY | os.O_CREAT | os.O_TRUNC, 0o644
        )
        self._buf = bytearray()
        self._flushed = 0  # bytes written to the OS so far (page multiple)
        self.written = 0  # logical bytes appended

    def write(self, data: bytes) -> None:
        self._buf += data
        self.written += len(data)
        if len(self._buf) >= PAGE_SIZE:
            whole = len(self._buf) & ~(PAGE_SIZE - 1)
            self._emit(bytes(self._buf[:whole]))
            del self._buf[:whole]

    def _emit(self, chunk: bytes) -> None:
        os.pwrite(self._fd, chunk, self._flushed)
        if self._cache is not None:
            for off in range(0, len(chunk), PAGE_SIZE):
                self._cache.set(
                    self.file_id,
                    self._flushed + off,
                    chunk[off : off + PAGE_SIZE],
                )
        self._flushed += len(chunk)

    def close(self, sync: bool = True) -> int:
        """Flush the zero-padded tail, truncate to logical size; returns
        logical size."""
        if self._fd < 0:
            return self.written
        if self._buf:
            tail = bytes(self._buf) + b"\x00" * (
                PAGE_SIZE - len(self._buf) % PAGE_SIZE
            ) if len(self._buf) % PAGE_SIZE else bytes(self._buf)
            self._emit(tail)
            self._buf.clear()
        # Pages are written whole (cache mirroring needs that), but the
        # file's logical length is exact so entry counts derive from size.
        os.ftruncate(self._fd, self.written)
        if sync:
            os.fsync(self._fd)
        os.close(self._fd)
        self._fd = -1
        return self.written

    def abort(self) -> None:
        if self._fd >= 0:
            os.close(self._fd)
            self._fd = -1
        try:
            os.unlink(self.path)
        except OSError:
            pass
