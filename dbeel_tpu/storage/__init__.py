"""L2/L3: I/O substrate + LSM-tree storage engine."""

from .entry import (  # noqa: F401
    ENTRY_HEADER,
    INDEX_ENTRY,
    INDEX_ENTRY_SIZE,
    PAGE_SIZE,
    TOMBSTONE,
    decode_entry,
    encode_entry,
)
from .lsm_tree import LSMTree  # noqa: F401

DEFAULT_TREE_CAPACITY = 8192  # reference storage_engine/mod.rs:18
DEFAULT_SSTABLE_BLOOM_MIN_SIZE = 1 << 20  # mod.rs:19
