"""Write-ahead log with page-padded records and coalesced fdatasync.

Role parity with the reference's WAL (/root/reference/src/storage_engine/
lsm_tree.rs:805-837 write path, 552-574 recovery): every set appends one
record at a page-aligned offset, padded to a whole number of 4 KiB pages;
sync is off by default, immediate with ``wal_sync``, or delay-coalesced
with ``wal_sync_delay`` (many writers share one fdatasync, lsm_tree.rs:
817-832).  Recovery strides the file page by page re-applying records.

Record layout at each page-aligned offset:
    [u32 magic][u32 entry_len][u32 crc32(entry)][u32 reserved][entry bytes]
padded with zeros to the next page boundary.  The crc + magic make torn
tail writes detectable (recovery stops at the first invalid record).
"""

from __future__ import annotations

import asyncio
import heapq
import logging
import os
import struct
import zlib
from collections import deque
from typing import Iterator, Tuple

from .entry import PAGE_SIZE, decode_entry, encode_entry
from ..utils.event import LocalEvent

log = logging.getLogger(__name__)

_MAGIC = 0x77A11065


def hub_fsync_errors() -> "int | None":
    """Process-wide count of FAILED IORING_OP_FSYNC completions in
    the native wal-sync hub (ADVICE r5 low #3): a non-zero value
    means the device rejected syncs and durable acks were held back
    and retried.  None when the native hub (or its counter ABI) is
    unavailable."""
    from . import native as native_mod

    lib = native_mod.load_if_built()
    if lib is None or not hasattr(lib, "dbeel_walsync_errors"):
        return None
    return int(lib.dbeel_walsync_errors())


# Process-wide wal-sync group-commit accounting: how many durable acks
# each completed fdatasync released (the batching win of batched
# multi-ops and pipelined connections, observable in production via
# get_stats, not just in benches).  Updated by BOTH sync backends —
# the native syncer's release pump and the executor-coalesced Python
# fallback — so the metric survives backend A/B flags.
_group_commit = {"syncs": 0, "ops_acked": 0, "max_batch": 0}


def _record_group_commit(released: int) -> None:
    if released <= 0:
        return
    _group_commit["syncs"] += 1
    _group_commit["ops_acked"] += released
    if released > _group_commit["max_batch"]:
        _group_commit["max_batch"] = released


def group_commit_stats() -> dict:
    g = _group_commit
    return {
        "syncs": g["syncs"],
        "ops_acked": g["ops_acked"],
        "max_batch": g["max_batch"],
        "mean_batch": (
            round(g["ops_acked"] / g["syncs"], 2) if g["syncs"] else None
        ),
    }
_HEADER = struct.Struct("<IIII")


class _SyncHub:
    """Process-wide io_uring group-commit hub (zero sync threads).

    Thread-mode wal-sync costs one dedicated fdatasync thread per WAL
    (64 shards/collections => 64 threads) plus a
    cv->thread->eventfd->epoll wake chain per durable ack.  The hub
    (native dbeel_walsync_hub_*) queues IORING_OP_FSYNC SQEs straight
    from the append path on a ring whose registered eventfd the loop
    polls — no threads at all, and fsyncs for different WALs overlap
    in the kernel.  Reference analog: glommio runs the WAL fdatasync
    on the same per-core io_uring reactor
    (/root/reference/src/storage_engine/lsm_tree.rs:805-837).

    Single-threaded contract: all attached WALs append from the one
    loop thread (server run_node / per-shard process / test loop).
    Across sequential loops (tests), the eventfd reader rebinds to
    the currently-running loop on first use."""

    _instance = None  # None = untried, False = unavailable

    def __init__(self, lib, handle) -> None:
        self._lib = lib
        self._h = handle
        self._efd = lib.dbeel_walsync_hub_eventfd(handle)
        self._syncers: set = set()
        self._loop = None

    @classmethod
    def get(cls, lib):
        if cls._instance is None:
            cls._instance = False
            try:
                if hasattr(lib, "dbeel_walsync_hub_new"):
                    h = lib.dbeel_walsync_hub_new(128)
                    if h:
                        cls._instance = cls(lib, h)
            except Exception:
                log.exception("wal sync hub unavailable")
        return cls._instance or None

    def register(self, syncer) -> None:
        self._syncers.add(syncer)
        loop = asyncio.get_event_loop()
        if self._loop is not loop:
            if self._loop is not None:
                try:
                    self._loop.remove_reader(self._efd)
                except Exception:
                    pass  # previous loop already torn down
            self._loop = loop
            loop.add_reader(self._efd, self._on_ready)

    def unregister(self, syncer) -> None:
        self._syncers.discard(syncer)

    def _on_ready(self) -> None:
        try:
            os.read(self._efd, 8)
        except (BlockingIOError, OSError):
            pass
        self._lib.dbeel_walsync_hub_reap(self._h)
        for s in list(self._syncers):
            s._pump()


class _NativeSyncer:
    """Event-loop bridge for native wal-sync group commit.  Two
    backends behind one park/wait/ticket surface:

    * hub mode (preferred): the io_uring _SyncHub above — the fsync
      is a SQE submitted from the append path, completion arrives on
      the hub's shared eventfd, zero threads.
    * thread mode (fallback, no io_uring): a dedicated C thread owns
      the coalesced fdatasync (dbeel_wal_sync_enable) and pings a
      per-WAL eventfd.

    Either way this object parks serving-plane responses and
    slow-path waiters on sync *tickets* (append sequence numbers) and
    releases them once the published watermark covers them — so a
    durable ack never leaves before its fdatasync, and the event loop
    never blocks on one (reference semantics:
    /root/reference/src/storage_engine/lsm_tree.rs:805-837)."""

    def __init__(self, lib, native, delay_us: int, hub=None) -> None:
        self._lib = lib
        self._native = native
        self._hub = hub
        if hub is not None:
            if lib.dbeel_wal_sync_attach(native, hub._h, delay_us) != 0:
                raise OSError("wal sync attach failed")
            self._efd = -1
        else:
            self._efd = os.eventfd(0, os.EFD_NONBLOCK | os.EFD_CLOEXEC)
            if lib.dbeel_wal_sync_enable(
                native, delay_us, self._efd
            ) != 0:
                os.close(self._efd)
                raise OSError("wal sync enable failed")
        self._loop = None
        self._parks: deque = deque()  # (ticket, callback), FIFO==ticket order
        self._waiters: list = []  # heap of (ticket, n, future)
        self._wseq = 0
        self._closed = False
        self._stopping = False
        self._on_done: list = []

    def ticket(self) -> int:
        """Current append sequence — call immediately after the
        append whose durability you need (loop thread only)."""
        return self._lib.dbeel_wal_seq(self._native)

    def _ensure_reader(self) -> None:
        if self._loop is None:
            self._loop = asyncio.get_event_loop()
            if self._hub is not None:
                self._hub.register(self)
            else:
                self._loop.add_reader(self._efd, self._on_ready)

    def park(self, ticket: int, cb) -> None:
        """Run ``cb()`` once a completed sync covers ``ticket``.
        Calls arrive in ticket order (single loop thread)."""
        if self._closed:
            cb()
            return
        self._ensure_reader()
        self._parks.append((ticket, cb))

    async def wait(self, ticket: int) -> None:
        if self._closed:
            return
        if self._lib.dbeel_wal_synced(self._native) >= ticket:
            return
        self._ensure_reader()
        fut = self._loop.create_future()
        self._wseq += 1
        heapq.heappush(self._waiters, (ticket, self._wseq, fut))
        await fut

    def _on_ready(self) -> None:
        try:
            os.read(self._efd, 8)  # clear the eventfd counter
        except (BlockingIOError, OSError):
            pass
        self._pump()

    def _pump(self) -> None:
        """Release everything a completed sync now covers (called
        from the per-WAL eventfd callback in thread mode, from the
        hub dispatcher in hub mode)."""
        self._release(self._lib.dbeel_wal_synced(self._native))
        if self._stopping and not self._closed:
            # Async close handshake: the backend's exit signal (final
            # drain published, watermark == seq) finishes the
            # shutdown here — the disable below then lands on an
            # already-exited thread / empty hub slot, so the loop
            # never blocks on an in-flight usleep/fdatasync.
            seq = self._lib.dbeel_wal_seq(self._native)
            if self._lib.dbeel_wal_synced(self._native) >= seq:
                self._finish_close()

    def _release(self, synced: int) -> None:
        released = 0
        while self._parks and self._parks[0][0] <= synced:
            _, cb = self._parks.popleft()
            released += 1
            try:
                cb()
            except Exception:
                log.exception("parked wal-sync ack release failed")
        while self._waiters and self._waiters[0][0] <= synced:
            _, _, fut = heapq.heappop(self._waiters)
            released += 1
            if not fut.done():
                fut.set_result(None)
        _record_group_commit(released)

    def close(self, on_done=None) -> None:
        """Stop the C sync thread (its final drain covers every
        outstanding append) and release everything parked.  Called
        before the WAL closes — by then flush has made the contents
        durable via the sstable, so releasing is correct even if the
        final fdatasync raced the close.

        When an event-loop reader is active this is ASYNCHRONOUS: the
        stop is signalled, the thread finishes its final drain off
        the loop, and its exit ping completes the shutdown from the
        eventfd callback (the loop never blocks on an in-flight
        usleep/fdatasync — review r4).  ``on_done`` runs after the
        native side is fully released (the WAL uses it to defer
        closing its fd/handle).  Without a reader (no loop engaged)
        it degrades to the synchronous join."""
        if self._closed:
            if on_done is not None:
                on_done()
            return
        if self._stopping:
            if on_done is not None:
                self._on_done.append(on_done)
            return
        if on_done is not None:
            self._on_done.append(on_done)
        # The async handshake needs a LIVE loop to deliver the exit
        # ping; after loop shutdown (process teardown, __del__) fall
        # back to the synchronous join or the native handle, eventfd,
        # and any pending unlink would leak forever.
        if self._loop is not None and self._loop.is_running():
            self._stopping = True
            if hasattr(self._lib, "dbeel_wal_sync_stop_async"):
                self._lib.dbeel_wal_sync_stop_async(self._native)
                return  # _on_ready finishes via the exit ping
        self._finish_close()

    def _finish_close(self) -> None:
        if self._closed:
            return
        self._closed = True
        # Thread mode: joins the sync thread (already exited on the
        # async path — its exit ping got us here).  Hub mode: detaches
        # the slot, draining any straggler SQE.
        self._lib.dbeel_wal_sync_disable(self._native)
        if self._hub is not None:
            self._hub.unregister(self)
        elif self._loop is not None:
            try:
                self._loop.remove_reader(self._efd)
            except Exception:
                pass
        self._release(self._lib.dbeel_wal_seq(self._native))
        if self._efd >= 0:
            os.close(self._efd)
        self._efd = -1
        for cb in self._on_done:
            try:
                cb()
            except Exception:
                log.exception("wal close completion callback failed")
        self._on_done = []


def _padded(n: int) -> int:
    return (n + PAGE_SIZE - 1) & ~(PAGE_SIZE - 1)


def _encode_record(key: bytes, value: bytes, timestamp: int) -> bytes:
    """One page-padded WAL record — the single owner of the on-disk
    framing (magic + length + crc + entry + zero padding), shared by
    the single-append and batch-append Python paths so the two can
    never diverge from what recovery parses."""
    entry = encode_entry(key, value, timestamp)
    record = _HEADER.pack(
        _MAGIC, len(entry), zlib.crc32(entry), 0
    ) + entry
    return record + b"\x00" * (_padded(len(record)) - len(record))


class Wal:
    def __init__(
        self,
        path: str,
        sync: bool = False,
        sync_delay_us: int = 0,
        on_error=None,
    ) -> None:
        self.path = path
        self._sync = sync
        self._sync_delay_us = sync_delay_us
        # Disk-fault escalation hook (degraded mode): called with the
        # OSError when an append or fdatasync hits EIO/ENOSPC — the
        # LSM tree threads it up to the shard, which flips read-only
        # instead of dying mid-pipeline.
        self._on_error = on_error
        self._fd = os.open(path, os.O_RDWR | os.O_CREAT, 0o644)
        # Resume appending after the last *valid* record: a torn tail from
        # a crash must be overwritten, not skipped, or post-recovery
        # appends land beyond the point where replay stops and acked
        # writes become unreachable.
        self._offset = _valid_end(self._fd)
        os.ftruncate(self._fd, self._offset)
        # Native appender (encode + crc + padded pwrite in one C call):
        # it owns the offset while alive, so the serving data plane and
        # this class can interleave appends on one shared counter.
        self._native = None
        self._lib = None
        try:
            from . import native as native_mod

            lib = native_mod.load_if_built()
            if lib is not None and hasattr(lib, "dbeel_wal_new"):
                handle = lib.dbeel_wal_new(self._fd, self._offset)
                if handle:
                    self._native = handle
                    self._lib = lib
        except Exception:
            self._native = None
        self._seq = 0  # appends so far
        self._synced_seq = 0  # appends covered by a completed fdatasync
        self._syncing = False
        self._sync_event = LocalEvent()
        self._inflight_syncs = 0
        self._closing = False
        self._unlink_on_close = False
        self._disposed = False
        self._dispose_future = None
        self._dispose_waiter = None
        self._sync_closing = False
        self._closing_syncer = None
        # Native group-commit syncer — hub mode (io_uring SQEs from
        # the append path, zero threads) with a dedicated-C-thread
        # fallback when io_uring is unavailable.  Either way the
        # serving data plane fast-paths durable writes (acks parked
        # on sync tickets); without any native backend, durable
        # writes punt to the executor-coalesced fdatasync path.
        # DBEEL_NO_WAL_SYNCER=1 disables both native backends;
        # DBEEL_NO_WAL_HUB=1 forces thread mode (A/B benching).
        self._syncer = None
        if (
            sync
            and self._native is not None
            and hasattr(os, "eventfd")
            and os.environ.get("DBEEL_NO_WAL_SYNCER", "0")
            in ("", "0")
        ):
            hub = None
            if os.environ.get("DBEEL_NO_WAL_HUB", "0") in ("", "0"):
                hub = _SyncHub.get(self._lib)
            if hub is not None:
                try:
                    self._syncer = _NativeSyncer(
                        self._lib, self._native, sync_delay_us, hub
                    )
                except Exception:
                    log.exception("wal sync hub attach failed")
                    self._syncer = None
            if self._syncer is None:
                try:
                    if hasattr(self._lib, "dbeel_wal_sync_enable"):
                        self._syncer = _NativeSyncer(
                            self._lib, self._native, sync_delay_us
                        )
                except Exception:
                    log.exception("native wal syncer unavailable")
                    self._syncer = None

    def _report_io_error(self, e: BaseException) -> None:
        if self._on_error is not None:
            try:
                self._on_error(e)
            except Exception:
                log.exception("wal on_error callback failed")

    def _append_record_sync(
        self, key: bytes, value: bytes, timestamp: int
    ) -> None:
        """One record appended, no sync (shared by append and
        append_batch; the native appender owns the offset when
        present).  EIO/ENOSPC surfaces as OSError AND fires the
        on_error escalation hook — both write backends inject
        identically through the file_io fault seam."""
        from . import file_io as _fio

        try:
            if _fio._faults:
                _fio.check_write_fault(self.path)
            if self._native is not None:
                new_off = self._lib.dbeel_wal_append(
                    self._native,
                    key,
                    len(key),
                    value,
                    len(value),
                    timestamp,
                )
                if new_off == 0:
                    raise OSError(
                        f"WAL append failed for {self.path}"
                    )
                self._offset = new_off
            else:
                record = _encode_record(key, value, timestamp)
                os.pwrite(self._fd, record, self._offset)
                self._offset += len(record)
        except OSError as e:
            self._report_io_error(e)
            raise
        self._seq += 1

    async def append(self, key: bytes, value: bytes, timestamp: int) -> None:
        self._append_record_sync(key, value, timestamp)
        await self._maybe_sync()

    async def append_batch(
        self, entries: "list[tuple[bytes, bytes, int]]"
    ) -> None:
        """Append N records, pay ONE durability wait (group commit).
        Record layout on disk is identical to N single appends —
        recovery/replay cannot tell them apart.  Without the native
        appender the records are concatenated into one buffer and land
        in a single pwrite (the writev shape); with it, appends are
        already a few µs of C each and the win is the single shared
        fdatasync ticket below."""
        if not entries:
            return
        if self._native is not None:
            for key, value, ts in entries:
                self._append_record_sync(key, value, ts)
        else:
            blob = b"".join(
                _encode_record(key, value, ts)
                for key, value, ts in entries
            )
            try:
                from . import file_io as _fio

                if _fio._faults:
                    _fio.check_write_fault(self.path)
                os.pwrite(self._fd, blob, self._offset)
            except OSError as e:
                self._report_io_error(e)
                raise
            self._offset += len(blob)
            self._seq += len(entries)
        await self._maybe_sync()

    async def _fdatasync(self) -> None:
        """fdatasync guarded against the flush path closing this WAL while
        a coalesced sync is still in flight (the file's contents are then
        durable via the flushed sstable instead)."""
        if self._closing or self._fd < 0:
            return
        self._inflight_syncs += 1

        def _sync_fd(fd=self._fd, path=self.path):
            from . import file_io as _fio

            if _fio._faults:
                _fio.check_write_fault(path)
            os.fdatasync(fd)

        try:
            await asyncio.get_event_loop().run_in_executor(
                None, _sync_fd
            )
        except OSError as e:
            # Riders are still released (the flush path makes the
            # contents durable via the sstable), but the failure
            # escalates: a device that rejects fsync is exactly the
            # degraded-mode trigger.
            self._report_io_error(e)
        finally:
            self._inflight_syncs -= 1
            if self._closing and self._inflight_syncs == 0:
                self._really_close()

    async def _maybe_sync(self) -> None:
        """Return only once a completed fdatasync covers this writer's
        append.  Writers that arrive while a sync is already in flight
        wait for a *later* sync — riding the in-flight one would ack bytes
        that fdatasync began before they were written
        (coalescing a la lsm_tree.rs:817-832, but watermark-correct)."""
        if not self._sync:
            return
        if self._syncer is not None:
            # Ticket = the native appender's sequence (it counted this
            # append); no await happened since, so it is exactly ours.
            await self._syncer.wait(self._syncer.ticket())
            return
        my_seq = self._seq
        while self._synced_seq < my_seq and not self._closing:
            if self._syncing:
                await self._sync_event.listen()
                continue
            self._syncing = True
            try:
                if self._sync_delay_us > 0:
                    await asyncio.sleep(self._sync_delay_us / 1e6)
                covered = self._seq
                await self._fdatasync()
                _record_group_commit(covered - self._synced_seq)
                self._synced_seq = max(self._synced_seq, covered)
            finally:
                self._syncing = False
                self._sync_event.notify()

    def _really_close(self) -> None:
        if self._native is not None:
            self._lib.dbeel_wal_free(self._native)
            self._native = None
        fd, self._fd = self._fd, -1
        unlink = self._unlink_on_close
        if fd < 0 and not unlink:
            return
        path = self.path

        def _dispose():
            # close() of a WAL with dirty page-cache data and unlink
            # of a page-padded multi-MB file both BLOCK for tens of
            # ms on this filesystem — measured as 27-90ms serving
            # stalls at every memtable rotation (loopwatch stacks
            # pointed exactly here).  Retired-WAL disposal is pure
            # cleanup with no ordering contract beyond the flush
            # being durable (which it is before delete() is called),
            # so it runs on an executor thread when a loop is up.
            # The NEXT flush awaits wait_disposed() before creating
            # its WAL, keeping the on-disk invariant at <= 2 WALs
            # for the recovery protocol.
            try:
                if fd >= 0:
                    try:
                        os.close(fd)
                    except OSError:
                        pass
                if unlink:
                    try:
                        os.unlink(path)
                    except OSError:
                        pass
            finally:
                self._disposed = True

        try:
            loop = asyncio.get_running_loop()
        except RuntimeError:
            _dispose()
            return
        self._dispose_future = loop.run_in_executor(None, _dispose)
        if (
            self._dispose_waiter is not None
            and not self._dispose_waiter.done()
        ):
            self._dispose_waiter.set_result(None)

    def join_disposed(self, timeout: float = 2.0) -> bool:
        """Synchronously wait (bounded) for an IN-FLIGHT off-loop
        disposal — terminal-path helper for LSMTree.close(): an
        in-process close->reopen of the same directory must not race
        recovery's file listing against the retired WAL's executor
        unlink.  Polling is safe only once the executor job exists
        (that thread progresses independently of the caller's loop);
        when disposal hasn't been scheduled yet (async-syncer close
        handshake still pending on loop callbacks) blocking here from
        the loop thread would PREVENT it — return False immediately
        and let recovery's own retry (LSMTree._open) absorb a later
        unlink."""
        import time as _time

        if self._dispose_future is None:
            return self._disposed
        deadline = _time.monotonic() + timeout
        while not self._disposed and _time.monotonic() < deadline:
            _time.sleep(0.002)
        return self._disposed

    async def wait_disposed(self) -> None:
        """Resolve once the off-loop fd close / unlink has finished
        (flush-ordering hook: at most 2 WALs may ever exist on
        disk)."""
        if self._dispose_future is None and not self._disposed:
            # Disposal not scheduled yet (async syncer close still in
            # flight): _really_close resolves this waiter the moment
            # it schedules the executor job.
            if self._dispose_waiter is None:
                self._dispose_waiter = (
                    asyncio.get_running_loop().create_future()
                )
            await self._dispose_waiter
        if self._dispose_future is not None:
            await self._dispose_future

    def close(self) -> None:
        self._closing = True
        if self._sync_closing:
            # Async syncer shutdown already pending: a second close()
            # (__del__, delete()) must NOT free the native handle the
            # in-flight eventfd callback still dereferences — UNLESS
            # the loop has stopped for good, in which case the exit
            # ping will never be delivered and the handshake must be
            # finished synchronously here (the C disable joins the
            # already-exiting thread) or the native handle, eventfd,
            # WAL fd, and a delete()'s unlink all leak (review r4).
            s = self._closing_syncer
            if s is not None and (
                s._loop is None or not s._loop.is_running()
            ):
                self._closing_syncer = None
                s._finish_close()
            return
        if self._syncer is not None:
            # Async shutdown: the C thread's final drain runs off the
            # loop; fd/handle teardown (and file unlink, see delete)
            # defer to its completion callback.  dbeel_wal_free's own
            # sync_disable then joins an already-exited thread.
            self._sync_closing = True
            syncer, self._syncer = self._syncer, None
            self._closing_syncer = syncer
            syncer.close(on_done=self._close_when_unreferenced)
            return
        self._sync_event.notify()  # release riders; contents now owned
        if self._inflight_syncs == 0:  # by the flushed sstable
            self._really_close()

    def _close_when_unreferenced(self) -> None:
        self._sync_closing = False
        self._closing_syncer = None
        self._sync_event.notify()
        if self._inflight_syncs == 0:
            self._really_close()

    def delete(self) -> None:
        self._unlink_on_close = True
        self.close()

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass


def _valid_end(fd: int) -> int:
    """Byte offset just past the last valid record in an open WAL."""
    size = os.fstat(fd).st_size
    buf = os.pread(fd, size, 0)
    offset = 0
    while offset + _HEADER.size <= len(buf):
        magic, entry_len, crc, _ = _HEADER.unpack_from(buf, offset)
        if magic != _MAGIC:
            break
        start = offset + _HEADER.size
        end = start + entry_len
        if end > len(buf) or zlib.crc32(buf[start:end]) != crc:
            break
        offset += _padded(_HEADER.size + entry_len)
    return offset


def replay(path: str) -> Iterator[Tuple[bytes, bytes, int]]:
    """Yield (key, value, timestamp) records; stops at the first hole or
    corrupt record (torn tail write)."""
    try:
        with open(path, "rb") as f:
            buf = f.read()
    except FileNotFoundError:
        return
    offset = 0
    n = len(buf)
    while offset + _HEADER.size <= n:
        magic, entry_len, crc, _ = _HEADER.unpack_from(buf, offset)
        if magic != _MAGIC:
            return
        start = offset + _HEADER.size
        end = start + entry_len
        if end > n:
            return
        entry = buf[start:end]
        if zlib.crc32(entry) != crc:
            return
        key, value, ts, _ = decode_entry(entry)
        yield key, value, ts
        offset += _padded(_HEADER.size + entry_len)
