"""Write-ahead log with page-padded records and coalesced fdatasync.

Role parity with the reference's WAL (/root/reference/src/storage_engine/
lsm_tree.rs:805-837 write path, 552-574 recovery): every set appends one
record at a page-aligned offset, padded to a whole number of 4 KiB pages;
sync is off by default, immediate with ``wal_sync``, or delay-coalesced
with ``wal_sync_delay`` (many writers share one fdatasync, lsm_tree.rs:
817-832).  Recovery strides the file page by page re-applying records.

Record layout at each page-aligned offset:
    [u32 magic][u32 entry_len][u32 crc32(entry)][u32 reserved][entry bytes]
padded with zeros to the next page boundary.  The crc + magic make torn
tail writes detectable (recovery stops at the first invalid record).
"""

from __future__ import annotations

import asyncio
import os
import struct
import zlib
from typing import Iterator, Tuple

from .entry import PAGE_SIZE, decode_entry, encode_entry
from ..utils.event import LocalEvent

_MAGIC = 0x77A11065
_HEADER = struct.Struct("<IIII")


def _padded(n: int) -> int:
    return (n + PAGE_SIZE - 1) & ~(PAGE_SIZE - 1)


class Wal:
    def __init__(
        self,
        path: str,
        sync: bool = False,
        sync_delay_us: int = 0,
    ) -> None:
        self.path = path
        self._sync = sync
        self._sync_delay_us = sync_delay_us
        self._fd = os.open(path, os.O_RDWR | os.O_CREAT, 0o644)
        # Resume appending after the last *valid* record: a torn tail from
        # a crash must be overwritten, not skipped, or post-recovery
        # appends land beyond the point where replay stops and acked
        # writes become unreachable.
        self._offset = _valid_end(self._fd)
        os.ftruncate(self._fd, self._offset)
        # Native appender (encode + crc + padded pwrite in one C call):
        # it owns the offset while alive, so the serving data plane and
        # this class can interleave appends on one shared counter.
        self._native = None
        self._lib = None
        try:
            from . import native as native_mod

            lib = native_mod.load_if_built()
            if lib is not None and hasattr(lib, "dbeel_wal_new"):
                handle = lib.dbeel_wal_new(self._fd, self._offset)
                if handle:
                    self._native = handle
                    self._lib = lib
        except Exception:
            self._native = None
        self._seq = 0  # appends so far
        self._synced_seq = 0  # appends covered by a completed fdatasync
        self._syncing = False
        self._sync_event = LocalEvent()
        self._inflight_syncs = 0
        self._closing = False

    async def append(self, key: bytes, value: bytes, timestamp: int) -> None:
        if self._native is not None:
            new_off = self._lib.dbeel_wal_append(
                self._native, key, len(key), value, len(value), timestamp
            )
            if new_off == 0:
                raise OSError(f"WAL append failed for {self.path}")
            self._offset = new_off
        else:
            entry = encode_entry(key, value, timestamp)
            record = _HEADER.pack(
                _MAGIC, len(entry), zlib.crc32(entry), 0
            ) + entry
            record += b"\x00" * (_padded(len(record)) - len(record))
            os.pwrite(self._fd, record, self._offset)
            self._offset += len(record)
        self._seq += 1
        await self._maybe_sync()

    async def _fdatasync(self) -> None:
        """fdatasync guarded against the flush path closing this WAL while
        a coalesced sync is still in flight (the file's contents are then
        durable via the flushed sstable instead)."""
        if self._closing or self._fd < 0:
            return
        self._inflight_syncs += 1
        try:
            await asyncio.get_event_loop().run_in_executor(
                None, os.fdatasync, self._fd
            )
        except OSError:
            pass
        finally:
            self._inflight_syncs -= 1
            if self._closing and self._inflight_syncs == 0:
                self._really_close()

    async def _maybe_sync(self) -> None:
        """Return only once a completed fdatasync covers this writer's
        append.  Writers that arrive while a sync is already in flight
        wait for a *later* sync — riding the in-flight one would ack bytes
        that fdatasync began before they were written
        (coalescing a la lsm_tree.rs:817-832, but watermark-correct)."""
        if not self._sync:
            return
        my_seq = self._seq
        while self._synced_seq < my_seq and not self._closing:
            if self._syncing:
                await self._sync_event.listen()
                continue
            self._syncing = True
            try:
                if self._sync_delay_us > 0:
                    await asyncio.sleep(self._sync_delay_us / 1e6)
                covered = self._seq
                await self._fdatasync()
                self._synced_seq = max(self._synced_seq, covered)
            finally:
                self._syncing = False
                self._sync_event.notify()

    def _really_close(self) -> None:
        if self._native is not None:
            self._lib.dbeel_wal_free(self._native)
            self._native = None
        if self._fd >= 0:
            os.close(self._fd)
            self._fd = -1

    def close(self) -> None:
        self._closing = True
        self._sync_event.notify()  # release riders; contents now owned
        if self._inflight_syncs == 0:  # by the flushed sstable
            self._really_close()

    def delete(self) -> None:
        self.close()
        try:
            os.unlink(self.path)
        except OSError:
            pass

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass


def _valid_end(fd: int) -> int:
    """Byte offset just past the last valid record in an open WAL."""
    size = os.fstat(fd).st_size
    buf = os.pread(fd, size, 0)
    offset = 0
    while offset + _HEADER.size <= len(buf):
        magic, entry_len, crc, _ = _HEADER.unpack_from(buf, offset)
        if magic != _MAGIC:
            break
        start = offset + _HEADER.size
        end = start + entry_len
        if end > len(buf) or zlib.crc32(buf[start:end]) != crc:
            break
        offset += _padded(_HEADER.size + entry_len)
    return offset


def replay(path: str) -> Iterator[Tuple[bytes, bytes, int]]:
    """Yield (key, value, timestamp) records; stops at the first hole or
    corrupt record (torn tail write)."""
    try:
        with open(path, "rb") as f:
            buf = f.read()
    except FileNotFoundError:
        return
    offset = 0
    n = len(buf)
    while offset + _HEADER.size <= n:
        magic, entry_len, crc, _ = _HEADER.unpack_from(buf, offset)
        if magic != _MAGIC:
            return
        start = offset + _HEADER.size
        end = start + entry_len
        if end > n:
            return
        entry = buf[start:end]
        if zlib.crc32(entry) != crc:
            return
        key, value, ts, _ = decode_entry(entry)
        yield key, value, ts
        offset += _padded(_HEADER.size + entry_len)
