"""Vectorized columnar scan staging (streaming scan plane, PR 12).

The per-entry async tree walk (``LSMTree.iter_filter``) pays
interpreted-Python cost per entry — fine for anti-entropy's bounded
pages, hopeless as the serving path of a client-visible scan.  This
module reuses the columnar trick of the vectorized range digests
(storage/range_digest.py): one bulk index-column read per sstable, one
native murmur batch for the arc filter, and numpy sorting for the
newest-wins merge — then every scan PAGE is a couple of searchsorteds
plus a cumsum over precomputed size columns, with value bytes
materialized ONLY for the entries actually emitted (through the
CRC-verified read path).

Shape:

* ``build_stage(memtable_items, tables)`` — one point-in-time merge of
  every source into key-sorted, newest-wins-deduplicated columns
  (padded fixed-width key matrix, ts/hash/value-size columns).  CPU
  heavy: run it off-loop on a scan snapshot; the owning tree caches
  the result until a write or table-list change invalidates it, so a
  multi-chunk scan stages once.
* ``ScanStage.select(...)`` — pure numpy page selection (arc/hash
  membership, key > start_after, key-prefix window, byte budget):
  returns the chosen positions without touching value bytes, so
  ``count`` and keys-only pushdown never materialize a value.
* ``ScanStage.materialize(...)`` — loop-side value reads for ONE page
  through ``CachedFileReader.read_at`` (page-cache + CRC sidecar
  verification, like every other Python read path).

Returns None (callers fall back to the per-entry path) when the native
murmur batch is unavailable, a table looks torn, keys are wider than
the padding cap, or any key ends in a NUL byte (numpy's fixed-width
bytes dtype strips trailing NULs, which would alias two distinct
keys).  Ordering is raw encoded-key byte order — the storage order —
and numpy 'S' comparison matches Python bytes comparison for
non-NUL-terminated keys (embedded NULs included).
"""

from __future__ import annotations

import zlib
from typing import List, Optional, Sequence, Tuple

import numpy as np

from ..errors import CorruptedFile

# Prefix-window upper bound: one definition, shared with the golden
# evaluator (query.py) so the staged window cut and the per-entry
# reference can never diverge on the all-0xff edge.
from ..query import increment_prefix  # noqa: F401  (re-exported)
from . import checksums
from . import native as native_mod
from .columnar import ranges_to_positions
from .entry import ENTRY_HEADER_SIZE, PAGE_SIZE
from .range_digest import _batch_hash, _Cols, range_members_mask

# Fallback guards: pathological key shapes take the per-entry path
# instead of an unbounded padded matrix.
MAX_KEY_WIDTH = 512
MAX_MATRIX_BYTES = 256 << 20
# Below this many total entries the stage build costs more than the
# per-entry loop it replaces.
MIN_VECTORIZED_ENTRIES = 512

# Per-entry wire overhead charged against the page byte budget (frame
# list headers + ts int), so budget accounting tracks what actually
# crosses the wire, not just raw key/value bytes.
ENTRY_OVERHEAD = 16




class _TableSrc:
    """One staged sstable's value-serving view: the data memmap the
    key columns were gathered from, plus lazy per-4KiB-page CRC
    verification against the .sums sidecar — each page verifies at
    most ONCE per stage lifetime, then values slice straight out of
    the mapping (the per-entry page-cache read_at measured ~6µs/value
    and dominated page cost; a one-shot crc32 per touched page is
    ~1µs/4KiB and upholds the verify-before-serve contract)."""

    __slots__ = ("table", "data", "mv", "crcs", "verified")

    def __init__(self, table, data: np.ndarray) -> None:
        self.table = table
        self.data = data
        # Values slice through a memoryview of the mapping: numpy
        # memmap __getitem__ constructs a fresh memmap object per
        # access (~4.6µs measured); a memoryview slice is ~0.1µs.
        self.mv = memoryview(data) if data.size else memoryview(b"")
        self.crcs = (
            table.sums.data_crcs
            if table.sums is not None
            and checksums.verification_enabled()
            else None
        )
        self.verified = (
            bytearray(
                (data.size + PAGE_SIZE - 1) // PAGE_SIZE
            )
            if self.crcs is not None
            else None
        )

    def _verify_page(self, i: int) -> None:
        start = i * PAGE_SIZE
        raw = bytes(self.mv[start : start + PAGE_SIZE])
        crc = zlib.crc32(raw)
        if len(raw) < PAGE_SIZE:
            crc = zlib.crc32(b"\x00" * (PAGE_SIZE - len(raw)), crc)
        if i >= len(self.crcs) or crc != self.crcs[i]:
            exc = CorruptedFile(
                f"{self.table.data_path}: scan-stage page {i} crc "
                "mismatch"
            )
            exc.path = self.table.data_path
            raise exc
        self.verified[i] = 1

    def value_at(self, off: int, ln: int) -> bytes:
        if self.verified is not None:
            first = off // PAGE_SIZE
            last = (off + ln - 1) // PAGE_SIZE
            for i in range(first, last + 1):
                if not self.verified[i]:
                    self._verify_page(i)
        return bytes(self.mv[off : off + ln])


class ScanStage:
    """Key-sorted, deduplicated columnar view of one tree snapshot."""

    __slots__ = (
        "keys",
        "klen",
        "ts",
        "hash",
        "vlen",
        "src",
        "off",
        "fsz",
        "sources",
        "n",
        "_hold",  # optional ScanSnapshot pinning table refs
        # Query compute plane (PR 13): lazily-built per-field value
        # columns and per-predicate match masks, cached for the
        # stage lifetime like the key matrix (storage/query_vec.py).
        "_field_cols",
        "_mask_cache",
    )

    def __init__(
        self, keys, klen, ts, h, vlen, src, off, fsz, sources
    ) -> None:
        self.keys = keys  # S{w}, ascending
        self.klen = klen  # int64
        self.ts = ts  # int64
        self.hash = h  # uint32 (murmur3_32 of the key)
        self.vlen = vlen  # int64 (0 = tombstone)
        self.src = src  # int32 index into sources
        self.off = off  # int64: record offset (tables) / item index
        self.fsz = fsz  # int64: full record size (tables only)
        self.sources = sources  # SSTable objects; last = memtable items
        self.n = int(keys.size)
        self._hold = None
        self._field_cols: dict = {}
        self._mask_cache: dict = {}

    # -- page selection (pure numpy; executor-safe) --------------------

    def select(
        self,
        start: int,
        end: int,
        start_after: Optional[bytes],
        prefix: Optional[bytes],
        limit: int,
        max_bytes: int,
        with_values: bool,
    ) -> Tuple[np.ndarray, bool]:
        """Positions of the next page (ascending by key) and whether
        more matching entries exist beyond it."""
        lo, hi = 0, self.n
        width = self.keys.dtype.itemsize
        if prefix:
            if len(prefix) > width:
                # Wider than any stored key: nothing can match.
                return np.zeros(0, dtype=np.int64), False
            lo = int(np.searchsorted(self.keys, prefix, side="left"))
            upper = increment_prefix(prefix)
            if upper is not None:
                hi = int(
                    np.searchsorted(self.keys, upper, side="left")
                )
        if start_after is not None:
            # Truncation to the column width keeps > exact: a stored
            # key exceeds a LONGER start_after iff it exceeds its
            # width-byte prefix (equality would make it a strict
            # prefix of start_after, i.e. smaller).
            lo = max(
                lo,
                int(
                    np.searchsorted(
                        self.keys,
                        start_after[:width],
                        side="right",
                    )
                ),
            )
        if lo >= hi:
            return np.zeros(0, dtype=np.int64), False
        member = range_members_mask(self.hash[lo:hi], start, end)
        pos = lo + np.flatnonzero(member)
        total = int(pos.size)
        if total == 0:
            return pos.astype(np.int64), False
        # Clip to the page entry limit BEFORE the size/cumsum work:
        # at most ``limit`` entries can be returned, and computing
        # sizes over every remaining matching entry would make a
        # full scan's total selection cost quadratic in stage size.
        pos = pos[: int(limit)]
        sz = self.klen[pos] + ENTRY_OVERHEAD
        if with_values:
            sz = sz + self.vlen[pos]
        cum = np.cumsum(sz)
        m = int(np.searchsorted(cum, max_bytes, side="left")) + 1
        m = max(1, min(m, int(limit), int(pos.size)))
        return pos[:m].astype(np.int64), m < total

    def select_window(
        self,
        start: int,
        end: int,
        start_after: Optional[bytes],
        prefix: Optional[bytes],
        limit: int,
        max_bytes: int,
    ) -> Tuple[np.ndarray, bool, int]:
        """Filtered-scan window (query compute plane, PR 13): the
        next ``limit``/``max_bytes``-bounded run of arc-member
        positions REGARDLESS of predicate outcome, plus whether more
        exist and the SCANNED byte size of the window.  Unlike
        ``select`` the cut is on bytes *scanned* (key + value + wire
        overhead — the work the filter actually performs), not bytes
        returned: that is what the coordinator bills against
        ``--scan-bytes-per-slice``, and it keeps a 0.01%-selectivity
        page from degenerating into an unbounded walk for one
        matching row.  The window's last key is the resume cover
        even when nothing in it matches."""
        lo, hi = 0, self.n
        width = self.keys.dtype.itemsize
        if prefix:
            if len(prefix) > width:
                return np.zeros(0, dtype=np.int64), False, 0
            lo = int(np.searchsorted(self.keys, prefix, side="left"))
            upper = increment_prefix(prefix)
            if upper is not None:
                hi = int(
                    np.searchsorted(self.keys, upper, side="left")
                )
        if start_after is not None:
            lo = max(
                lo,
                int(
                    np.searchsorted(
                        self.keys,
                        start_after[:width],
                        side="right",
                    )
                ),
            )
        if lo >= hi:
            return np.zeros(0, dtype=np.int64), False, 0
        member = range_members_mask(self.hash[lo:hi], start, end)
        pos = lo + np.flatnonzero(member)
        total = int(pos.size)
        if total == 0:
            return pos.astype(np.int64), False, 0
        pos = pos[: int(limit)]
        sz = self.klen[pos] + ENTRY_OVERHEAD + self.vlen[pos]
        cum = np.cumsum(sz)
        m = int(np.searchsorted(cum, max_bytes, side="left")) + 1
        m = max(1, min(m, int(limit), int(pos.size)))
        return (
            pos[:m].astype(np.int64),
            m < total,
            int(cum[m - 1]),
        )

    # -- materialization (loop-side; verified reads) -------------------

    def key_at(self, p: int) -> bytes:
        # Item access strips trailing NULs — exact for the keys the
        # build guard admits (none end in NUL).
        return bytes(self.keys[p])

    def entries_at(
        self, pos: np.ndarray, with_values: bool
    ) -> list:
        """Wire entries [key, value|nil, ts] for a page's positions,
        column-at-a-time: one ``.tolist()`` per column instead of
        eight numpy scalar indexings per entry (the per-entry form
        measured ~4x slower and dominated page cost).  Live values
        read through the CRC-verified ``read_at`` path — value bytes
        only, no record re-copy; tombstones and keys-only pages read
        nothing."""
        keys = self.keys[pos].tolist()  # S dtype -> python bytes
        ts = self.ts[pos].tolist()
        vlen = self.vlen[pos].tolist()
        if not with_values:
            return [
                [k, b"" if v == 0 else None, t]
                for k, t, v in zip(keys, ts, vlen)
            ]
        src = self.src[pos].tolist()
        off = self.off[pos].tolist()
        klen = self.klen[pos].tolist()
        sources = self.sources
        out = []
        for i, k in enumerate(keys):
            v = vlen[i]
            if v == 0:
                out.append([k, b"", ts[i]])  # tombstone: explicit
                continue
            source = sources[src[i]]
            if isinstance(source, list):  # memtable items
                out.append([k, source[off[i]][1], ts[i]])
            else:
                out.append(
                    [
                        k,
                        source.value_at(
                            off[i] + ENTRY_HEADER_SIZE + klen[i],
                            v,
                        ),
                        ts[i],
                    ]
                )
        return out


def _table_columns(table):
    """(key_cols, entry_off, full_size, vlen) for one sstable, or None
    on a torn view."""
    offs, ks, fs = table.read_index_columns()
    n = offs.size
    empty = np.zeros(0, np.int64)
    if n == 0:
        cols = _Cols(
            np.zeros(0, np.uint8),
            empty,
            np.zeros(0, np.uint32),
            empty.copy(),
        )
        return cols, empty, empty.copy(), empty.copy()
    data = np.memmap(table.data_path, dtype=np.uint8, mode="r")
    if data.size < int(offs[-1]) + ENTRY_HEADER_SIZE + int(ks[-1]):
        return None
    off64 = offs.astype(np.int64)
    tpos = off64[:, None] + np.arange(8, 16, dtype=np.int64)[None, :]
    ts = (
        np.ascontiguousarray(data[tpos].reshape(n, 8))
        .view("<i8")
        .reshape(n)
        .astype(np.int64)
    )
    cols = _Cols(
        data, off64 + ENTRY_HEADER_SIZE, ks.astype(np.uint32), ts
    )
    vlen = (
        fs.astype(np.int64)
        - ENTRY_HEADER_SIZE
        - ks.astype(np.int64)
    )
    return cols, off64, fs.astype(np.int64), vlen


def build_stage(
    memtable_items: Sequence[Tuple[bytes, bytes, int]],
    tables: Sequence,
) -> Optional[ScanStage]:
    """Merge every source into one ScanStage, or None when a guard
    trips (caller falls back to the per-entry scan)."""
    lib = native_mod.load_if_built()
    if lib is None:
        return None

    cols_list: List[_Cols] = []
    off_list: List[np.ndarray] = []
    fsz_list: List[np.ndarray] = []
    vlen_list: List[np.ndarray] = []
    sources: List = []
    for t in tables:
        got = _table_columns(t)
        if got is None:
            return None
        cols, off, fsz, vlen = got
        cols_list.append(cols)
        off_list.append(off)
        fsz_list.append(fsz)
        vlen_list.append(vlen)
        sources.append(_TableSrc(t, cols.data))

    mem = list(memtable_items)
    if mem:
        keys = [k for k, _v, _ts in mem]
        lens = np.array([len(k) for k in keys], dtype=np.uint32)
        moffs = np.zeros(len(keys), dtype=np.int64)
        np.cumsum(lens[:-1], out=moffs[1:])
        blob = np.frombuffer(b"".join(keys), dtype=np.uint8)
        mts = np.array([t for _k, _v, t in mem], dtype=np.int64)
        cols_list.append(_Cols(blob, moffs, lens, mts))
        off_list.append(np.arange(len(mem), dtype=np.int64))
        fsz_list.append(np.zeros(len(mem), dtype=np.int64))
        vlen_list.append(
            np.array([len(v) for _k, v, _ts in mem], dtype=np.int64)
        )
    else:
        z = np.zeros(0, np.int64)
        cols_list.append(
            _Cols(
                np.zeros(0, np.uint8),
                z,
                np.zeros(0, np.uint32),
                z.copy(),
            )
        )
        off_list.append(z.copy())
        fsz_list.append(z.copy())
        vlen_list.append(z.copy())
    sources.append(mem)

    n_total = sum(int(c.key_off.size) for c in cols_list)
    klen_all = np.concatenate(
        [c.key_len.astype(np.int64) for c in cols_list]
    )
    width = int(klen_all.max()) if n_total else 1
    if width > MAX_KEY_WIDTH or n_total * max(1, width) > (
        MAX_MATRIX_BYTES
    ):
        return None

    if n_total == 0:
        z = np.zeros(0, np.int64)
        return ScanStage(
            np.zeros(0, dtype=f"S{max(1, width)}"),
            z,
            z.copy(),
            np.zeros(0, np.uint32),
            z.copy(),
            np.zeros(0, np.int32),
            z.copy(),
            z.copy(),
            sources,
        )

    # Padded key matrix: one gather per source into (n, width) uint8,
    # viewed as a fixed-width bytes column.
    flat = np.zeros(n_total * width, dtype=np.uint8)
    row0 = 0
    for c in cols_list:
        m = int(c.key_off.size)
        if m:
            lens = c.key_len.astype(np.int64)
            dst = ranges_to_positions(
                (row0 + np.arange(m, dtype=np.int64)) * width, lens
            )
            srcpos = ranges_to_positions(c.key_off, lens)
            flat[dst] = c.data[srcpos]
        row0 += m
    keys_all = flat.view(f"S{width}")

    # NUL-terminated keys alias under the S dtype: fall back.
    last = flat.reshape(n_total, width)[
        np.arange(n_total), klen_all - 1
    ]
    if bool((last == 0).any()):
        return None

    ts_all = np.concatenate([c.ts for c in cols_list])
    h_all = np.concatenate(
        [_batch_hash(lib, c, 0) for c in cols_list]
    )
    src_all = np.concatenate(
        [
            np.full(int(c.key_off.size), i, dtype=np.int32)
            for i, c in enumerate(cols_list)
        ]
    )
    off_all = np.concatenate(off_list)
    fsz_all = np.concatenate(fsz_list)
    vlen_all = np.concatenate(vlen_list)

    # Sort ascending by key with ties newest-first (ts desc), then
    # keep the first row of every equal-key run — the newest-wins
    # merge the quorum read path applies per key, done once for the
    # whole snapshot.
    o1 = np.argsort(-ts_all, kind="stable")
    o2 = np.argsort(keys_all[o1], kind="stable")
    order = o1[o2]
    keys_s = keys_all[order]
    first = np.ones(n_total, dtype=bool)
    first[1:] = keys_s[1:] != keys_s[:-1]
    sel = order[first]
    return ScanStage(
        keys_s[first],
        klen_all[sel],
        ts_all[sel],
        h_all[sel],
        vlen_all[sel],
        src_all[sel],
        off_all[sel],
        fsz_all[sel],
        sources,
    )
