"""Persistent secondary index runs — first-class LSM artifacts
(ISSUE 17).

A selective ``scan(filter=)`` on a value field used to scan every
live row server-side: the query compute plane (PR 13) made the
*wire* cheap, but keys-matched/s stayed bounded by raw scan
bandwidth.  This module gives each SSTable an optional ``.fidx``
*index run* for the collection's declared index fields, built INLINE
at flush/compaction time from the writer's still-resident buffers
(the PR 15 single-pass discipline: zero extra data-byte reads), and
a planner that turns an indexed cmp/prefix/range predicate into a
candidate-row mask so the scan path exact-evaluates only candidates
inside the unchanged ``select_window`` windows — covers, scanned
accounting and results stay byte-identical to the non-indexed path.

Run format (little-endian, self-checking)::

    [u32 magic][u16 version][u16 n_fields]
    per field:
        [u16 name_len][name utf-8]
        [u32 n_num][u32 n_bytes]
        [n_num f64 values, ascending]    [n_num u64 data offsets]
        [n_bytes S16 prefixes, ascending][n_bytes u64 data offsets]
    [u32 crc32 of everything before]

Two lanes per field mirror the golden evaluator's typing rules
(query._leaf_cmp): numeric operands compare only against numeric
values (NUM lane: float64, huge ints clamped to ±inf so one-sided
intervals still cover them; NaN never matches a plannable op and is
dropped), str/bytes operands compare bytewise (BYTES lane: the first
16 value bytes, NUL-padded — numpy 'S' order IS that padded bytewise
order).  Rows whose document lacks the field / holds a bool or
non-scalar live in NEITHER lane: they match no leaf, so excluding
them is sound.  Lane intervals are widened outward (nextafter /
prefix truncation slack), making every candidate set a SUPERSET of
the true matches — the planner re-checks candidates with the golden
``query.match_entry``, so a lossy lane can cost speed, never
correctness.

Crash safety / integrity: runs carry a ``.fidx_sums`` page-CRC
sidecar (checksums.py format, index lane empty), compaction outputs
are written as ``compact_fidx`` and renamed by the same action
journal as the data triplet, and ``SSTable.paths()`` includes the
run so it retires/quarantines in lockstep with its data.  A run that
fails verification is quarantined ALONE (moved aside, error raised
retryably) — the data triplet keeps serving and the retried scan
plans without the run.  A torn run with no valid sidecar demotes to
"absent" (legacy semantics), like a torn ``.sums``.
"""

from __future__ import annotations

import logging
import os
import struct
import threading
import zlib
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from .. import query as Q
from ..errors import CorruptedFile
from . import checksums
from .entry import (
    COMPACT_FIDX_FILE_EXT,
    COMPACT_FIDX_SUMS_FILE_EXT,
    FIDX_FILE_EXT,
    FIDX_SUMS_FILE_EXT,
    file_name,
)

log = logging.getLogger(__name__)

_MAGIC = 0x5846_4449  # "IDFX" little-endian tag
_VERSION = 1
_HEADER = struct.Struct("<IHH")
_FIELD_HDR = struct.Struct("<II")  # n_num, n_bytes
_TRAILER = struct.Struct("<I")

# Byte-lane prefix width: 16 bytes covers realistic scalar values
# and keeps a 1M-entry lane at 24 MB; longer values fall back to
# prefix-interval candidates plus the exact re-check.
PREFIX_WIDTH = 16

# Numeric values beyond float64's finite range clamp to ±inf so
# one-sided intervals still capture them (float() would raise).
_F64_HUGE = 8.98846567431158e307 * 2  # ~max float64

# Planner decision rule: when more than this fraction of staged rows
# are candidates, a full vectorized evaluation is cheaper than
# per-candidate golden re-checks — decline (planner miss).
MAX_CANDIDATE_FRACTION = 0.25

# Bound per-field lane cardinality per run: a run is per-SSTable, so
# this is a sanity ceiling against a corrupt header, not a policy.
_MAX_LANE = 1 << 28


class IndexStats:
    """Process-wide secondary-index accounting (``get_stats.index``):
    build/merge emission, planner outcomes, and the quarantine
    counter the corruption tests assert on."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self.runs_built = 0
        self.runs_merged = 0
        self.entries_indexed = 0
        self.bytes_written = 0
        self.planner_hits = 0
        self.planner_misses = 0
        self.intervals_emitted = 0
        self.runs_quarantined = 0

    def note_emit(
        self, compact: bool, entries: int, nbytes: int
    ) -> None:
        with self._lock:
            if compact:
                self.runs_merged += 1
            else:
                self.runs_built += 1
            self.entries_indexed += int(entries)
            self.bytes_written += int(nbytes)

    def note_plan(self, hit: bool, intervals: int = 0) -> None:
        with self._lock:
            if hit:
                self.planner_hits += 1
            else:
                self.planner_misses += 1
            self.intervals_emitted += int(intervals)

    def note_quarantine(self) -> None:
        with self._lock:
            self.runs_quarantined += 1

    def stats(self) -> dict:
        with self._lock:
            return {
                "runs_built": self.runs_built,
                "runs_merged": self.runs_merged,
                "entries_indexed": self.entries_indexed,
                "bytes_written": self.bytes_written,
                "planner_hits": self.planner_hits,
                "planner_misses": self.planner_misses,
                "intervals_emitted": self.intervals_emitted,
                "runs_quarantined": self.runs_quarantined,
            }


index_stats = IndexStats()


def sanitize_index_fields(raw) -> Optional[List[str]]:
    """Normalize a DDL/metadata/gossip index declaration into a
    sorted, deduplicated field-name list, or None (no indexes).
    Silently drops junk entries instead of erroring: declarations
    ride gossip frames from peers of any version."""
    if not isinstance(raw, (list, tuple)):
        return None
    out = []
    for f in raw:
        if isinstance(f, bytes):
            try:
                f = f.decode("utf-8")
            except UnicodeDecodeError:
                continue
        if (
            isinstance(f, str)
            and f
            and f != Q.KEY_FIELD
            and len(f) <= 256
        ):
            out.append(f)
    out = sorted(set(out))
    return out[:16] or None


# ---------------------------------------------------------------------
# Extraction + serialization (runs off-loop in flush/merge workers)
# ---------------------------------------------------------------------


def _pad_prefix(b: bytes) -> bytes:
    return b[:PREFIX_WIDTH].ljust(PREFIX_WIDTH, b"\x00")


def build_run_blob(
    fields: Sequence[str],
    rows: Sequence[Tuple[int, bytes]],
) -> Tuple[bytes, int]:
    """Serialize one index run from ``(data_offset, value_bytes)``
    rows (tombstones may be included; they are skipped).  The rows
    come from RAM-resident flush/merge buffers — this function never
    reads a data file.  Returns (blob, entries_indexed)."""
    per_field: Dict[str, tuple] = {
        f: ([], [], [], []) for f in fields
    }
    entries = 0
    for off, value in rows:
        if not value:
            continue  # tombstone: matches nothing
        doc = Q.decode_doc(value)
        if doc is None:
            continue
        for f in fields:
            x = Q.field_value(doc, f)
            if x is None:
                continue
            nv, no, bv, bo = per_field[f]
            if isinstance(x, (int, float)):
                try:
                    xf = float(x)
                except OverflowError:
                    xf = (
                        float("inf") if x > 0 else float("-inf")
                    )
                if xf != xf:  # NaN: matches no plannable op
                    continue
                if xf > _F64_HUGE:
                    xf = float("inf")
                elif xf < -_F64_HUGE:
                    xf = float("-inf")
                nv.append(xf)
                no.append(off)
            else:
                xb = (
                    x.encode("utf-8")
                    if isinstance(x, str)
                    else x
                )
                bv.append(_pad_prefix(xb))
                bo.append(off)
            entries += 1
    parts = [_HEADER.pack(_MAGIC, _VERSION, len(fields))]
    for f in fields:
        nv, no, bv, bo = per_field[f]
        name = f.encode("utf-8")
        parts.append(struct.pack("<H", len(name)))
        parts.append(name)
        parts.append(_FIELD_HDR.pack(len(nv), len(bv)))
        if nv:
            va = np.asarray(nv, dtype="<f8")
            oa = np.asarray(no, dtype="<u8")
            order = np.argsort(va, kind="stable")
            parts.append(va[order].tobytes())
            parts.append(oa[order].tobytes())
        if bv:
            va = np.array(bv, dtype=f"S{PREFIX_WIDTH}")
            oa = np.asarray(bo, dtype="<u8")
            order = np.argsort(va, kind="stable")
            parts.append(va[order].tobytes())
            parts.append(oa[order].tobytes())
    body = b"".join(parts)
    return body + _TRAILER.pack(zlib.crc32(body)), entries


def run_paths(
    dir_path: str, index: int, compact: bool = False
) -> Tuple[str, str]:
    """(run path, sidecar path) for a table index."""
    if compact:
        exts = (COMPACT_FIDX_FILE_EXT, COMPACT_FIDX_SUMS_FILE_EXT)
    else:
        exts = (FIDX_FILE_EXT, FIDX_SUMS_FILE_EXT)
    return (
        os.path.join(dir_path, file_name(index, exts[0])),
        os.path.join(dir_path, file_name(index, exts[1])),
    )


def emit_run(
    dir_path: str,
    index: int,
    fields: Sequence[str],
    rows: Sequence[Tuple[int, bytes]],
    compact: bool,
) -> int:
    """Build + write one index run and its CRC sidecar next to the
    (compact_) triplet at ``index``.  Returns bytes written.  Called
    from flush/merge workers with the output rows still in RAM —
    the single-pass contract: the sidecar CRCs are computed from the
    resident blob, never from a re-read."""
    blob, entries = build_run_blob(fields, rows)
    path, _sums = run_paths(dir_path, index, compact)
    with open(path, "wb") as f:
        f.write(blob)
    checksums.write(
        dir_path,
        index,
        checksums.page_crcs(blob),
        [],
        len(blob),
        None,
        ext=(
            COMPACT_FIDX_SUMS_FILE_EXT
            if compact
            else FIDX_SUMS_FILE_EXT
        ),
    )
    index_stats.note_emit(compact, entries, len(blob))
    return len(blob)


def rows_from_items(items) -> List[Tuple[int, bytes]]:
    """(offset, value) rows for the flush path from sorted memtable
    ``items`` ([(key, (value, ts)), ...]) — offsets are the running
    data-record offsets the EntryWriter/native writer produce for the
    same order, so no file is read back."""
    rows: List[Tuple[int, bytes]] = []
    off = 0
    for key, (value, _ts) in items:
        rows.append((off, value))
        off += 16 + len(key) + len(value)
    return rows


# ---------------------------------------------------------------------
# Load + verify
# ---------------------------------------------------------------------


class IndexRun:
    """Parsed run: per field, the two sorted lanes + parallel data
    offsets."""

    __slots__ = ("fields",)

    def __init__(self, fields: dict) -> None:
        # {name: (num_vals f64, num_offs u64, byte_vals S16,
        #         byte_offs u64)}
        self.fields = fields


def _parse_run(blob: bytes, path: str) -> IndexRun:
    if len(blob) < _HEADER.size + _TRAILER.size:
        raise ValueError("fidx too short")
    (crc,) = _TRAILER.unpack_from(blob, len(blob) - 4)
    if zlib.crc32(blob[:-4]) != crc:
        raise ValueError("fidx failed its own checksum")
    magic, version, n_fields = _HEADER.unpack_from(blob, 0)
    if magic != _MAGIC:
        raise ValueError("bad fidx magic")
    if version != _VERSION:
        raise ValueError(f"unknown fidx version {version}")
    off = _HEADER.size
    end = len(blob) - 4
    fields: dict = {}
    for _ in range(n_fields):
        if off + 2 > end:
            raise ValueError("fidx truncated in field header")
        (nlen,) = struct.unpack_from("<H", blob, off)
        off += 2
        name = blob[off : off + nlen].decode("utf-8")
        off += nlen
        if off + _FIELD_HDR.size > end:
            raise ValueError("fidx truncated in lane counts")
        n_num, n_bytes = _FIELD_HDR.unpack_from(blob, off)
        off += _FIELD_HDR.size
        if n_num > _MAX_LANE or n_bytes > _MAX_LANE:
            raise ValueError("fidx lane count implausible")
        need = n_num * 16 + n_bytes * (PREFIX_WIDTH + 8)
        if off + need > end:
            raise ValueError("fidx truncated in lanes")
        nv = np.frombuffer(blob, dtype="<f8", count=n_num, offset=off)
        off += n_num * 8
        no = np.frombuffer(blob, dtype="<u8", count=n_num, offset=off)
        off += n_num * 8
        bv = np.frombuffer(
            blob, dtype=f"S{PREFIX_WIDTH}", count=n_bytes, offset=off
        )
        off += n_bytes * PREFIX_WIDTH
        bo = np.frombuffer(blob, dtype="<u8", count=n_bytes, offset=off)
        off += n_bytes * 8
        fields[name] = (nv, no, bv, bo)
    if off != end:
        raise ValueError("fidx trailing garbage")
    return IndexRun(fields)


def load_run(dir_path: str, index: int) -> Optional[IndexRun]:
    """Load + verify one table's index run.  Returns None when no
    run exists or a torn write demoted it (no valid sidecar AND a
    failed self-check); raises CorruptedFile (``.path`` stamped on
    the run file) when the run is present but PROVABLY corrupt — a
    valid sidecar disagrees with the bytes, or the sidecar validates
    while the body's trailer doesn't."""
    path, _sums_p = run_paths(dir_path, index)
    try:
        with open(path, "rb") as f:
            blob = f.read()
    except OSError:
        return None
    sums = checksums.load(dir_path, index, FIDX_SUMS_FILE_EXT)
    verified = False
    if sums is not None and checksums.verification_enabled():
        got = checksums.page_crcs(blob)
        ok = len(blob) == sums.data_size and len(got) == len(
            sums.data_crcs
        ) and all(g == e for g, e in zip(got, sums.data_crcs))
        if not ok:
            exc = CorruptedFile(
                f"{path}: index run failed sidecar CRC verification"
            )
            exc.path = path
            raise exc
        verified = True
    try:
        return _parse_run(blob, path)
    except ValueError as e:
        if verified:
            # The bytes match their sidecar yet don't parse: the
            # run was written corrupt — same containment as a body
            # CRC failure.
            exc = CorruptedFile(f"{path}: {e}")
            exc.path = path
            raise exc from e
        # Torn write (crash between run and sidecar): demote to
        # absent, like a torn .sums — never an error.
        log.warning("ignoring torn index run %s: %s", path, e)
        return None


# ---------------------------------------------------------------------
# Planner: predicate tree -> candidate row mask over a ScanStage
# ---------------------------------------------------------------------


def _num_interval(lane: np.ndarray, lo, hi) -> Tuple[int, int]:
    """[i0, i1) slice of the sorted NUM lane covering every value in
    the CLOSED interval [lo, hi] (None = open end), pre-widened by
    one ulp each side so float64 rounding of stored ints can never
    exclude a true match."""
    i0 = 0
    i1 = lane.size
    if lo is not None:
        i0 = int(
            np.searchsorted(
                lane, np.nextafter(lo, -np.inf), side="left"
            )
        )
    if hi is not None:
        i1 = int(
            np.searchsorted(
                lane, np.nextafter(hi, np.inf), side="right"
            )
        )
    return i0, max(i0, i1)


def _bytes_interval(
    lane: np.ndarray, lo: Optional[bytes], hi: Optional[bytes]
) -> Tuple[int, int]:
    """[i0, i1) slice of the sorted BYTES lane covering every stored
    prefix in the CLOSED padded interval [lo, hi] (None = open
    end)."""
    i0 = 0
    i1 = lane.size
    if lo is not None:
        i0 = int(np.searchsorted(lane, lo, side="left"))
    if hi is not None:
        i1 = int(np.searchsorted(lane, hi, side="right"))
    return i0, max(i0, i1)


def _leaf_lane_offsets(run_field, node):
    """Candidate data offsets (unsorted u64 arrays) in one run for
    one plannable leaf, or None when the leaf cannot be narrowed
    (the caller treats every row of that source as a candidate).
    Returns (list_of_offset_arrays, intervals_count)."""
    nv, no, bv, bo = run_field
    kind = node[0]
    if kind == "cmp":
        op, operand = node[2], node[3]
        if op == "!=":
            return None
        if isinstance(operand, (int, float)):
            try:
                vf = float(operand)
            except OverflowError:
                return None
            if vf != vf:
                return [], 0  # NaN operand matches nothing
            if op == "==":
                i0, i1 = _num_interval(nv, vf, vf)
            elif op in ("<", "<="):
                i0, i1 = _num_interval(nv, None, vf)
            else:  # > >=
                i0, i1 = _num_interval(nv, vf, None)
            return [no[i0:i1]], 1
        xb = (
            operand.encode("utf-8")
            if isinstance(operand, str)
            else bytes(operand)
        )
        p = _pad_prefix(xb)
        if op == "==":
            i0, i1 = _bytes_interval(bv, p, p)
        elif op in ("<", "<="):
            i0, i1 = _bytes_interval(bv, None, p)
        else:
            i0, i1 = _bytes_interval(bv, p, None)
        return [bo[i0:i1]], 1
    if kind == "prefix":
        p = node[2]
        if len(p) > PREFIX_WIDTH:
            q = _pad_prefix(p)
            i0, i1 = _bytes_interval(bv, q, q)
            return [bo[i0:i1]], 1
        lo = _pad_prefix(p)
        upper = Q.increment_prefix(p)
        if upper is None:
            i0, i1 = _bytes_interval(bv, lo, None)
        else:
            i0 = int(np.searchsorted(bv, lo, side="left"))
            i1 = int(
                np.searchsorted(bv, _pad_prefix(upper), side="left")
            )
            i1 = max(i0, i1)
        return [bo[i0:i1]], 1
    if kind == "range":
        lo, hi = node[2], node[3]
        if lo is None and hi is None:
            # Matches any scalar-typed value: both full lanes.
            return [no, bo], 2
        if isinstance(lo, (int, float)) or isinstance(
            hi, (int, float)
        ):
            try:
                i0, i1 = _num_interval(
                    nv,
                    float(lo) if lo is not None else None,
                    float(hi) if hi is not None else None,
                )
            except OverflowError:
                return None
            return [no[i0:i1]], 1
        i0, i1 = _bytes_interval(
            bv,
            _pad_prefix(lo) if lo is not None else None,
            _pad_prefix(hi) if hi is not None else None,
        )
        return [bo[i0:i1]], 1
    return None


class _PlanCtx:
    __slots__ = ("intervals", "narrowed")

    def __init__(self) -> None:
        self.intervals = 0
        self.narrowed = False


def _leaf_mask(stage, node, runs_by_src, index_fields, ctx):
    """Candidate mask for one leaf, or None (no narrowing: the leaf
    is on $key / an unindexed field / an unplannable op — every row
    remains a candidate, which is always a sound superset)."""
    field = node[1]
    if field == Q.KEY_FIELD or field not in index_fields:
        return None
    mask = np.zeros(stage.n, dtype=bool)
    any_narrow = False
    for s, source in enumerate(stage.sources):
        rows = np.flatnonzero(stage.src == np.int32(s))
        if rows.size == 0:
            continue
        if isinstance(source, list):
            mask[rows] = True  # memtable rows: no run, exact-check
            continue
        run = runs_by_src.get(s)
        rf = run.fields.get(field) if run is not None else None
        if rf is None:
            mask[rows] = True  # no run / field absent from run
            continue
        got = _leaf_lane_offsets(rf, node)
        if got is None:
            mask[rows] = True
            continue
        lanes, n_iv = got
        ctx.intervals += n_iv
        any_narrow = True
        for offs in lanes:
            if offs.size == 0:
                continue
            cand = np.sort(offs)
            o = stage.off[rows].astype(np.uint64)
            j = np.searchsorted(cand, o)
            j = np.minimum(j, cand.size - 1)
            hit = cand[j] == o
            mask[rows[hit]] = True
    if not any_narrow:
        return None
    ctx.narrowed = True
    return mask


def _tree_mask(stage, where, runs_by_src, index_fields, ctx):
    kind = where[0]
    if kind == "and":
        m = None
        for c in where[1:]:
            cm = _tree_mask(
                stage, c, runs_by_src, index_fields, ctx
            )
            if cm is not None:
                m = cm if m is None else (m & cm)
        return m
    if kind == "or":
        m = None
        for c in where[1:]:
            cm = _tree_mask(
                stage, c, runs_by_src, index_fields, ctx
            )
            if cm is None:
                return None  # one unnarrowed branch floods the or
            m = cm.copy() if m is None else (m | cm)
        return m
    return _leaf_mask(stage, where, runs_by_src, index_fields, ctx)


def candidate_mask(
    stage, where, runs_by_src: dict, index_fields: Sequence[str]
):
    """Superset candidate mask over ``stage`` rows for ``where``,
    or None when the indexes cannot narrow the predicate (planner
    miss).  ``runs_by_src`` maps stage source position -> IndexRun
    (missing/None entries mean "no usable run": their rows stay
    candidates).  Every returned candidate set is a superset of the
    true matches — the caller must exact-evaluate candidates with
    query.match_entry."""
    if where is None or not index_fields:
        index_stats.note_plan(False)
        return None
    ctx = _PlanCtx()
    mask = _tree_mask(stage, where, runs_by_src, index_fields, ctx)
    if mask is None or not ctx.narrowed:
        index_stats.note_plan(False)
        return None
    frac = float(mask.mean()) if stage.n else 1.0
    if frac > MAX_CANDIDATE_FRACTION:
        index_stats.note_plan(False, ctx.intervals)
        return None
    index_stats.note_plan(True, ctx.intervals)
    return mask
