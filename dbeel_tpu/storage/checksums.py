"""Per-block CRC32 integrity sidecar for SSTable triplets.

The WAL already frames every record with a CRC (wal.py), but SSTable
data/index/bloom bytes used to be trusted verbatim: one flipped bit was
either served to clients as corrupt msgpack or crashed the read path
with an unclassified struct/msgpack error.  This module gives every
table a ``<index>.sums`` sidecar holding one CRC32 per 4 KiB page of
the data and index files (computed over the zero-padded page, exactly
what both the page-mirroring writer emits and the padded pread
returns) plus a whole-file CRC for the bloom filter.

Why a sidecar and not in-band framing: the data/index layouts are
load-bearing far beyond the Python reader — the native C flush/merge
writers produce them byte-identically (golden-tested), compaction
columnarizes whole files via ``np.frombuffer``, the sparse read index
``np.memmap``s them, and entry counts derive from file size.
Interleaving CRCs would fork every one of those paths (and the C
writers with them); a self-checksummed sidecar keeps the primary
format frozen while still verifying every page before it enters the
page cache.  A corrupted sidecar is detected by its own trailer CRC
and demotes the table to legacy-unverified instead of quarantining
good data.

Versioning: the sidecar ends in a fixed-size footer
``[magic][version][data_size][data_pages][index_pages][bloom_crc]
[flags][crc32-of-everything-before]``.  Tables with no sidecar (or an
unreadable one) are *legacy*: they open read-only-as-ever and serve
unverified, so a pre-checksum store upgrades in place — new flushes
and every compaction output gain sums, so the whole store converges
to verified as it churns.

``DBEEL_NO_CHECKSUMS=1`` disables verification (bench baseline /
emergency escape hatch); sums are still written.
"""

from __future__ import annotations

import logging
import os
import struct
import zlib
from typing import List, Optional, Sequence

from .entry import (
    COMPACT_SUMS_FILE_EXT,
    PAGE_SIZE,
    SUMS_FILE_EXT,
    file_name,
)

log = logging.getLogger(__name__)

__all__ = [
    "SUMS_FILE_EXT",
    "COMPACT_SUMS_FILE_EXT",
    "TableSums",
    "page_crcs",
    "page_count",
    "verification_enabled",
    "load",
    "write",
    "write_crcs",
    "compute_and_write",
    "sums_path",
]

_MAGIC = 0x5C5C_C12C
_VERSION = 1
# magic, version, data_size, data_pages, index_pages, bloom_crc, flags
_FOOTER = struct.Struct("<IIQIIII")
_FLAG_HAS_BLOOM = 1
_TRAILER = struct.Struct("<I")  # crc32 of everything before it


def verification_enabled() -> bool:
    return os.environ.get("DBEEL_NO_CHECKSUMS", "0") in ("", "0")


def page_count(size: int) -> int:
    return (size + PAGE_SIZE - 1) // PAGE_SIZE


def page_crcs(buf, logical_size: Optional[int] = None) -> List[int]:
    """CRC32 per 4 KiB page of ``buf`` (zero-padded final page).
    ``logical_size`` trims a buffer that carries trailing garbage
    (e.g. a memmap of a file that grew)."""
    mv = memoryview(buf)
    if logical_size is not None:
        mv = mv[:logical_size]
    n = len(mv)
    out: List[int] = []
    for off in range(0, n, PAGE_SIZE):
        page = mv[off : off + PAGE_SIZE]
        crc = zlib.crc32(page)
        if len(page) < PAGE_SIZE:
            crc = zlib.crc32(b"\x00" * (PAGE_SIZE - len(page)), crc)
        out.append(crc)
    return out


class TableSums:
    """Parsed sidecar: per-page CRCs for the data and index files and
    a whole-file CRC for the bloom."""

    __slots__ = (
        "version",
        "data_size",
        "data_crcs",
        "index_crcs",
        "bloom_crc",
        "has_bloom",
    )

    def __init__(
        self,
        data_size: int,
        data_crcs: Sequence[int],
        index_crcs: Sequence[int],
        bloom_crc: int = 0,
        has_bloom: bool = False,
        version: int = _VERSION,
    ) -> None:
        self.version = version
        self.data_size = data_size
        # Kept as handed in (array('I') from deserialize, plain lists
        # from the write side) — readers only index, never mutate.
        self.data_crcs = data_crcs
        self.index_crcs = index_crcs
        self.bloom_crc = bloom_crc
        self.has_bloom = has_bloom

    # -- serialization -------------------------------------------------

    def serialize(self) -> bytes:
        body = b"".join(
            crc.to_bytes(4, "little")
            for crc in (*self.data_crcs, *self.index_crcs)
        )
        footer = _FOOTER.pack(
            _MAGIC,
            self.version,
            self.data_size,
            len(self.data_crcs),
            len(self.index_crcs),
            self.bloom_crc,
            _FLAG_HAS_BLOOM if self.has_bloom else 0,
        )
        blob = body + footer
        return blob + _TRAILER.pack(zlib.crc32(blob))

    @classmethod
    def deserialize(cls, blob: bytes) -> "TableSums":
        """Raises ValueError on any malformation (caller demotes the
        table to legacy-unverified)."""
        fixed = _FOOTER.size + _TRAILER.size
        if len(blob) < fixed:
            raise ValueError("sums file too short")
        (trailer_crc,) = _TRAILER.unpack_from(blob, len(blob) - 4)
        if zlib.crc32(blob[:-4]) != trailer_crc:
            raise ValueError("sums file failed its own checksum")
        magic, version, data_size, ndata, nindex, bloom_crc, flags = (
            _FOOTER.unpack_from(blob, len(blob) - fixed)
        )
        if magic != _MAGIC:
            raise ValueError("bad sums magic")
        if version != _VERSION:
            # Forward compatibility: an unknown version is not
            # corruption — the caller treats the table as legacy.
            raise ValueError(f"unknown sums version {version}")
        if 4 * (ndata + nindex) != len(blob) - fixed:
            raise ValueError("sums body size mismatch")
        # One C-level parse into typed arrays (a large table has ~1M
        # page CRCs: a per-4-byte Python loop plus list-of-int
        # overhead would cost real loop-thread time and ~30 MB per
        # copy at SSTable open).  The readers index these arrays
        # without copying.
        import sys
        from array import array

        crcs = array("I")
        crcs.frombytes(blob[: 4 * (ndata + nindex)])
        if sys.byteorder != "little":
            crcs.byteswap()
        return cls(
            data_size,
            crcs[:ndata],
            crcs[ndata:],
            bloom_crc,
            bool(flags & _FLAG_HAS_BLOOM),
            version,
        )

    # -- verification helpers ------------------------------------------

    def verify_buffer(self, kind: str, buf, logical_size: int) -> bool:
        """Whole-file check for the bulk read paths (compaction
        columnarize, dense read-index build)."""
        expect = self.data_crcs if kind == "data" else self.index_crcs
        got = page_crcs(buf, logical_size)
        # expect may be array('I') (a list == array compare is always
        # False): compare element-wise.
        return len(got) == len(expect) and all(
            g == e for g, e in zip(got, expect)
        )


def sums_path(dir_path: str, index: int, ext: str = SUMS_FILE_EXT) -> str:
    return os.path.join(dir_path, file_name(index, ext))


def load(
    dir_path: str, index: int, ext: str = SUMS_FILE_EXT
) -> Optional[TableSums]:
    """Sidecar for a live table, or None (legacy/unverified — missing
    file, short file, failed self-check, unknown version)."""
    path = sums_path(dir_path, index, ext)
    try:
        with open(path, "rb") as f:
            blob = f.read()
    except OSError:
        return None
    try:
        return TableSums.deserialize(blob)
    except ValueError as e:
        log.warning("ignoring invalid sums sidecar %s: %s", path, e)
        return None


def write(
    dir_path: str,
    index: int,
    data_crcs: Sequence[int],
    index_crcs: Sequence[int],
    data_size: int,
    bloom_bytes: Optional[bytes] = None,
    ext: str = SUMS_FILE_EXT,
) -> None:
    """Write a sums sidecar (ext=COMPACT_SUMS_FILE_EXT for compaction
    outputs, renamed into place by the action journal).

    Deliberately NOT fsynced: the sidecar is self-validating (trailer
    CRC), so a crash that tears it just demotes the table to
    legacy-unverified on reopen — correctness never depends on its
    durability, and an extra fsync per flush is a measurable tail cost
    on this filesystem (~30 ms each)."""
    sums = TableSums(
        data_size,
        data_crcs,
        index_crcs,
        zlib.crc32(bloom_bytes) if bloom_bytes is not None else 0,
        bloom_bytes is not None,
    )
    path = sums_path(dir_path, index, ext)
    with open(path, "wb") as f:
        f.write(sums.serialize())


def write_crcs(
    dir_path: str,
    index: int,
    data_crcs: Sequence[int],
    index_crcs: Sequence[int],
    data_size: int,
    bloom_crc: int = 0,
    has_bloom: bool = False,
    ext: str = SUMS_FILE_EXT,
) -> None:
    """Write a sums sidecar from PRE-COMPUTED page CRCs — the
    single-pass compaction/flush path (ISSUE 15): the native writers
    accumulate the per-page CRCs (and the bloom whole-file CRC) as
    they emit bytes, so the sidecar costs zero re-reads.  Byte-
    identical to ``write()`` given the same inputs (one serializer,
    golden-tested against ``compute_and_write``)."""
    sums = TableSums(
        data_size, data_crcs, index_crcs, bloom_crc, has_bloom
    )
    path = sums_path(dir_path, index, ext)
    with open(path, "wb") as f:
        f.write(sums.serialize())


def _file_page_crcs(path: str) -> "tuple[list, int]":
    """(page CRCs, logical size) of a whole file, streamed in 4 MiB
    chunks so a multi-GB compaction output never needs a second
    whole-file resident copy."""
    crcs: List[int] = []
    size = 0
    with open(path, "rb") as f:
        while True:
            chunk = f.read(4 << 20)  # page-multiple chunk size
            if not chunk:
                break
            size += len(chunk)
            crcs.extend(page_crcs(chunk))
    return crcs, size


def compute_and_write(
    dir_path: str,
    index: int,
    data_path: str,
    index_path: str,
    bloom_path: str,
    ext: str = SUMS_FILE_EXT,
) -> None:
    """Post-hoc sidecar for a triplet written by a native (C) writer —
    the files are read back page by page (they are OS-cache-hot right
    after the write) and summed.  Runs off-loop (flush/compaction
    executor jobs)."""
    data_crcs, data_size = _file_page_crcs(data_path)
    index_crcs, _ = _file_page_crcs(index_path)
    bloom_bytes = None
    try:
        with open(bloom_path, "rb") as f:
            bloom_bytes = f.read()
    except OSError:
        pass
    write(
        dir_path,
        index,
        data_crcs,
        index_crcs,
        data_size,
        bloom_bytes,
        ext,
    )
