"""Bloom filter for SSTables.

Role parity with the reference's use of the ``bloomfilter`` crate at 1% FP
(/root/reference/src/storage_engine/lsm_tree.rs:44-50, 1026-1034): one
filter per sufficiently-large SSTable, checked before the index binary
search on reads.

Double hashing (Kirsch–Mitzenmacher): bit_i = (h1 + i*h2) mod m with two
murmur3_32 seeds.  ``add_batch`` vectorizes the build over all keys of an
SSTable with numpy, which is how the device compaction path rebuilds
blooms for merged outputs without a per-key Python loop.
"""

from __future__ import annotations

import math
import struct
from typing import Iterable, Optional

import numpy as np

from ..utils.murmur import murmur3_32, murmur3_32_batch

_SEED1 = 0x9747B28C
_SEED2 = 0x85EBCA6B

_HEADER = struct.Struct("<QII")  # num_bits, num_hashes, reserved


class BloomFilter:
    def __init__(self, num_bits: int, num_hashes: int) -> None:
        self.num_bits = max(64, int(num_bits))
        self.num_hashes = max(1, int(num_hashes))
        self.bits = np.zeros((self.num_bits + 7) // 8, dtype=np.uint8)

    @classmethod
    def with_capacity(
        cls, n_items: int, fp_rate: float = 0.01
    ) -> "BloomFilter":
        n = max(1, n_items)
        m = int(-n * math.log(fp_rate) / (math.log(2) ** 2)) + 1
        k = max(1, round(m / n * math.log(2)))
        return cls(m, k)

    def _indices(self, key: bytes) -> np.ndarray:
        h1 = murmur3_32(key, _SEED1)
        h2 = murmur3_32(key, _SEED2) | 1
        i = np.arange(self.num_hashes, dtype=np.uint64)
        return (np.uint64(h1) + i * np.uint64(h2)) % np.uint64(self.num_bits)

    def add(self, key: bytes) -> None:
        idx = self._indices(key)
        np.bitwise_or.at(
            self.bits, (idx >> np.uint64(3)).astype(np.int64),
            np.left_shift(1, (idx & np.uint64(7)).astype(np.int64)).astype(
                np.uint8
            ),
        )

    def add_batch(self, keys: Iterable[bytes]) -> None:
        keys = list(keys)
        if not keys:
            return
        h1 = murmur3_32_batch(keys, _SEED1).astype(np.uint64)
        h2 = (murmur3_32_batch(keys, _SEED2) | 1).astype(np.uint64)
        i = np.arange(self.num_hashes, dtype=np.uint64)[None, :]
        idx = (h1[:, None] + i * h2[:, None]) % np.uint64(self.num_bits)
        idx = idx.ravel()
        np.bitwise_or.at(
            self.bits, (idx >> np.uint64(3)).astype(np.int64),
            np.left_shift(1, (idx & np.uint64(7)).astype(np.int64)).astype(
                np.uint8
            ),
        )

    def check(self, key: bytes) -> bool:
        idx = self._indices(key)
        byte = self.bits[(idx >> np.uint64(3)).astype(np.int64)]
        bit = (byte >> (idx & np.uint64(7)).astype(np.uint8)) & 1
        return bool(bit.all())

    def serialize(self) -> bytes:
        return (
            _HEADER.pack(self.num_bits, self.num_hashes, 0)
            + self.bits.tobytes()
        )

    @classmethod
    def deserialize(cls, buf: bytes) -> Optional["BloomFilter"]:
        if len(buf) < _HEADER.size:
            return None
        num_bits, num_hashes, _ = _HEADER.unpack_from(buf, 0)
        bf = cls(num_bits, num_hashes)
        body = np.frombuffer(buf, dtype=np.uint8, offset=_HEADER.size)
        if body.size != bf.bits.size:
            return None
        bf.bits = body.copy()
        return bf
