"""ctypes bindings for the C++ native runtime (native/).

Builds ``native/build/libdbeel_native.so`` on first use (make) and
exposes NativeMergeStrategy — the reference-grade CPU k-way heap merge
(the honest CPU baseline for BASELINE.md's ≥5x target) with native
bloom building — plus a murmur3_32 parity hook used by tests.

Everything degrades gracefully to the pure-Python/numpy implementations
when no C++ toolchain is available (get_strategy('native') then
resolves to the columnar strategy).
"""

from __future__ import annotations

import ctypes
import logging
import os
import subprocess
from typing import Optional

import numpy as np

from .bloom import BloomFilter, _SEED1, _SEED2
from .compaction import (
    CompactionStrategy,
    MergeResult,
    _write_bloom,
)
from .entry import (
    COMPACT_DATA_FILE_EXT,
    COMPACT_INDEX_FILE_EXT,
    file_name,
)
from .file_io import PageMirroringWriter

log = logging.getLogger(__name__)

# Outputs at/above this size write through the C++ O_DIRECT streamer
# instead of the page-mirroring Python writer.
ODIRECT_MIN_BYTES = 64 << 20

_NATIVE_DIR = os.path.join(
    os.path.dirname(os.path.dirname(os.path.dirname(__file__))), "native"
)
_DEFAULT_LIB_PATH = os.path.join(
    _NATIVE_DIR, "build", "libdbeel_native.so"
)
# DBEEL_NATIVE_SO selects an alternate prebuilt library — the
# sanitizer workflow loads build/libdbeel_native_asan.so (made via
# `make SANITIZE=asan`) this way.  An explicit override is loaded
# as-is: no staleness check, no rebuild (rebuilding would clobber an
# instrumented binary with a plain one mid-run).
_LIB_PATH = os.environ.get("DBEEL_NATIVE_SO") or _DEFAULT_LIB_PATH
_LIB_OVERRIDDEN = _LIB_PATH != _DEFAULT_LIB_PATH

_lib: Optional[ctypes.CDLL] = None
_tried = False

# C-side latency-class hook: the heap merge calls back into Python
# every TICK_EVERY popped entries so the BgThrottle can yield CPU to
# serving (the callback re-acquires the GIL; at this stride the cost
# is noise — ~15 calls per million entries).
TICK_FN = ctypes.CFUNCTYPE(None)
_MERGE_TICK_EVERY = 65536
# Chunk size for throttle-ticked merge IO (reads of input runs and
# O_DIRECT writes of the merged output): small enough that the
# BgThrottle can pace the virtio-queue burst against serving, large
# enough to keep near-sequential disk bandwidth.
_IO_CHUNK_BYTES = 16 << 20


def _load() -> Optional[ctypes.CDLL]:
    global _lib, _tried
    if _lib is not None or _tried:
        if _lib is None and _LIB_OVERRIDDEN:
            # The override's loud-failure contract must hold for
            # EVERY caller, not just the first: with DBEEL_NATIVE_SO
            # set, all failure paths below raise, so a latched
            # (_tried, no lib) state can only mean a prior failure —
            # re-raising keeps later tests in the same process from
            # silently degrading to the Python paths.
            raise RuntimeError(
                f"DBEEL_NATIVE_SO={_LIB_PATH} failed to load "
                "earlier in this process"
            )
        return _lib
    _tried = True
    def _src_mtime() -> float:
        """Newest .cpp under native/src drives staleness."""
        src_dir = os.path.join(_NATIVE_DIR, "src")
        try:
            return max(
                os.path.getmtime(os.path.join(src_dir, f))
                for f in os.listdir(src_dir)
                if f.endswith(".cpp")
            )
        except (OSError, ValueError):
            return 0.0

    stale = (
        not _LIB_OVERRIDDEN
        and os.path.exists(_LIB_PATH)
        and os.path.getmtime(_LIB_PATH) < _src_mtime()
    )
    if not _LIB_OVERRIDDEN and (
        not os.path.exists(_LIB_PATH) or stale
    ):
        # Rebuild BEFORE the first dlopen: ctypes.CDLL caches by path,
        # so a stale library loaded once cannot be swapped in-process.
        # Serialized under an flock: with --processes every shard
        # process races through here at startup, and the lock makes
        # the others wait for one build instead of compiling N times
        # (the Makefile's atomic rename already guarantees nobody can
        # dlopen a half-written library).
        try:
            import fcntl

            os.makedirs(
                os.path.join(_NATIVE_DIR, "build"), exist_ok=True
            )
            lock_path = os.path.join(_NATIVE_DIR, "build", ".lock")
            with open(lock_path, "w") as lock_f:
                fcntl.flock(lock_f, fcntl.LOCK_EX)
                # Re-check under the lock: another process may have
                # just finished the same rebuild.
                stale = os.path.exists(_LIB_PATH) and os.path.getmtime(
                    _LIB_PATH
                ) < _src_mtime()
                if not os.path.exists(_LIB_PATH) or stale:
                    subprocess.run(
                        ["make", "-C", _NATIVE_DIR, "-B"] if stale
                        else ["make", "-C", _NATIVE_DIR],
                        check=True,
                        capture_output=True,
                        timeout=120,
                    )
        except Exception as e:
            log.info("native build unavailable: %s", e)
            if not os.path.exists(_LIB_PATH):
                return None
    try:
        lib = ctypes.CDLL(_LIB_PATH)
    except OSError as e:
        if _LIB_OVERRIDDEN:
            # An explicit DBEEL_NATIVE_SO that does not load is an
            # operator error: degrading silently would run a
            # "sanitized" suite against no native code at all (the
            # broken-.so-means-green failure tier1.sh exists to
            # prevent).
            raise RuntimeError(
                f"DBEEL_NATIVE_SO={_LIB_PATH} failed to load: {e}"
            ) from e
        log.info("native lib load failed: %s", e)
        return None
    if not hasattr(lib, "dbeel_writer_open") or not hasattr(
        lib, "dbeel_write_file"
    ):
        if _LIB_OVERRIDDEN:
            # Same loud-failure contract as the dlopen branch above:
            # an explicit override that loads but predates the ABI
            # would silently run "native" suites against pure Python.
            raise RuntimeError(
                f"DBEEL_NATIVE_SO={_LIB_PATH} loaded but lacks the "
                "pipeline ABI (dbeel_writer_open/dbeel_write_file) — "
                "stale or wrong-branch build"
            )
        # Still stale (rebuild failed / old binary pinned): degrade to
        # the pure-Python paths rather than crash on registration.
        log.warning(
            "native library at %s predates the pipeline API; "
            "falling back to host merges", _LIB_PATH
        )
        return None

    u8p = ctypes.POINTER(ctypes.c_uint8)
    lib.dbeel_murmur3_32.restype = ctypes.c_uint32
    lib.dbeel_murmur3_32.argtypes = [
        ctypes.c_char_p,
        ctypes.c_uint64,
        ctypes.c_uint32,
    ]
    lib.dbeel_murmur3_32_batch.restype = None
    lib.dbeel_murmur3_32_batch.argtypes = [
        ctypes.c_char_p,
        ctypes.POINTER(ctypes.c_uint64),
        ctypes.POINTER(ctypes.c_uint32),
        ctypes.c_uint64,
        ctypes.c_uint32,
        ctypes.POINTER(ctypes.c_uint32),
    ]
    lib.dbeel_read_file.restype = ctypes.c_int64
    lib.dbeel_read_file.argtypes = [
        ctypes.c_char_p,
        u8p,
        ctypes.c_uint64,
    ]
    lib.dbeel_write_file.restype = ctypes.c_int64
    lib.dbeel_write_file.argtypes = [
        ctypes.c_char_p,
        u8p,
        ctypes.c_uint64,
    ]
    if hasattr(lib, "dbeel_read_file_cb"):
        lib.dbeel_read_file_cb.restype = ctypes.c_int64
        lib.dbeel_read_file_cb.argtypes = [
            ctypes.c_char_p,
            u8p,
            ctypes.c_uint64,
            TICK_FN,
            ctypes.c_uint64,
        ]
        lib.dbeel_write_file_cb.restype = ctypes.c_int64
        lib.dbeel_write_file_cb.argtypes = [
            ctypes.c_char_p,
            u8p,
            ctypes.c_uint64,
            TICK_FN,
            ctypes.c_uint64,
        ]
    if hasattr(lib, "dbeel_stage_prefixes"):
        lib.dbeel_stage_prefixes.restype = None
        lib.dbeel_stage_prefixes.argtypes = [
            u8p,
            ctypes.c_uint64,
            ctypes.POINTER(ctypes.c_uint64),
            ctypes.POINTER(ctypes.c_uint32),
            ctypes.c_uint64,
            ctypes.c_uint64,
            u8p,
        ]
    lib.dbeel_writer_open.restype = ctypes.c_void_p
    lib.dbeel_writer_open.argtypes = [ctypes.c_char_p, ctypes.c_char_p]
    lib.dbeel_writer_put.restype = ctypes.c_int64
    lib.dbeel_writer_put.argtypes = [
        ctypes.c_void_p,
        ctypes.POINTER(u8p),
        ctypes.POINTER(ctypes.c_uint32),
        ctypes.POINTER(ctypes.c_uint64),
        ctypes.POINTER(ctypes.c_uint32),
        ctypes.POINTER(ctypes.c_uint32),
        ctypes.c_uint64,
    ]
    lib.dbeel_writer_close.restype = ctypes.c_int64
    lib.dbeel_writer_close.argtypes = [
        ctypes.c_void_p,
        ctypes.POINTER(ctypes.c_uint64),
    ]
    lib.dbeel_writer_abort.restype = None
    lib.dbeel_writer_abort.argtypes = [ctypes.c_void_p]
    if hasattr(lib, "dbeel_writer_sync"):
        lib.dbeel_writer_sync.restype = None
        lib.dbeel_writer_sync.argtypes = [ctypes.c_void_p]
    if hasattr(lib, "dbeel_writer_open2"):
        # Single-pass sidecar gather writer (ISSUE 15): per-page CRCs
        # accumulated as bytes are emitted, handed back at close so
        # the .sums sidecar costs zero re-reads.  Gated together with
        # close2 — one build ships both.
        lib.dbeel_writer_open2.restype = ctypes.c_void_p
        lib.dbeel_writer_open2.argtypes = [
            ctypes.c_char_p,
            ctypes.c_char_p,
            ctypes.c_int32,
        ]
        lib.dbeel_writer_close2.restype = ctypes.c_int64
        lib.dbeel_writer_close2.argtypes = [
            ctypes.c_void_p,
            ctypes.POINTER(ctypes.c_uint64),
            ctypes.POINTER(ctypes.c_uint32),
            ctypes.c_uint64,
            ctypes.POINTER(ctypes.c_uint32),
            ctypes.c_uint64,
            ctypes.POINTER(ctypes.c_uint64),
            ctypes.POINTER(ctypes.c_uint64),
        ]
    if hasattr(lib, "dbeel_memtable_flush_write2"):
        # Single-pass native flush: triplet write + inline sidecar
        # CRCs in one GIL-free call (replaces the post-hoc
        # compute_and_write re-read of the whole freshly-written
        # triplet).
        lib.dbeel_memtable_flush_write2.restype = ctypes.c_int64
        lib.dbeel_memtable_flush_write2.argtypes = [
            ctypes.c_void_p,
            ctypes.c_char_p,
            ctypes.c_uint64,
            ctypes.c_uint64,
            ctypes.POINTER(ctypes.c_uint32),
            ctypes.c_uint64,
            ctypes.POINTER(ctypes.c_uint32),
            ctypes.c_uint64,
            ctypes.POINTER(ctypes.c_uint64),
            ctypes.POINTER(ctypes.c_uint64),
            ctypes.POINTER(ctypes.c_uint32),
            ctypes.POINTER(ctypes.c_int32),
        ]
    if hasattr(lib, "dbeel_read_files_overlapped"):
        # Overlapped O_DIRECT input loader (io_uring double-buffered;
        # serial fallback counted) — the k-way merge's input pass.
        lib.dbeel_read_files_overlapped.restype = ctypes.c_int64
        lib.dbeel_read_files_overlapped.argtypes = [
            ctypes.POINTER(ctypes.c_char_p),
            ctypes.POINTER(u8p),
            ctypes.POINTER(ctypes.c_uint64),
            ctypes.c_uint32,
            TICK_FN,
            ctypes.c_uint64,
        ]
        lib.dbeel_read_overlap_stats.restype = None
        lib.dbeel_read_overlap_stats.argtypes = [
            ctypes.POINTER(ctypes.c_uint64),
            ctypes.POINTER(ctypes.c_uint64),
        ]
    if hasattr(lib, "dbeel_dp_handle"):
        lib.dbeel_wal_new.restype = ctypes.c_void_p
        lib.dbeel_wal_new.argtypes = [ctypes.c_int32, ctypes.c_uint64]
        lib.dbeel_wal_free.restype = None
        lib.dbeel_wal_free.argtypes = [ctypes.c_void_p]
        lib.dbeel_wal_offset.restype = ctypes.c_uint64
        lib.dbeel_wal_offset.argtypes = [ctypes.c_void_p]
        lib.dbeel_wal_append.restype = ctypes.c_uint64
        lib.dbeel_wal_append.argtypes = [
            ctypes.c_void_p,
            ctypes.c_char_p,
            ctypes.c_uint32,
            ctypes.c_char_p,
            ctypes.c_uint32,
            ctypes.c_int64,
        ]
    if hasattr(lib, "dbeel_qf_new"):
        # Quorum fan-out engine (coordinator-side replica writes +
        # ack compare in C; cluster/native_fanout.py is the loop
        # bridge).
        lib.dbeel_qf_new.restype = ctypes.c_void_p
        lib.dbeel_qf_new.argtypes = []
        lib.dbeel_qf_free.restype = None
        lib.dbeel_qf_free.argtypes = [ctypes.c_void_p]
        lib.dbeel_qf_set_stream.restype = ctypes.c_int32
        lib.dbeel_qf_set_stream.argtypes = [
            ctypes.c_void_p,
            ctypes.c_int32,
            ctypes.c_int32,
        ]
        lib.dbeel_qf_stream_alive.restype = ctypes.c_int32
        lib.dbeel_qf_stream_alive.argtypes = [
            ctypes.c_void_p,
            ctypes.c_int32,
        ]
        lib.dbeel_qf_kill_stream.restype = None
        lib.dbeel_qf_kill_stream.argtypes = [
            ctypes.c_void_p,
            ctypes.c_int32,
        ]
        lib.dbeel_qf_close_stream.restype = None
        lib.dbeel_qf_close_stream.argtypes = [
            ctypes.c_void_p,
            ctypes.c_int32,
        ]
        lib.dbeel_qf_submit.restype = ctypes.c_uint64
        lib.dbeel_qf_submit.argtypes = [
            ctypes.c_void_p,
            ctypes.c_char_p,
            ctypes.c_uint32,
            ctypes.POINTER(ctypes.c_int32),
            ctypes.c_uint32,
            ctypes.c_char_p,
            ctypes.c_uint32,
        ]
        lib.dbeel_qf_wants_write.restype = ctypes.c_int32
        lib.dbeel_qf_wants_write.argtypes = [
            ctypes.c_void_p,
            ctypes.c_int32,
        ]
        lib.dbeel_qf_on_writable.restype = ctypes.c_int32
        lib.dbeel_qf_on_writable.argtypes = [
            ctypes.c_void_p,
            ctypes.c_int32,
        ]
        lib.dbeel_qf_on_readable.restype = ctypes.c_int32
        lib.dbeel_qf_on_readable.argtypes = [
            ctypes.c_void_p,
            ctypes.c_int32,
        ]
        lib.dbeel_qf_next_event.restype = ctypes.c_int32
        lib.dbeel_qf_next_event.argtypes = [
            ctypes.c_void_p,
            ctypes.POINTER(ctypes.c_uint64),
            ctypes.POINTER(ctypes.c_int32),
            ctypes.POINTER(ctypes.c_int32),
            ctypes.c_char_p,
            ctypes.c_uint32,
            ctypes.POINTER(ctypes.c_uint32),
        ]
        lib.dbeel_qf_fanout_ops.restype = ctypes.c_uint64
        lib.dbeel_qf_fanout_ops.argtypes = [ctypes.c_void_p]
    if hasattr(lib, "dbeel_wal_sync_enable"):
        # Group-commit syncer (wal-sync mode): a C thread owns the
        # coalesced fdatasync, completion pings an eventfd.
        lib.dbeel_wal_sync_enable.restype = ctypes.c_int32
        lib.dbeel_wal_sync_enable.argtypes = [
            ctypes.c_void_p,
            ctypes.c_uint64,
            ctypes.c_int32,
        ]
        lib.dbeel_wal_sync_disable.restype = None
        lib.dbeel_wal_sync_disable.argtypes = [ctypes.c_void_p]
        if hasattr(lib, "dbeel_wal_sync_stop_async"):
            lib.dbeel_wal_sync_stop_async.restype = None
            lib.dbeel_wal_sync_stop_async.argtypes = [ctypes.c_void_p]
        lib.dbeel_wal_seq.restype = ctypes.c_uint64
        lib.dbeel_wal_seq.argtypes = [ctypes.c_void_p]
        lib.dbeel_wal_synced.restype = ctypes.c_uint64
        lib.dbeel_wal_synced.argtypes = [ctypes.c_void_p]
    if hasattr(lib, "dbeel_memtable_max_ts"):
        lib.dbeel_memtable_max_ts.restype = ctypes.c_int64
        lib.dbeel_memtable_max_ts.argtypes = [ctypes.c_void_p]
    if hasattr(lib, "dbeel_dp_set_watermark"):
        # Flush-watermark guard: shard-plane writes at or below it
        # punt to Python's read-guarded apply (dataplane.py).
        lib.dbeel_dp_set_watermark.restype = None
        lib.dbeel_dp_set_watermark.argtypes = [
            ctypes.c_void_p,
            ctypes.c_char_p,
            ctypes.c_uint32,
            ctypes.c_int64,
        ]
    if hasattr(lib, "dbeel_walsync_hub_new"):
        # Loop-driven io_uring group commit: fsyncs are SQEs on a
        # loop-owned ring, zero sync threads (wal.py _SyncHub).
        lib.dbeel_walsync_hub_new.restype = ctypes.c_void_p
        lib.dbeel_walsync_hub_new.argtypes = [ctypes.c_uint32]
        lib.dbeel_walsync_hub_free.restype = None
        lib.dbeel_walsync_hub_free.argtypes = [ctypes.c_void_p]
        lib.dbeel_walsync_hub_eventfd.restype = ctypes.c_int32
        lib.dbeel_walsync_hub_eventfd.argtypes = [ctypes.c_void_p]
        lib.dbeel_walsync_hub_reap.restype = None
        lib.dbeel_walsync_hub_reap.argtypes = [ctypes.c_void_p]
        lib.dbeel_wal_sync_attach.restype = ctypes.c_int32
        lib.dbeel_wal_sync_attach.argtypes = [
            ctypes.c_void_p,
            ctypes.c_void_p,
            ctypes.c_uint64,
        ]
    if hasattr(lib, "dbeel_walsync_errors"):
        # Failed-fsync counter (gated separately: stale .so tolerance).
        lib.dbeel_walsync_errors.restype = ctypes.c_uint64
        lib.dbeel_walsync_errors.argtypes = []
    if hasattr(lib, "dbeel_dp_handle"):
        # (continuation of the data-plane prototypes: these must stay
        # gated on dbeel_dp_handle, NOT on the newer syncer symbols —
        # a stale .so without the syncer still runs the data plane and
        # needs every prototype declared.)
        lib.dbeel_dp_new.restype = ctypes.c_void_p
        lib.dbeel_dp_new.argtypes = []
        lib.dbeel_dp_free.restype = None
        lib.dbeel_dp_free.argtypes = [ctypes.c_void_p]
        lib.dbeel_dp_set_ownership.restype = None
        lib.dbeel_dp_set_ownership.argtypes = [
            ctypes.c_void_p,
            ctypes.c_int32,
            ctypes.c_uint32,
            ctypes.c_uint32,
        ]
        lib.dbeel_dp_register.restype = ctypes.c_int32
        lib.dbeel_dp_register.argtypes = [
            ctypes.c_void_p,
            ctypes.c_char_p,
            ctypes.c_uint32,
            ctypes.c_void_p,
            ctypes.c_void_p,
            ctypes.c_void_p,
            ctypes.c_uint32,
            ctypes.c_int32,  # client_plane (0 = replica-plane only)
        ]
        if hasattr(lib, "dbeel_dp_handle_shard"):
            lib.dbeel_dp_handle_shard.restype = ctypes.c_int64
            lib.dbeel_dp_handle_shard.argtypes = [
                ctypes.c_void_p,
                ctypes.c_char_p,
                ctypes.c_uint32,
                ctypes.c_char_p,
                ctypes.c_uint32,
                ctypes.POINTER(ctypes.c_uint32),
            ]
            lib.dbeel_dp_fast_replica_ops.restype = ctypes.c_uint64
            lib.dbeel_dp_fast_replica_ops.argtypes = [ctypes.c_void_p]
        if hasattr(lib, "dbeel_dp_handle_coord"):
            lib.dbeel_dp_handle_coord.restype = ctypes.c_int64
            lib.dbeel_dp_handle_coord.argtypes = [
                ctypes.c_void_p,
                ctypes.c_char_p,
                ctypes.c_uint32,
                ctypes.c_char_p,
                ctypes.c_uint32,
                ctypes.POINTER(ctypes.c_uint32),
            ]
            lib.dbeel_dp_fast_coord_writes.restype = ctypes.c_uint64
            lib.dbeel_dp_fast_coord_writes.argtypes = [
                ctypes.c_void_p
            ]
            lib.dbeel_dp_fast_coord_gets.restype = ctypes.c_uint64
            lib.dbeel_dp_fast_coord_gets.argtypes = [ctypes.c_void_p]
        lib.dbeel_dp_unregister.restype = None
        lib.dbeel_dp_unregister.argtypes = [
            ctypes.c_void_p,
            ctypes.c_char_p,
            ctypes.c_uint32,
        ]
        lib.dbeel_dp_fast_sets.restype = ctypes.c_uint64
        lib.dbeel_dp_fast_sets.argtypes = [ctypes.c_void_p]
        lib.dbeel_dp_fast_gets.restype = ctypes.c_uint64
        lib.dbeel_dp_fast_gets.argtypes = [ctypes.c_void_p]
        if hasattr(lib, "dbeel_dp_set_tables"):
            lib.dbeel_dp_set_tables.restype = ctypes.c_int32
            lib.dbeel_dp_set_tables.argtypes = [
                ctypes.c_void_p,
                ctypes.c_char_p,
                ctypes.c_uint32,
                ctypes.c_void_p,  # FastTable descriptor array
                ctypes.c_int32,
            ]
            lib.dbeel_dp_fast_table_gets.restype = ctypes.c_uint64
            lib.dbeel_dp_fast_table_gets.argtypes = [ctypes.c_void_p]
        if hasattr(lib, "dbeel_dp_set_overload"):
            # All-native serving path (ISSUE 6): multi-op frames,
            # native overload/deadline answers, CRC probe
            # verification.  Gated together: one build ships them
            # all.
            lib.dbeel_dp_set_overload.restype = None
            lib.dbeel_dp_set_overload.argtypes = [
                ctypes.c_void_p,
                ctypes.c_int32,
            ]
            lib.dbeel_dp_set_overload_resp.restype = None
            lib.dbeel_dp_set_overload_resp.argtypes = [
                ctypes.c_void_p,
                ctypes.c_char_p,
                ctypes.c_uint32,
                ctypes.c_char_p,
                ctypes.c_uint32,
            ]
            lib.dbeel_dp_set_verify.restype = None
            lib.dbeel_dp_set_verify.argtypes = [
                ctypes.c_void_p,
                ctypes.c_int32,
            ]
            for fn in (
                lib.dbeel_dp_fast_multi_sets,
                lib.dbeel_dp_fast_multi_gets,
                lib.dbeel_dp_native_sheds,
                lib.dbeel_dp_native_deadline_drops,
                lib.dbeel_dp_crc_failures,
            ):
                fn.restype = ctypes.c_uint64
                fn.argtypes = [ctypes.c_void_p]
            lib.dbeel_crc32_pages.restype = None
            lib.dbeel_crc32_pages.argtypes = [
                u8p,
                ctypes.c_uint64,
                ctypes.POINTER(ctypes.c_uint32),
            ]
            lib.dbeel_odirect_fallbacks.restype = ctypes.c_uint64
            lib.dbeel_odirect_fallbacks.argtypes = []
        if hasattr(lib, "dbeel_dp_set_class_levels"):
            # QoS plane (ISSUE 14): per-class shed levels + per-class
            # native shed counters.  Gated separately — stale .so
            # tolerance (a class-blind .so keeps the scalar gate).
            lib.dbeel_dp_set_class_levels.restype = None
            lib.dbeel_dp_set_class_levels.argtypes = [
                ctypes.c_void_p,
                ctypes.c_int32,
                ctypes.c_int32,
                ctypes.c_int32,
            ]
            lib.dbeel_dp_sheds_by_class.restype = None
            lib.dbeel_dp_sheds_by_class.argtypes = [
                ctypes.c_void_p,
                ctypes.POINTER(ctypes.c_uint64),
            ]
        if hasattr(lib, "dbeel_dp_admits_by_class"):
            # Native lane accounting (ISSUE 15 satellite): per-class
            # served-frame counters (client/coord plane + peer plane),
            # mirrored like sheds_by_class.  Gated separately — stale
            # .so tolerance.
            lib.dbeel_dp_admits_by_class.restype = None
            lib.dbeel_dp_admits_by_class.argtypes = [
                ctypes.c_void_p,
                ctypes.POINTER(ctypes.c_uint64),
            ]
        if hasattr(lib, "dbeel_dp_trace_snapshot"):
            # Tracing plane (PR 9): coarse per-verb native stage
            # counters.  Gated separately — stale .so tolerance.
            lib.dbeel_dp_set_trace.restype = None
            lib.dbeel_dp_set_trace.argtypes = [
                ctypes.c_void_p,
                ctypes.c_int32,
            ]
            lib.dbeel_dp_trace_snapshot.restype = ctypes.c_int32
            lib.dbeel_dp_trace_snapshot.argtypes = [
                ctypes.c_void_p,
                ctypes.POINTER(ctypes.c_uint64),
                ctypes.c_int32,
            ]
        lib.dbeel_dp_handle.restype = ctypes.c_int64
        lib.dbeel_dp_handle.argtypes = [
            ctypes.c_void_p,
            ctypes.c_char_p,
            ctypes.c_uint32,
            ctypes.c_char_p,
            ctypes.c_uint32,
            ctypes.POINTER(ctypes.c_uint32),
        ]
    lib.dbeel_memtable_new.restype = ctypes.c_void_p
    lib.dbeel_memtable_new.argtypes = [ctypes.c_uint32]
    lib.dbeel_memtable_free.restype = None
    lib.dbeel_memtable_free.argtypes = [ctypes.c_void_p]
    lib.dbeel_memtable_len.restype = ctypes.c_uint32
    lib.dbeel_memtable_len.argtypes = [ctypes.c_void_p]
    lib.dbeel_memtable_bytes.restype = ctypes.c_uint64
    lib.dbeel_memtable_bytes.argtypes = [ctypes.c_void_p]
    lib.dbeel_memtable_set.restype = ctypes.c_int32
    lib.dbeel_memtable_set.argtypes = [
        ctypes.c_void_p,
        ctypes.c_char_p,
        ctypes.c_uint32,
        ctypes.c_char_p,
        ctypes.c_uint32,
        ctypes.c_int64,
        ctypes.POINTER(ctypes.c_uint32),
    ]
    lib.dbeel_memtable_get.restype = ctypes.c_int32
    lib.dbeel_memtable_get.argtypes = [
        ctypes.c_void_p,
        ctypes.c_char_p,
        ctypes.c_uint32,
        ctypes.POINTER(ctypes.POINTER(ctypes.c_uint8)),
        ctypes.POINTER(ctypes.c_uint32),
        ctypes.POINTER(ctypes.c_int64),
    ]
    lib.dbeel_memtable_dump_size.restype = ctypes.c_uint64
    lib.dbeel_memtable_dump_size.argtypes = [ctypes.c_void_p]
    lib.dbeel_memtable_dump.restype = ctypes.c_uint64
    lib.dbeel_memtable_dump.argtypes = [ctypes.c_void_p, u8p]
    if hasattr(lib, "dbeel_memtable_flush_write"):
        lib.dbeel_memtable_flush_write.restype = ctypes.c_int64
        lib.dbeel_memtable_flush_write.argtypes = [
            ctypes.c_void_p,
            ctypes.c_char_p,
            ctypes.c_uint64,
            ctypes.c_uint64,
        ]
    lib.dbeel_bloom_add_batch.restype = None
    lib.dbeel_merge.restype = ctypes.c_int64
    lib.dbeel_merge.argtypes = [
        ctypes.POINTER(ctypes.c_char_p),
        ctypes.POINTER(ctypes.c_char_p),
        ctypes.POINTER(ctypes.c_uint64),
        ctypes.c_uint32,
        ctypes.c_int,
        u8p,
        ctypes.POINTER(ctypes.c_uint64),
        u8p,
    ]
    if hasattr(lib, "dbeel_merge_cb"):
        lib.dbeel_merge_cb.restype = ctypes.c_int64
        lib.dbeel_merge_cb.argtypes = [
            ctypes.POINTER(ctypes.c_char_p),
            ctypes.POINTER(ctypes.c_char_p),
            ctypes.POINTER(ctypes.c_uint64),
            ctypes.c_uint32,
            ctypes.c_int,
            u8p,
            ctypes.POINTER(ctypes.c_uint64),
            u8p,
            TICK_FN,
            ctypes.c_uint64,
        ]
    if hasattr(lib, "dbeel_merge_grace_cb"):
        # gc_grace merge (tombstones younger than the int64-ns cutoff
        # survive a drop-tombstones merge).
        lib.dbeel_merge_grace_cb.restype = ctypes.c_int64
        lib.dbeel_merge_grace_cb.argtypes = [
            ctypes.POINTER(ctypes.c_char_p),
            ctypes.POINTER(ctypes.c_char_p),
            ctypes.POINTER(ctypes.c_uint64),
            ctypes.c_uint32,
            ctypes.c_int,
            ctypes.c_int64,
            u8p,
            ctypes.POINTER(ctypes.c_uint64),
            u8p,
            TICK_FN,
            ctypes.c_uint64,
        ]
    _lib = lib
    return _lib


def native_available() -> bool:
    return _load() is not None


def load_if_built() -> Optional[ctypes.CDLL]:
    """Return the lib only if already built — never runs make (safe to
    call from latency-sensitive / event-loop contexts).  An explicit
    DBEEL_NATIVE_SO override skips the exists-check and goes through
    _load(), which raises loudly on ANY override failure (a typo'd
    path silently degrading to Python would green-light a "sanitized"
    run that tested no native code); _load() never runs make for
    overrides, so the latency contract holds."""
    if _lib is not None:
        return _lib
    if not _LIB_OVERRIDDEN and not os.path.exists(_LIB_PATH):
        return None
    return _load()


_odirect_warned = False


def odirect_fallbacks() -> int:
    """Process-wide count of silent O_DIRECT → buffered degradations
    in the C streamers (unaligned destination buffers, filesystems
    refusing O_DIRECT).  Previously these fell back with NO signal —
    the only symptom was a mysterious throughput cliff (ISSUE 6
    satellite); now the count rides ``get_stats.durability`` and the
    first occurrence logs a warning."""
    global _odirect_warned
    lib = _lib  # never triggers a build: observability must be free
    if lib is None or not hasattr(lib, "dbeel_odirect_fallbacks"):
        return 0
    n = int(lib.dbeel_odirect_fallbacks())
    if n and not _odirect_warned:
        _odirect_warned = True
        log.warning(
            "O_DIRECT degraded to buffered I/O %d time(s) "
            "(unaligned buffer or filesystem without O_DIRECT "
            "support) — large merges/reads lose the page-cache "
            "bypass",
            n,
        )
    return n


def read_overlap_stats() -> "tuple[int, int]":
    """(uring_passes, serial_passes) of the overlapped multi-file
    input loader — how many merge input passes rode io_uring vs fell
    back to the serial chunked reader.  Free when the lib is not
    loaded (observability must never trigger a build)."""
    lib = _lib
    if lib is None or not hasattr(lib, "dbeel_read_overlap_stats"):
        return (0, 0)
    a = ctypes.c_uint64(0)
    b = ctypes.c_uint64(0)
    lib.dbeel_read_overlap_stats(ctypes.byref(a), ctypes.byref(b))
    return (int(a.value), int(b.value))


def aligned_u8_buffer(size: int) -> np.ndarray:
    """4 KiB-aligned uint8 destination of ``max(1, size)`` logical
    bytes with page-rounded capacity — what the O_DIRECT readers
    require (an unaligned buffer silently degrades to buffered IO)."""
    cap = (size + 4095) & ~4095
    raw = np.empty(cap + 4096, dtype=np.uint8)
    off = (-raw.ctypes.data) % 4096
    return raw[off : off + max(1, size)]


def page_crcs_native(lib, arr: np.ndarray, size: int) -> list:
    """Per-4KiB-page CRCs of ``arr[:size]`` via the C kernel — the
    in-RAM half of the single-pass sidecar (the merged output is
    still resident; summing it here beats re-reading the file it was
    just written to)."""
    from .entry import PAGE_SIZE

    npages = (size + PAGE_SIZE - 1) // PAGE_SIZE
    if npages == 0:
        return []
    if lib is None or not hasattr(lib, "dbeel_crc32_pages"):
        from . import checksums

        return checksums.page_crcs(memoryview(arr)[:size])
    out = np.zeros(npages, dtype=np.uint32)
    lib.dbeel_crc32_pages(
        arr.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
        ctypes.c_uint64(int(size)),
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_uint32)),
    )
    return out.tolist()


def murmur3_32_native(data: bytes, seed: int = 0) -> int:
    lib = _load()
    if lib is None:
        from ..utils.murmur import murmur3_32

        return murmur3_32(data, seed)
    return lib.dbeel_murmur3_32(data, len(data), seed)


class NativeMergeStrategy(CompactionStrategy):
    """C++ k-way heap merge — reference semantics at native speed."""

    name = "native"

    def merge(
        self,
        sources,
        dir_path,
        output_index,
        cache,
        keep_tombstones,
        bloom_min_size,
    ) -> MergeResult:
        lib = _load()
        assert lib is not None

        throttle = self.throttle
        # Chunked, throttle-ticked input reads: one unbroken
        # multi-hundred-MB read saturates the virtio queue and
        # starves the serving loop (measured 40-200ms stalls at
        # compaction start); 16MB chunks with a tick between let the
        # BgThrottle pace the burst while serving is busy.
        tick_cb = (
            TICK_FN(throttle.tick) if throttle is not None else TICK_FN()
        )
        use_cb = throttle is not None and hasattr(
            lib, "dbeel_read_file_cb"
        )

        def _read_whole(path: str, size: int) -> bytes:
            if not use_cb or size < _IO_CHUNK_BYTES * 2:
                with open(path, "rb") as f:
                    data = f.read(size)
                if len(data) != size:
                    # The merge sizes its buffers from the index
                    # metadata: a truncated data file must fail here,
                    # not as an OOB read in C.
                    raise OSError(
                        f"short read {len(data)} != {size} for {path}"
                    )
                return data
            # 4KiB-aligned destination so the chunked read takes the
            # O_DIRECT path (an unaligned buffer silently falls back
            # to buffered reads).
            buf = aligned_u8_buffer(size)
            got = lib.dbeel_read_file_cb(
                path.encode(),
                buf.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
                ctypes.c_uint64(size),
                tick_cb,
                ctypes.c_uint64(_IO_CHUNK_BYTES),
            )
            if got != size:
                raise OSError(f"short read {got} != {size} for {path}")
            return buf

        # Overlapped input pass (ISSUE 15): all data+index files ride
        # ONE io_uring with double-buffered chunk reads, so the k-way
        # merge's input load approaches device bandwidth instead of
        # paying per-file latency in sequence.  tick() still fires per
        # chunk — the BgThrottle pacing is unchanged.  Small merges
        # and stale .so keep the serial reader.
        counts = [s.entry_count for s in sources]
        datas: "list | None" = None
        indexes: "list | None" = None
        total_in = sum(
            s.data_size + s.entry_count * 16 for s in sources
        )
        if (
            hasattr(lib, "dbeel_read_files_overlapped")
            and total_in >= _IO_CHUNK_BYTES
            # Escape hatch + bench-baseline switch: serial chunked
            # reads exactly as before ISSUE 15.
            and os.environ.get("DBEEL_NO_OVERLAP_READS", "0")
            in ("", "0")
        ):
            paths = [s.data_path for s in sources] + [
                s.index_path for s in sources
            ]
            sizes = [s.data_size for s in sources] + [
                s.entry_count * 16 for s in sources
            ]
            bufs = [aligned_u8_buffer(sz) for sz in sizes]
            PathArr = ctypes.c_char_p * len(paths)
            PtrArr = ctypes.POINTER(ctypes.c_uint8) * len(paths)
            SizeArr = ctypes.c_uint64 * len(paths)
            got = lib.dbeel_read_files_overlapped(
                PathArr(*[p.encode() for p in paths]),
                PtrArr(
                    *[
                        b.ctypes.data_as(
                            ctypes.POINTER(ctypes.c_uint8)
                        )
                        for b in bufs
                    ]
                ),
                SizeArr(*sizes),
                len(paths),
                tick_cb,
                ctypes.c_uint64(_IO_CHUNK_BYTES),
            )
            if got == sum(sizes):
                datas = bufs[: len(sources)]
                indexes = bufs[len(sources) :]
            else:
                log.warning(
                    "overlapped input read failed (%d); serial "
                    "fallback",
                    got,
                )
        if datas is None or indexes is None:
            datas = [
                _read_whole(s.data_path, s.data_size)
                for s in sources
            ]
            indexes = [
                _read_whole(s.index_path, s.entry_count * 16)
                for s in sources
            ]

        total_data = sum(s.data_size for s in sources)
        total_count = sum(counts)
        out_data = np.zeros(max(1, total_data), dtype=np.uint8)
        out_index = np.zeros(max(1, total_count * 16), dtype=np.uint8)
        out_size = ctypes.c_uint64(0)

        def _as_cptr(b):
            if isinstance(b, np.ndarray):
                return ctypes.cast(
                    b.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
                    ctypes.c_char_p,
                )
            return ctypes.c_char_p(b)

        DataArr = ctypes.c_char_p * len(sources)
        CountArr = ctypes.c_uint64 * len(sources)
        keep = 1 if keep_tombstones else 0
        cutoff = int(self.tombstone_drop_before or 0)
        if (
            not keep
            and cutoff > 0
            and not hasattr(lib, "dbeel_merge_grace_cb")
        ):
            # Stale .so without the grace merge: keeping ALL
            # tombstones is the conservative degradation (never
            # resurrect a delete; the space is reclaimed once the
            # library is rebuilt).
            keep = 1
            cutoff = 0
        args = (
            DataArr(*[_as_cptr(d) for d in datas]),
            DataArr(*[_as_cptr(i) for i in indexes]),
            CountArr(*counts),
            len(sources),
            keep,
            out_data.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
            ctypes.byref(out_size),
            out_index.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
        )
        if not keep and cutoff > 0:
            n_out = lib.dbeel_merge_grace_cb(
                *args[:5],
                ctypes.c_int64(cutoff),
                *args[5:],
                tick_cb,
                _MERGE_TICK_EVERY,
            )
        elif hasattr(lib, "dbeel_merge_cb"):
            # TICK_FN() is a NULL fn pointer — same as dbeel_merge.
            n_out = lib.dbeel_merge_cb(
                *args, tick_cb, _MERGE_TICK_EVERY
            )
        else:
            n_out = lib.dbeel_merge(*args)
        data_size = out_size.value
        self._tick()

        from .entry import DATA_FILE_EXT, INDEX_FILE_EXT

        data_path = (
            f"{dir_path}/{file_name(output_index, COMPACT_DATA_FILE_EXT)}"
        )
        index_path = (
            f"{dir_path}/{file_name(output_index, COMPACT_INDEX_FILE_EXT)}"
        )
        # Large outputs: O_DIRECT native writes (no Python buffer
        # copies, no page-cache mirroring — same policy as the device
        # pipeline).  Small outputs keep the mirroring writer so fresh
        # little SSTables stay warm.  (bench.py overrides the module
        # constant to reproduce the round-1 baseline definition.)
        if data_size >= ODIRECT_MIN_BYTES:
            if use_cb and hasattr(lib, "dbeel_write_file_cb"):
                rc1 = lib.dbeel_write_file_cb(
                    data_path.encode(),
                    out_data.ctypes.data_as(
                        ctypes.POINTER(ctypes.c_uint8)
                    ),
                    ctypes.c_uint64(int(data_size)),
                    tick_cb,
                    ctypes.c_uint64(_IO_CHUNK_BYTES),
                )
                rc2 = lib.dbeel_write_file_cb(
                    index_path.encode(),
                    out_index.ctypes.data_as(
                        ctypes.POINTER(ctypes.c_uint8)
                    ),
                    ctypes.c_uint64(int(n_out) * 16),
                    tick_cb,
                    ctypes.c_uint64(_IO_CHUNK_BYTES),
                )
            else:
                rc1 = lib.dbeel_write_file(
                    data_path.encode(),
                    out_data.ctypes.data_as(
                        ctypes.POINTER(ctypes.c_uint8)
                    ),
                    ctypes.c_uint64(int(data_size)),
                )
                rc2 = lib.dbeel_write_file(
                    index_path.encode(),
                    out_index.ctypes.data_as(
                        ctypes.POINTER(ctypes.c_uint8)
                    ),
                    ctypes.c_uint64(int(n_out) * 16),
                )
            if rc1 != 0 or rc2 != 0:
                raise OSError("native O_DIRECT write failed")
        else:
            data_w = PageMirroringWriter(
                data_path,
                (DATA_FILE_EXT, output_index),
                cache,
            )
            data_w.write(out_data[:data_size].tobytes())
            data_w.close()
            index_w = PageMirroringWriter(
                index_path,
                (INDEX_FILE_EXT, output_index),
                cache,
            )
            index_w.write(out_index[: n_out * 16].tobytes())
            index_w.close()

        wrote_bloom = False
        bloom_bytes = None
        if data_size >= bloom_min_size and n_out > 0:
            rec = np.frombuffer(
                out_index[: n_out * 16].tobytes(),
                dtype=np.dtype(
                    [
                        ("offset", "<u8"),
                        ("key_size", "<u4"),
                        ("full_size", "<u4"),
                    ]
                ),
            )
            bloom = BloomFilter.with_capacity(int(n_out))
            key_offsets = (rec["offset"] + 16).astype(np.uint64)
            key_lens = rec["key_size"].astype(np.uint32)
            lib.dbeel_bloom_add_batch(
                bloom.bits.ctypes.data_as(
                    ctypes.POINTER(ctypes.c_uint8)
                ),
                ctypes.c_uint64(bloom.num_bits),
                ctypes.c_uint32(bloom.num_hashes),
                out_data.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
                key_offsets.ctypes.data_as(
                    ctypes.POINTER(ctypes.c_uint64)
                ),
                key_lens.ctypes.data_as(ctypes.POINTER(ctypes.c_uint32)),
                ctypes.c_uint64(n_out),
                ctypes.c_uint32(_SEED1),
                ctypes.c_uint32(_SEED2),
            )
            bloom_bytes = _write_bloom(dir_path, output_index, bloom)
            wrote_bloom = True

        # Single-pass sidecar (ISSUE 15): the merged output is still
        # resident — page-CRC it in RAM (C kernel) and write the
        # compact_sums sidecar inline under the same journaled rename,
        # instead of the post-hoc whole-triplet re-read that roughly
        # doubled compaction read amplification.
        from . import checksums

        checksums.write(
            dir_path,
            output_index,
            page_crcs_native(lib, out_data, int(data_size)),
            page_crcs_native(lib, out_index, int(n_out) * 16),
            int(data_size),
            bloom_bytes,
            ext=checksums.COMPACT_SUMS_FILE_EXT,
        )

        if self.index_fields and n_out > 0:
            # Index run (ISSUE 17): extracted from the SAME resident
            # out_data/out_index buffers the C merge just filled —
            # like the inline sidecar above, it adds zero data-file
            # reads.
            from . import secondary_index as si

            irec = np.frombuffer(
                out_index[: n_out * 16].tobytes(),
                dtype=np.dtype(
                    [
                        ("offset", "<u8"),
                        ("key_size", "<u4"),
                        ("full_size", "<u4"),
                    ]
                ),
            )
            dview = memoryview(out_data)
            offs = irec["offset"].tolist()
            kss = irec["key_size"].tolist()
            fss = irec["full_size"].tolist()
            si.emit_run(
                dir_path,
                output_index,
                self.index_fields,
                (
                    (
                        offs[i],
                        bytes(
                            dview[
                                offs[i] + 16 + kss[i] : offs[i]
                                + fss[i]
                            ]
                        ),
                    )
                    for i in range(int(n_out))
                ),
                compact=True,
            )

        return MergeResult(int(n_out), int(data_size), wrote_bloom)
