"""asyncio bridge over the native raw-io_uring reader.

Role parity with glommio's io_uring read path
(/root/reference/src/storage_engine/cached_file_reader.rs:28-88,
DmaFile::read_at_aligned): page reads SUBMIT from the event-loop
thread without blocking and complete via an eventfd the loop polls —
no executor threads, no ~120µs thread-hop on every cold point read
(the round-2 gap: async reads were thread-pool preads).

One ``UringReader`` per event loop (``get_for_loop``); callers fall
back to the executor path when io_uring is unavailable (sandboxes,
old kernels, lib not built) or the submission queue is full.
"""

from __future__ import annotations

import asyncio
import ctypes
import logging
import os
import weakref
from typing import Dict, Optional, Tuple

from . import native as native_mod

log = logging.getLogger(__name__)

_ENTRIES = 256
_loop_readers: "weakref.WeakKeyDictionary" = weakref.WeakKeyDictionary()
_unavailable = False
# Buffers abandoned at close while kernel reads were in flight (see
# UringReader.close) — intentionally immortal.
_leaked_buffers: list = []


def _bind(lib) -> bool:
    if not hasattr(lib, "dbeel_uring_create"):
        return False
    if getattr(lib, "_uring_bound", False):
        return True
    lib.dbeel_uring_create.restype = ctypes.c_void_p
    lib.dbeel_uring_create.argtypes = [ctypes.c_uint]
    lib.dbeel_uring_destroy.restype = None
    lib.dbeel_uring_destroy.argtypes = [ctypes.c_void_p]
    lib.dbeel_uring_eventfd.restype = ctypes.c_int
    lib.dbeel_uring_eventfd.argtypes = [ctypes.c_void_p]
    lib.dbeel_uring_submit_read.restype = ctypes.c_int
    lib.dbeel_uring_submit_read.argtypes = [
        ctypes.c_void_p,
        ctypes.c_int,
        ctypes.c_void_p,
        ctypes.c_uint32,
        ctypes.c_uint64,
        ctypes.c_uint64,
    ]
    lib.dbeel_uring_queue_read.restype = ctypes.c_int
    lib.dbeel_uring_queue_read.argtypes = (
        lib.dbeel_uring_submit_read.argtypes
    )
    lib.dbeel_uring_flush.restype = ctypes.c_int
    lib.dbeel_uring_flush.argtypes = [ctypes.c_void_p]
    lib.dbeel_uring_reap.restype = ctypes.c_int
    lib.dbeel_uring_reap.argtypes = [
        ctypes.c_void_p,
        ctypes.POINTER(ctypes.c_uint64),
        ctypes.POINTER(ctypes.c_int32),
        ctypes.c_int,
    ]
    lib._uring_bound = True
    return True


class UringReader:
    """Event-loop-confined io_uring submission/completion bridge."""

    def __init__(self, loop: asyncio.AbstractEventLoop, lib) -> None:
        self._lib = lib
        self._h = lib.dbeel_uring_create(_ENTRIES)
        if not self._h:
            raise OSError("io_uring unavailable")
        self._efd = lib.dbeel_uring_eventfd(self._h)
        self._loop = loop
        self._tag = 0
        # tag -> (future, buffer, requested_len)
        self._pending: Dict[int, Tuple[asyncio.Future, object, int]] = {}
        self._reap_tags = (ctypes.c_uint64 * _ENTRIES)()
        self._reap_res = (ctypes.c_int32 * _ENTRIES)()
        loop.add_reader(self._efd, self._drain)

    def close(self) -> None:
        if self._h:
            try:
                self._loop.remove_reader(self._efd)
            except Exception:
                pass
            if self._pending:
                # The kernel may still DMA into these buffers after
                # the ring fd closes (in-flight ops hold references):
                # leak them deliberately rather than free memory under
                # a live write.
                _leaked_buffers.append(
                    [b for _f, b, _n in self._pending.values()]
                )
            self._lib.dbeel_uring_destroy(self._h)
            self._h = None
        for fut, _buf, _n in self._pending.values():
            if not fut.done():
                fut.set_exception(OSError("uring reader closed"))
        self._pending.clear()

    def queue_pread(
        self, fd: int, size: int, offset: int
    ) -> Optional[asyncio.Future]:
        """Queue one positional read WITHOUT submitting; call
        ``flush()`` once per batch (one syscall for the whole miss
        list).  Returns a Future resolving to the raw bytes (possibly
        short at EOF), or None when the ring is at capacity / gone
        (caller falls back to the executor path).  The C side caps
        in-flight + queued at the CQ size, so completions can never
        overflow and hang."""
        if not self._h:
            return None
        buf = ctypes.create_string_buffer(size)
        self._tag += 1
        tag = self._tag
        rc = self._lib.dbeel_uring_queue_read(
            self._h,
            fd,
            ctypes.cast(buf, ctypes.c_void_p),
            size,
            offset,
            tag,
        )
        if rc != 0:
            return None
        fut = self._loop.create_future()
        self._pending[tag] = (fut, buf, size)
        return fut

    def flush(self) -> bool:
        """Submit everything queued; False on kernel rejection (the
        queued futures will then never complete — callers must treat
        this as fatal for those reads)."""
        if not self._h:
            return False
        return self._lib.dbeel_uring_flush(self._h) >= 0

    def submit_pread(
        self, fd: int, size: int, offset: int
    ) -> Optional[asyncio.Future]:
        """queue_pread + flush for single-read callers."""
        fut = self.queue_pread(fd, size, offset)
        if fut is None:
            return None
        if not self.flush():
            tag = self._tag
            self._pending.pop(tag, None)
            return None
        return fut

    def _drain(self) -> None:
        try:
            os.read(self._efd, 8)
        except BlockingIOError:
            pass
        while True:
            n = self._lib.dbeel_uring_reap(
                self._h, self._reap_tags, self._reap_res, _ENTRIES
            )
            if n <= 0:
                break
            for i in range(n):
                entry = self._pending.pop(
                    int(self._reap_tags[i]), None
                )
                if entry is None:
                    continue
                fut, buf, _size = entry
                if fut.done():
                    continue
                res = int(self._reap_res[i])
                if res < 0:
                    fut.set_exception(
                        OSError(-res, os.strerror(-res))
                    )
                else:
                    fut.set_result(buf.raw[:res])
            if n < _ENTRIES:
                break


def get_for_loop(
    loop: Optional[asyncio.AbstractEventLoop] = None,
) -> Optional[UringReader]:
    """The loop's UringReader, created on first use; None when
    io_uring / the native lib is unavailable or DBEEL_NO_URING set."""
    global _unavailable
    if _unavailable or os.environ.get("DBEEL_NO_URING"):
        return None
    if loop is None:
        loop = asyncio.get_event_loop()
    reader = _loop_readers.get(loop)
    if reader is not None:
        return reader if reader._h else None
    lib = native_mod.load_if_built()
    if lib is None or not _bind(lib):
        _unavailable = True
        return None
    try:
        reader = UringReader(loop, lib)
    except OSError as e:
        log.info("io_uring unavailable (%s); executor reads", e)
        _unavailable = True
        return None
    _loop_readers[loop] = reader
    # Free the ring (fd + eventfd + mmaps) when the LOOP is collected:
    # per-test/per-run loops would otherwise each leak one ring.
    weakref.finalize(loop, reader.close)
    return reader
