"""Vectorized filter/aggregate evaluation over ScanStage columns
(query compute plane, PR 13).

``query.py`` defines the spec grammar and the golden per-entry
evaluator; this module evaluates the SAME semantics columnar over a
staged snapshot:

* ``field_column(stage, name)`` — batched decode of one value field
  into fixed-width columns (int64 + float64 numeric lanes, an
  ``S{w}`` byte lane), built lazily and cached on the stage exactly
  like the key matrix.  Value bytes read through the stage's lazy
  per-page CRC verify (``_TableSrc.value_at``) — the column build is
  the ONLY place a filtered scan touches non-matching values, once
  per stage, and corruption surfaces as the usual quarantine +
  retryable error.
* ``eval_where(stage, where)`` — numpy mask evaluation of the
  predicate tree: key leaves become searchsorted index intervals
  (the key matrix is sorted), field leaves become elementwise lane
  comparisons, AND/OR become logical reductions.  A tiny ``fix`` row
  set (ints beyond 2^53, byte values that the S dtype would alias)
  is re-evaluated through the golden scalar path, so the combined
  mask is byte-identical to the golden walk on EVERY input, not just
  typical ones.  Numeric float64 leaves can run on the jitted device
  twins (ops/query_kernels.py) when that gate is open.
* ``agg_partial_for(stage, positions, agg)`` — columnar aggregate
  reduction over accepted rows only: exact int-lane sums, exact
  Shewchuk float partials, first-achiever min/max, group-by-key-
  prefix folding.  Never materializes a value byte.
"""

from __future__ import annotations

from typing import Any, List, Optional, Tuple

import msgpack
import numpy as np

from .. import query as Q
from ..ops import query_kernels
from .entry import ENTRY_HEADER_SIZE

# Byte values wider than this leave the S lane (scalar fix-up): an
# unbounded padded matrix over a blob-ish field would be an
# allocation lever.
FIELD_WIDTH_CAP = 256

# Per-stage cache caps: the mask key includes predicate OPERANDS, so
# a client sweeping operand values (or field names) must not be able
# to pin one n-byte mask (or one whole decoded column) per distinct
# spec for the stage lifetime — an allocation lever on the
# network-facing port.  Clear-on-overflow like the peer-spec cache:
# the evaluator just rebuilds (cheap for masks; a column rebuild
# costs one decode pass, paid by the sweeping client's own scan).
MAX_CACHED_MASKS = 32
MAX_CACHED_FIELD_COLS = 8

_F53 = 1 << 53


class FieldCol:
    """One decoded value field in columnar lanes."""

    __slots__ = (
        "is_int",
        "is_float",
        "is_num",
        "is_bytes",
        "i64",
        "f64",
        "bval",
        "width",
        "fix",
        "fixvals",
        "valid",
    )

    def __init__(self, n: int, width: int) -> None:
        self.is_int = np.zeros(n, dtype=bool)
        self.is_float = np.zeros(n, dtype=bool)
        self.is_num = np.zeros(n, dtype=bool)
        self.is_bytes = np.zeros(n, dtype=bool)
        self.i64 = np.zeros(n, dtype=np.int64)
        self.f64 = np.zeros(n, dtype=np.float64)
        self.width = width
        self.bval = np.zeros(n, dtype=f"S{max(1, width)}")
        self.fix = np.zeros(n, dtype=bool)
        self.fixvals: dict = {}
        self.valid = np.zeros(n, dtype=bool)

    def typed_at(self, p: int) -> Any:
        """The exact typed value at row p (None = no comparable
        value) — the same value the golden evaluator would see."""
        if self.is_int[p]:
            return int(self.i64[p])
        if self.is_float[p]:
            return float(self.f64[p])
        if self.fix[p]:
            return self.fixvals.get(int(p))
        if self.is_bytes[p]:
            return bytes(self.bval[p])
        return None


def _value_bytes(stage, p: int) -> bytes:
    src = stage.sources[int(stage.src[p])]
    if isinstance(src, list):  # memtable items
        return src[int(stage.off[p])][1]
    return src.value_at(
        int(stage.off[p])
        + ENTRY_HEADER_SIZE
        + int(stage.klen[p]),
        int(stage.vlen[p]),
    )


def field_column(stage, name: str) -> FieldCol:
    """The cached column for one value field, building it on first
    use (one per-entry decode pass per stage lifetime — every later
    page and every later chunk of the scan reuses it)."""
    col = stage._field_cols.get(name)
    if col is not None:
        return col
    n = stage.n
    vlen = stage.vlen
    typed: List[Tuple[int, Any]] = []
    width = 1
    for p in range(n):
        if vlen[p] == 0:
            continue  # tombstones match nothing
        x = Q.field_value(
            Q.decode_doc(_value_bytes(stage, p)), name
        )
        if x is None:
            continue
        if isinstance(x, (str, bytes)):
            b = x.encode("utf-8") if isinstance(x, str) else x
            typed.append((p, ("b", b)))
            if len(b) <= FIELD_WIDTH_CAP:
                width = max(width, len(b))
        else:
            typed.append((p, ("n", x)))
    col = FieldCol(n, width)
    for p, (kind, x) in typed:
        col.valid[p] = True
        if kind == "n":
            if isinstance(x, int):
                if abs(x) > _F53:
                    # Beyond exact float64: the vector lanes would
                    # round — golden scalar owns these rows.
                    col.fix[p] = True
                    col.fixvals[p] = x
                else:
                    col.is_int[p] = True
                    col.is_num[p] = True
                    col.i64[p] = x
                    col.f64[p] = x
            else:
                col.is_float[p] = True
                col.is_num[p] = True
                col.f64[p] = x
        else:
            if len(x) > FIELD_WIDTH_CAP or x.endswith(b"\x00"):
                # Wider than the padded lane, or trailing-NUL (the
                # S dtype strips those, aliasing two values).
                col.fix[p] = True
                col.fixvals[p] = x
            else:
                col.is_bytes[p] = True
                col.bval[p] = x
    if len(stage._field_cols) >= MAX_CACHED_FIELD_COLS:
        stage._field_cols.clear()
    stage._field_cols[name] = col
    return col


# ---------------------------------------------------------------------
# Key leaves: index intervals over the sorted key matrix
# ---------------------------------------------------------------------


def _key_cuts(stage, b: bytes) -> Tuple[int, int]:
    """(first index >= b, first index > b) with exact semantics for
    operands wider than the column (stored keys are all <= width and
    never NUL-terminated, so a stored key exceeds a longer operand
    iff it exceeds its width-byte prefix; equality is impossible)."""
    keys = stage.keys
    width = keys.dtype.itemsize
    if len(b) <= width:
        lo = int(np.searchsorted(keys, b, side="left"))
        hi = int(np.searchsorted(keys, b, side="right"))
        return lo, hi
    t = int(np.searchsorted(keys, b[:width], side="right"))
    return t, t


def _key_leaf_mask(stage, node: list) -> np.ndarray:
    n = stage.n
    mask = np.zeros(n, dtype=bool)
    kind = node[0]
    if kind == "cmp":
        op, b = node[2], node[3]
        ge, gt = _key_cuts(stage, b)
        if op == "==":
            mask[ge:gt] = True
        elif op == "!=":
            mask[:] = True
            mask[ge:gt] = False
        elif op == "<":
            mask[:ge] = True
        elif op == "<=":
            mask[:gt] = True
        elif op == ">":
            mask[gt:] = True
        else:  # >=
            mask[ge:] = True
        return mask
    if kind == "prefix":
        p = node[2]
        width = stage.keys.dtype.itemsize
        if len(p) > width:
            return mask
        lo, _ = _key_cuts(stage, p)
        upper = Q.increment_prefix(p)
        hi = n if upper is None else _key_cuts(stage, upper)[0]
        mask[lo:hi] = True
        return mask
    # range: lo <= key < hi
    lo_b, hi_b = node[2], node[3]
    lo = 0 if lo_b is None else _key_cuts(stage, lo_b)[0]
    hi = n if hi_b is None else _key_cuts(stage, hi_b)[0]
    mask[lo:hi] = True
    return mask


# ---------------------------------------------------------------------
# Field leaves: elementwise lane comparisons
# ---------------------------------------------------------------------

_NP_CMP = {
    "==": np.equal,
    "!=": np.not_equal,
    "<": np.less,
    "<=": np.less_equal,
    ">": np.greater,
    ">=": np.greater_equal,
}


def _scalar_overlay(
    mask: np.ndarray, col: FieldCol, node: list
) -> None:
    """Re-evaluate the fix rows through the golden scalar leaf and
    overwrite their mask bits (the vector lanes never saw them)."""
    if not col.fixvals:
        return
    for p, x in col.fixvals.items():
        kind = node[0]
        if kind == "cmp":
            mask[p] = Q._leaf_cmp(x, node[2], node[3])
        elif kind == "prefix":
            mask[p] = isinstance(x, bytes) and x.startswith(
                node[2]
            )
        else:  # range
            mask[p] = _scalar_range(x, node[2], node[3])


def _scalar_range(x: Any, lo: Any, hi: Any) -> bool:
    num_bounds = isinstance(lo, (int, float)) or isinstance(
        hi, (int, float)
    )
    if isinstance(x, (int, float)) != num_bounds and not (
        lo is None and hi is None
    ):
        return False
    if lo is not None and not (lo <= x):
        return False
    if hi is not None and not (x < hi):
        return False
    return True


def _bytes_scalar_leaf(
    col: FieldCol, node: list
) -> np.ndarray:
    """Byte-lane leaf evaluated per row (operand shapes the S lane
    cannot compare exactly: trailing-NUL or wider-than-lane
    operands).  Bounded by the byte-lane population."""
    n = col.is_bytes.size
    mask = np.zeros(n, dtype=bool)
    rows = np.flatnonzero(col.is_bytes)
    vals = col.bval[rows].tolist()
    kind = node[0]
    for r, v in zip(rows.tolist(), vals):
        if kind == "cmp":
            mask[r] = Q._leaf_cmp(v, node[2], node[3])
        elif kind == "prefix":
            mask[r] = v.startswith(node[2])
        else:
            mask[r] = _scalar_range(v, node[2], node[3])
    return mask


def _num_cmp_mask(
    col: FieldCol, op: str, operand, counters: dict
) -> np.ndarray:
    if isinstance(operand, int) and abs(operand) > _F53:
        # Operand beyond exact float64: scalar over the numeric
        # lanes (int rows compare exactly in Python).
        n = col.is_num.size
        mask = np.zeros(n, dtype=bool)
        rows = np.flatnonzero(col.is_num)
        for r in rows.tolist():
            x = (
                int(col.i64[r])
                if col.is_int[r]
                else float(col.f64[r])
            )
            mask[r] = Q._leaf_cmp(x, op, operand)
        return mask
    dev = query_kernels.eval_cmp_f64(
        col.f64, col.is_num, float(operand), op
    )
    if dev is not None:
        counters["device"] += 1
        return dev
    counters["host"] += 1
    return _NP_CMP[op](col.f64, float(operand)) & col.is_num


def _field_leaf_mask(
    stage, node: list, counters: dict
) -> np.ndarray:
    col = field_column(stage, node[1])
    kind = node[0]
    if kind == "cmp":
        operand = node[3]
        if isinstance(operand, (int, float)):
            mask = _num_cmp_mask(col, node[2], operand, counters)
        else:
            nb = (
                operand.encode("utf-8")
                if isinstance(operand, str)
                else operand
            )
            if len(nb) > col.width or nb.endswith(b"\x00"):
                mask = _bytes_scalar_leaf(
                    col, ["cmp", node[1], node[2], nb]
                )
            else:
                counters["host"] += 1
                mask = (
                    _NP_CMP[node[2]](col.bval, nb) & col.is_bytes
                )
        _scalar_overlay(mask, col, node)
        return mask
    if kind == "prefix":
        p = node[2]
        if len(p) > col.width or p.endswith(b"\x00"):
            mask = _bytes_scalar_leaf(col, node)
        elif len(p) == 0:
            mask = col.is_bytes.copy()
        else:
            counters["host"] += 1
            upper = Q.increment_prefix(p)
            mask = (col.bval >= p) & col.is_bytes
            if upper is not None:
                mask &= col.bval < upper
        _scalar_overlay(mask, col, node)
        return mask
    # range
    lo, hi = node[2], node[3]
    if lo is None and hi is None:
        mask = col.valid.copy()
        return mask
    if isinstance(lo, (int, float)) or isinstance(
        hi, (int, float)
    ):
        big = (
            isinstance(lo, int) and abs(lo) > _F53
        ) or (isinstance(hi, int) and abs(hi) > _F53)
        dev = (
            None
            if big
            else query_kernels.eval_range_f64(
                col.f64,
                col.is_num,
                None if lo is None else float(lo),
                None if hi is None else float(hi),
            )
        )
        if dev is not None:
            counters["device"] += 1
            mask = dev
        elif big:
            n = col.is_num.size
            mask = np.zeros(n, dtype=bool)
            for r in np.flatnonzero(col.is_num).tolist():
                x = (
                    int(col.i64[r])
                    if col.is_int[r]
                    else float(col.f64[r])
                )
                mask[r] = _scalar_range(x, lo, hi)
        else:
            counters["host"] += 1
            mask = col.is_num.copy()
            if lo is not None:
                mask &= col.f64 >= float(lo)
            if hi is not None:
                mask &= col.f64 < float(hi)
    else:
        bad = (
            lo is not None
            and (len(lo) > col.width or lo.endswith(b"\x00"))
        ) or (
            hi is not None
            and (len(hi) > col.width or hi.endswith(b"\x00"))
        )
        if bad:
            mask = _bytes_scalar_leaf(col, node)
        else:
            counters["host"] += 1
            mask = col.is_bytes.copy()
            if lo is not None:
                mask &= col.bval >= lo
            if hi is not None:
                mask &= col.bval < hi
    _scalar_overlay(mask, col, node)
    return mask


def _eval_node(stage, node: list, counters: dict) -> np.ndarray:
    kind = node[0]
    if kind == "and":
        return np.logical_and.reduce(
            [_eval_node(stage, c, counters) for c in node[1:]]
        )
    if kind == "or":
        return np.logical_or.reduce(
            [_eval_node(stage, c, counters) for c in node[1:]]
        )
    if node[1] == Q.KEY_FIELD:
        counters["host"] += 1
        return _key_leaf_mask(stage, node)
    return _field_leaf_mask(stage, node, counters)


def eval_where(
    stage, where: Optional[list]
) -> Tuple[np.ndarray, str]:
    """(match mask over the whole stage, eval path) — the mask is
    cached on the stage keyed by the packed tree, so every page and
    every chunk of a multi-chunk scan reuses one evaluation.  Path:
    "cached" | "device" (>=1 leaf ran the jit twin) | "numpy".
    Tombstone rows are always False (suppressors, not matches)."""
    if where is None:
        return stage.vlen != 0, "numpy"
    key = msgpack.packb(where, use_bin_type=True)
    cached = stage._mask_cache.get(key)
    if cached is not None:
        return cached, "cached"
    counters = {"device": 0, "host": 0}
    mask = _eval_node(stage, where, counters)
    mask = mask & (stage.vlen != 0)
    if len(stage._mask_cache) >= MAX_CACHED_MASKS:
        stage._mask_cache.clear()
    stage._mask_cache[key] = mask
    return mask, ("device" if counters["device"] else "numpy")


# ---------------------------------------------------------------------
# Columnar aggregate reduction (exact; accepted rows only)
# ---------------------------------------------------------------------


def _exact_int_sum(arr: np.ndarray) -> int:
    """Exact sum of an int64 column (int64 accumulation when it
    provably cannot wrap, Python fold otherwise)."""
    if arr.size == 0:
        return 0
    m = int(np.abs(arr).max())
    if m and arr.size > (1 << 62) // m:
        return sum(int(v) for v in arr.tolist())
    return int(arr.sum())


def _first_pos(rows: np.ndarray, cond: np.ndarray) -> int:
    return int(rows[np.flatnonzero(cond)[0]])


def _lane_extreme(
    col: FieldCol, pos: np.ndarray, want_min: bool
) -> Optional[Tuple[Any, int]]:
    """(value, first achieving position) of the numeric-lane extreme
    over ``pos``, preserving the golden first-on-tie and NaN
    semantics.  None when no numeric rows."""
    ipos = pos[col.is_int[pos]]
    fpos = pos[col.is_float[pos]]
    xpos = [
        p for p in pos.tolist() if col.fix[p]
        and isinstance(col.fixvals.get(p), int)
    ]
    farr = col.f64[fpos]
    if farr.size and bool(np.isnan(farr).any()):
        # NaN poisons ordered folds in golden (strict-< never
        # replaces it): replicate sequentially.
        best = None
        bp = -1
        for p in sorted(
            ipos.tolist() + fpos.tolist() + xpos
        ):
            x = col.typed_at(p)
            if best is None:
                best, bp = x, p
            elif (x < best) if want_min else (x > best):
                best, bp = x, p
        return None if best is None else (best, bp)
    cands: List[Tuple[Any, int]] = []
    if ipos.size:
        arr = col.i64[ipos]
        v = int(arr.min() if want_min else arr.max())
        cands.append((v, _first_pos(ipos, arr == v)))
    if fpos.size:
        v = float(farr.min() if want_min else farr.max())
        cands.append((float(v), _first_pos(fpos, farr == v)))
    for p in xpos:
        cands.append((col.fixvals[p], p))
    if not cands:
        return None
    best, bp = cands[0]
    for v, p in cands[1:]:
        better = (v < best) if want_min else (v > best)
        if better or (v == best and p < bp):
            best, bp = v, p
    return best, bp


def agg_partial_for(
    stage, pos: np.ndarray, agg: dict
) -> Any:
    """Wire-form partial aggregate over accepted positions: the
    ungrouped state list, or [group_key, state] pairs (grouped).
    Exactly equal to folding the same rows through query.agg_fold in
    position order."""
    op = agg["op"]
    group = agg["group"]
    if group:
        # Grouped: fold per row (bounded by the page), columnar
        # typed extraction — group keys come from the key matrix.
        out: dict = {}
        col = (
            None
            if op == "count"
            else field_column(stage, agg["field"])
        )
        for p in pos.tolist():
            x = None if col is None else col.typed_at(p)
            if not Q.contributes(op, x):
                continue
            k = stage.key_at(p)[:group]
            st = out.get(k)
            if st is None:
                if len(out) >= Q.MAX_GROUPS:
                    from ..errors import BadFieldType

                    raise BadFieldType(
                        "spec: aggregate group cardinality too high"
                    )
                st = out[k] = Q.agg_new()
            Q.agg_fold(st, op, None if op == "count" else x)
        return [[k, st] for k, st in sorted(out.items())]

    state = Q.agg_new()
    if op == "count":
        state[0] = int(pos.size)
        return state
    col = field_column(stage, agg["field"])
    ipos = pos[col.is_int[pos]]
    fpos = pos[col.is_float[pos]]
    fix_num = [
        (p, col.fixvals[p])
        for p in pos.tolist()
        if col.fix[p] and isinstance(col.fixvals.get(p), int)
    ]
    state[0] = int(ipos.size + fpos.size) + len(fix_num)
    if op in ("sum", "avg"):
        state[1] = _exact_int_sum(col.i64[ipos]) + sum(
            x for _p, x in fix_num
        )
        for v in col.f64[fpos].tolist():
            Q.grow_partials(state[2], v)
    mn = _lane_extreme(col, pos, True)
    mx = _lane_extreme(col, pos, False)
    state[3] = None if mn is None else mn[0]
    state[4] = None if mx is None else mx[0]
    return state
