"""Columnar staging for batched (vectorized / device) compaction.

This is the host side of the north-star design (BASELINE.md): the
reference's per-entry k-way heap merge (/root/reference/src/storage_engine/
lsm_tree.rs:1038-1066) is re-expressed as bulk array ops —

  1. *columnarize*: one bulk read per SSTable; index files parse straight
     into (offset, key_size, full_size) columns, keys load into a fixed
     16-byte big-endian prefix matrix viewed as 4 uint32 words (numeric
     compare == lexicographic compare);
  2. *sort + dedup kernel*: an ascending lexicographic sort over
     (key words, key_len, ~timestamp, ~source) — so within one key the
     newest timestamp (tie: newest input) comes first — then a
     keep-first-per-key mask.  Runs on numpy (host) or jax (TPU device);
  3. *fixup*: keys longer than the 16-byte prefix can tie; every tied
     prefix block is re-sorted on the host with full-key compares (rare);
  4. *gather*: surviving records are copied out of the source data files
     by vectorized range-gather and streamed to the output SSTable.

Dedup semantics match the reference exactly: keep the newest timestamp
per key, ties broken toward the newer input sstable; tombstones dropped
only when compacting the bottom level (compaction.rs:90-92).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import List, Sequence, Tuple

import numpy as np

from .entry import ENTRY_HEADER_SIZE

KEY_PREFIX_BYTES = 16
KEY_PREFIX_WORDS = KEY_PREFIX_BYTES // 4


@dataclass
class MergeColumns:
    """Concatenated columns over all input sstables, in input order
    (sources must be passed oldest→newest so larger src == newer)."""

    data: np.ndarray  # uint8, all data files concatenated
    start: np.ndarray  # u64, absolute record start in `data`
    key_size: np.ndarray  # u32
    full_size: np.ndarray  # u32
    timestamp: np.ndarray  # u64 bit-view of int64 nanos (always >= 0)
    src: np.ndarray  # u32, index into sources (position, not sstable id)
    key_words: np.ndarray  # (N, 4) u32 big-endian prefix words
    is_tombstone: np.ndarray  # bool

    def __len__(self) -> int:
        return int(self.start.size)


def read_source_pieces(sources: Sequence):
    """One bulk read per sstable → [(raw, offsets, key_sizes,
    full_sizes)] for assemble_columns."""
    return [
        (table.read_data_bytes(), *table.read_index_columns())
        for table in sources
    ]


def load_columns(sources: Sequence) -> MergeColumns:
    """sources: SSTable-likes exposing read_index_columns() and
    read_data_bytes()."""
    return assemble_columns(read_source_pieces(sources))


def assemble_columns(pieces) -> MergeColumns:
    """pieces: [(raw_bytes, offsets u64, key_sizes u32, full_sizes
    u32)] per source, oldest→newest."""
    datas: List[bytes] = []
    starts: List[np.ndarray] = []
    key_sizes: List[np.ndarray] = []
    full_sizes: List[np.ndarray] = []
    srcs: List[np.ndarray] = []
    base = 0
    for i, (raw, offs, ks, fs) in enumerate(pieces):
        datas.append(raw)
        starts.append(offs.astype(np.uint64) + np.uint64(base))
        key_sizes.append(ks)
        full_sizes.append(fs)
        srcs.append(np.full(offs.size, i, dtype=np.uint32))
        base += len(raw)
    data = np.frombuffer(b"".join(datas), dtype=np.uint8)
    start = np.concatenate(starts) if starts else np.zeros(0, np.uint64)
    key_size = (
        np.concatenate(key_sizes) if key_sizes else np.zeros(0, np.uint32)
    )
    full_size = (
        np.concatenate(full_sizes) if full_sizes else np.zeros(0, np.uint32)
    )
    src = np.concatenate(srcs) if srcs else np.zeros(0, np.uint32)
    n = start.size

    uniform = (
        n > 0
        and data.size == n * int(full_size[0])
        and (full_size == full_size[0]).all()
        and (key_size == key_size[0]).all()
        # Record i must actually live at row i (same guard as
        # gather_records) — duck-typed sources could order differently.
        and (
            start
            == np.arange(n, dtype=np.uint64) * np.uint64(full_size[0])
        ).all()
    )
    if uniform:
        # Fixed-size records: the whole data blob is an (N, record)
        # matrix — strided views replace fancy-indexed gathers.
        rec = int(full_size[0])
        ks = int(key_size[0])
        mat = data.reshape(n, rec)
        ts = mat[:, 8:16].reshape(-1).view("<u8").astype(np.uint64)
        kmat = np.zeros((n, KEY_PREFIX_BYTES), dtype=np.uint8)
        kmat[:, : min(ks, KEY_PREFIX_BYTES)] = mat[
            :, ENTRY_HEADER_SIZE : ENTRY_HEADER_SIZE
            + min(ks, KEY_PREFIX_BYTES)
        ]
        key_words = (
            np.ascontiguousarray(kmat)
            .view(np.dtype(">u4"))
            .astype(np.uint32)
            .reshape(n, KEY_PREFIX_WORDS)
        )
    else:
        # Timestamps live at record offset 8 (header: kl, vl, ts).
        ts = np.zeros(n, dtype=np.uint64)
        if n:
            ts_pos = (start + np.uint64(8))[:, None] + np.arange(
                8, dtype=np.uint64
            )
            ts_bytes = data[ts_pos.astype(np.int64)]
            ts = ts_bytes.astype(np.uint64) @ (
                np.uint64(1)
                << (np.arange(8, dtype=np.uint64) * np.uint64(8))
            )
        key_words = prefix_words(data, start, key_size)

    # value_len == 0 <=> tombstone (full == header + key).
    is_tomb = full_size == key_size + np.uint32(ENTRY_HEADER_SIZE)
    return MergeColumns(
        data=data,
        start=start,
        key_size=key_size,
        full_size=full_size,
        timestamp=ts,
        src=src,
        key_words=key_words,
        is_tombstone=is_tomb,
    )


def prefix_words(
    data: np.ndarray, start: np.ndarray, key_size: np.ndarray
) -> np.ndarray:
    """(N, 4) big-endian uint32 words of the zero-padded 16-byte key
    prefix."""
    n = start.size
    if n == 0:
        return np.zeros((0, KEY_PREFIX_WORDS), dtype=np.uint32)
    key_start = start + np.uint64(ENTRY_HEADER_SIZE)
    lanes = np.arange(KEY_PREFIX_BYTES, dtype=np.uint64)
    pos = key_start[:, None] + lanes
    valid = lanes < key_size.astype(np.uint64)[:, None]
    pos = np.minimum(pos, np.uint64(max(0, data.size - 1)))
    mat = np.where(valid, data[pos.astype(np.int64)], 0).astype(np.uint8)
    return (
        np.ascontiguousarray(mat)
        .view(np.dtype(">u4"))
        .astype(np.uint32)
        .reshape(n, KEY_PREFIX_WORDS)
    )


def sort_columns_numpy(cols: MergeColumns) -> np.ndarray:
    """Host (numpy) lexicographic sort: key asc, then newest ts first,
    then newest source first.  Returns the permutation."""
    inv_ts = ~cols.timestamp
    inv_src = ~cols.src
    return np.lexsort(
        (
            inv_src,
            inv_ts,
            cols.key_size,
            cols.key_words[:, 3],
            cols.key_words[:, 2],
            cols.key_words[:, 1],
            cols.key_words[:, 0],
        )
    )


def full_key(cols: MergeColumns, i: int) -> bytes:
    s = int(cols.start[i]) + ENTRY_HEADER_SIZE
    return cols.data[s : s + int(cols.key_size[i])].tobytes()


def _flags_to_runs(flags: np.ndarray) -> List[Tuple[int, int]]:
    """Adjacent-pair flags → [lo, hi) index runs covering flagged pairs."""
    runs: List[Tuple[int, int]] = []
    run_start = None
    run_end = 0
    for b in np.flatnonzero(flags):
        if run_start is None:
            run_start, run_end = b, b + 1
        elif b == run_end:
            run_end = b + 1
        else:
            runs.append((run_start, run_end + 1))
            run_start, run_end = b, b + 1
    if run_start is not None:
        runs.append((run_start, run_end + 1))
    return runs


def tie_positions_and_blocks(flags: np.ndarray):
    """Adjacent-pair tie flags (n-1,) → (positions, block_id): the
    sorted positions participating in any tie block, and a 0-based
    block index per position.  Blocks are maximal chains of flagged
    pairs; a False flag between two flagged pairs separates blocks even
    when the positions are contiguous."""
    if flags.size == 0 or not flags.any():
        return np.zeros(0, np.int64), np.zeros(0, np.int64)
    in_block = np.zeros(flags.size + 1, dtype=bool)
    in_block[:-1] |= flags
    in_block[1:] |= flags
    positions = np.flatnonzero(in_block)
    starts = np.ones(positions.size, dtype=bool)
    starts[1:] = ~flags[positions[:-1]]
    block_id = np.cumsum(starts) - 1
    return positions, block_id


def tie_block_sort(
    block_id: np.ndarray,  # (m,) int64, ascending
    key_words: np.ndarray,  # (m, W) native u64 of BE-padded key bytes
    key_len: np.ndarray,  # (m,)
    inv_ts: np.ndarray,  # (m,) u64, ~timestamp
    inv_src: np.ndarray,  # (m,)  ~source (newest-first tiebreak)
):
    """One vectorized lexsort ordering every tie block by the exact
    merge order (full key asc, newest ts, newest src), blocks kept in
    place via the primary block_id key.  Returns (order, dup): the
    permutation over the m tie entries and per-sorted-entry duplicate
    flags (equal full key as predecessor within the same block; the
    first = newest survives)."""
    cols = (
        (inv_src, inv_ts, key_len)
        + tuple(
            key_words[:, w]
            for w in range(key_words.shape[1] - 1, -1, -1)
        )
        + (block_id,)
    )
    order = np.lexsort(cols)
    dup = np.zeros(order.size, dtype=bool)
    if order.size > 1:
        kb = key_words[order]
        dup[1:] = (
            (block_id[order][1:] == block_id[order][:-1])
            & (key_len[order][1:] == key_len[order][:-1])
            & np.all(kb[1:] == kb[:-1], axis=1)
        )
    return order, dup


def padded_key_words(
    data: np.ndarray,
    key_start: np.ndarray,
    key_len: np.ndarray,
    pad_to: int = 0,
) -> np.ndarray:
    """(m, W) native-u64 words of the zero-padded key bytes (big-endian
    within each word, so numeric order == lexicographic byte order;
    equal padded words + equal length <=> equal key).  ``pad_to``
    forces a common byte width across separate calls (multi-buffer
    callers gathering per source)."""
    m = key_start.size
    max_len = int(key_len.max()) if m else 0
    lpad = max(8, pad_to, ((max_len + 7) // 8) * 8)
    if m == 0:
        return np.zeros((0, lpad // 8), dtype=np.uint64)
    lanes = np.arange(lpad, dtype=np.uint64)
    pos = key_start.astype(np.uint64)[:, None] + lanes
    valid = lanes < key_len.astype(np.uint64)[:, None]
    pos = np.minimum(pos, np.uint64(max(0, data.size - 1)))
    mat = np.where(valid, data[pos.astype(np.int64)], 0).astype(
        np.uint8
    )
    return (
        np.ascontiguousarray(mat)
        .view(np.dtype(">u8"))
        .astype(np.uint64)
        .reshape(m, lpad // 8)
    )


def tie_block_widths(
    block_id: np.ndarray, key_len: np.ndarray
) -> np.ndarray:
    """Per-entry padded-key byte width, bounded by the entry's BLOCK
    max key length (pow2-multiples-of-8 buckets): one long-key outlier
    widens only its own bucket's key matrix, not every tie entry's."""
    if block_id.size == 0:
        return np.zeros(0, np.int64)
    nblocks = int(block_id[-1]) + 1
    blk_max = np.zeros(nblocks, dtype=np.int64)
    np.maximum.at(blk_max, block_id, key_len.astype(np.int64))
    widths = np.empty(nblocks, np.int64)
    for b in np.unique(blk_max):
        c = (int(b) + 7) // 8
        p = 1
        while p < max(1, c):
            p <<= 1
        widths[blk_max == b] = 8 * p
    return widths[block_id]


def fixup_and_dedup_prefix(
    cols: MergeColumns, perm: np.ndarray, words: int = KEY_PREFIX_WORDS
):
    """Vectorized tie fixup + dedup: one lexsort per key-width bucket
    over the tie-block entries (full padded key, ~ts, ~src) instead of
    per-entry Python compares.  Returns (perm, keep)."""
    n = perm.size
    keep = np.ones(n, dtype=bool)
    if n <= 1:
        return perm, keep
    kw = cols.key_words[perm]
    flags = np.all(kw[1:, :words] == kw[:-1, :words], axis=1)
    positions, block_id = tie_positions_and_blocks(flags)
    if positions.size == 0:
        return perm, keep
    sel = perm[positions]
    ks = cols.key_size[sel]
    inv_ts = ~cols.timestamp[sel]
    inv_src = ~cols.src[sel]
    ent_w = tie_block_widths(block_id, ks)
    perm = perm.copy()
    for w in np.unique(ent_w):
        bm = ent_w == w
        kwords = padded_key_words(
            cols.data,
            cols.start[sel[bm]] + np.uint64(ENTRY_HEADER_SIZE),
            ks[bm],
            pad_to=int(w),
        )
        order, dup = tie_block_sort(
            block_id[bm], kwords, ks[bm], inv_ts[bm], inv_src[bm]
        )
        sub_pos = positions[bm]
        perm[sub_pos] = sel[bm][order]
        keep[sub_pos] = ~dup
    return perm, keep


def fixup_long_key_ties(cols: MergeColumns, perm: np.ndarray) -> np.ndarray:
    """Re-sort prefix-tie blocks containing keys longer than the prefix.

    After the columnar sort, all entries sharing an exact 16-byte prefix
    are contiguous.  If any of them extends past the prefix, (prefix,
    key_len) no longer determines lexicographic order, so the block is
    re-sorted on the host with full-key compares.  Never triggers when
    keys fit the prefix (e.g. the 16-byte-key benchmark)."""
    if perm.size <= 1:
        return perm
    kw = cols.key_words[perm]
    ks = cols.key_size[perm]
    same_prefix = np.all(kw[1:] == kw[:-1], axis=1)
    long = ks > KEY_PREFIX_BYTES
    tie = same_prefix & (long[1:] | long[:-1])
    if not tie.any():
        return perm
    perm = perm.copy()
    for lo, hi in _flags_to_runs(tie):
        block = perm[lo:hi]
        order = sorted(
            range(block.size),
            key=lambda j: (
                full_key(cols, int(block[j])),
                ~cols.timestamp[block[j]],
                ~cols.src[block[j]],
            ),
        )
        perm[lo:hi] = block[np.array(order)]
    return perm


def dedup_mask(cols: MergeColumns, perm: np.ndarray) -> np.ndarray:
    """keep-first-per-key over the sorted permutation (newest wins)."""
    n = perm.size
    keep = np.ones(n, dtype=bool)
    if n <= 1:
        return keep
    kw = cols.key_words[perm]
    ks = cols.key_size[perm]
    same = np.all(kw[1:] == kw[:-1], axis=1) & (ks[1:] == ks[:-1])
    # Prefix+len equality is only provisional for long keys: confirm with
    # full compares there (runs are already correctly ordered by fixup).
    long = ks > KEY_PREFIX_BYTES
    suspect = np.flatnonzero(same & (long[1:] | long[:-1]))
    if suspect.size:
        for j in suspect:
            if full_key(cols, int(perm[j + 1])) != full_key(
                cols, int(perm[j])
            ):
                same[j] = False
    keep[1:] = ~same
    return keep


def ranges_to_positions(
    starts: np.ndarray, lengths: np.ndarray
) -> np.ndarray:
    """Expand (start, length) ranges into one flat index vector.

    Vectorized multi-range gather: out[k] indexes every byte of every
    range, in range order."""
    lengths = lengths.astype(np.int64)
    starts = starts.astype(np.int64)
    total = int(lengths.sum())
    if total == 0:
        return np.zeros(0, dtype=np.int64)
    step = np.ones(total, dtype=np.int64)
    step[0] = starts[0]
    ends = np.cumsum(lengths)[:-1]
    if ends.size:
        step[ends] = starts[1:] - (starts[:-1] + lengths[:-1] - 1)
    return np.cumsum(step)


def gather_records_array(
    cols: MergeColumns, order: np.ndarray
) -> np.ndarray:
    """Raw records selected by ``order`` (post-dedup) as one uint8
    array (no extra bytes copy — write it in chunks)."""
    if order.size == 0:
        return np.zeros(0, dtype=np.uint8)
    fs = cols.full_size
    rec = int(fs[0])
    if cols.data.size == fs.size * rec and (fs == fs[0]).all():
        # Uniform records: row-gather of an (N, rec) view — orders of
        # magnitude faster than the per-byte position expansion.
        if (cols.start == np.arange(fs.size, dtype=np.uint64) * rec).all():
            return cols.data.reshape(-1, rec)[order].reshape(-1)
    pos = ranges_to_positions(
        cols.start[order], cols.full_size[order]
    )
    return cols.data[pos]


def gather_records(cols: MergeColumns, order: np.ndarray) -> bytes:
    return gather_records_array(cols, order).tobytes()
