"""EntryWriter — streams an SSTable's data + index files concurrently.

Role parity with /root/reference/src/storage_engine/entry_writer.rs:18-160:
entries are appended to the data stream while fixed 16-byte offset records
go to the index stream; both mirror completed pages into the page cache so
a freshly flushed/compacted SSTable reads hot.
"""

from __future__ import annotations

from typing import Optional

from .entry import (
    DATA_FILE_EXT,
    INDEX_ENTRY,
    INDEX_FILE_EXT,
    encode_entry,
    file_name,
)
from .file_io import PageMirroringWriter
from .page_cache import PartitionPageCache


class EntryWriter:
    def __init__(
        self,
        dir_path: str,
        index: int,
        cache: Optional[PartitionPageCache],
        data_ext: str = DATA_FILE_EXT,
        index_ext: str = INDEX_FILE_EXT,
    ) -> None:
        self.index = index
        self.data_path = f"{dir_path}/{file_name(index, data_ext)}"
        self.index_path = f"{dir_path}/{file_name(index, index_ext)}"
        # Cache keys use the *live* extension so pages written under a
        # compact_* name are warm after the rename (the reference keys by
        # FileType, which is likewise rename-invariant).
        self._data = PageMirroringWriter(
            self.data_path, (DATA_FILE_EXT, index), cache
        )
        self._index = PageMirroringWriter(
            self.index_path, (INDEX_FILE_EXT, index), cache
        )
        self.entries_written = 0

    @property
    def data_size(self) -> int:
        return self._data.written

    def write(self, key: bytes, value: bytes, timestamp: int) -> None:
        record = encode_entry(key, value, timestamp)
        offset = self._data.written
        self._data.write(record)
        self._index.write(INDEX_ENTRY.pack(offset, len(key), len(record)))
        self.entries_written += 1

    def write_raw(self, record: bytes, key_size: int) -> None:
        """Append an already-encoded record (device compaction gather)."""
        offset = self._data.written
        self._data.write(record)
        self._index.write(INDEX_ENTRY.pack(offset, key_size, len(record)))
        self.entries_written += 1

    def close(self, sync: bool = True) -> int:
        """Returns logical data size in bytes."""
        size = self._data.close(sync=sync)
        self._index.close(sync=sync)
        return size

    def page_crcs(self):
        """(data page CRCs, index page CRCs) accumulated by the
        mirroring writers — the inputs for the table's sums sidecar
        (storage/checksums.py); valid after close()."""
        return self._data.page_crcs, self._index.page_crcs

    @property
    def index_size(self) -> int:
        return self._index.written

    def abort(self) -> None:
        self._data.abort()
        self._index.abort()
