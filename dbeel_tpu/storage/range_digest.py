"""Vectorized anti-entropy range digests.

The per-entry async scan (MyShard.compute_range_digests' fallback)
pays interpreted-Python cost per entry — multi-second background load
per cycle on a big collection (round-2 ADVICE).  This module computes
the SAME per-bucket (count, digest) vectors with numpy + the native
murmur batch: one bulk read per sstable, one batch hash call per seed,
hash-group duplicate resolution, and an XOR scatter — ~20× cheaper
constants, identical results (golden-tested against the per-entry
path in tests/test_range_digest.py).

Semantics (must match MyShard's scalar path exactly):
  * every entry in every sstable + both memtables participates;
    tombstones count (deletions must converge);
  * per unique key, the NEWEST timestamp wins;
  * membership/bucket derive from murmur3_32(key) over the wrap range
    [start, end) split into ``nbuckets`` equal slices;
  * digest ^= murmur(key||ts_le8, SEED_A) | murmur(...SEED_B) << 32.
"""

from __future__ import annotations

import ctypes
from dataclasses import dataclass
from typing import List, Optional, Sequence, Tuple

import numpy as np

from . import native as native_mod
from .columnar import ranges_to_positions
from .entry import ENTRY_HEADER_SIZE

_SEED_A = 0x0A57E4A1
_SEED_B = 0x51C6E57A
_RING = 1 << 32
_MASK = _RING - 1

# Below this many total entries the executor hop + array setup costs
# more than the per-entry loop; callers should use the async path.
MIN_VECTORIZED_ENTRIES = 2048


@dataclass
class _Cols:
    """One scan source in columnar form: key bytes live in ``data`` at
    ``key_off``/``key_len``; ``ts`` is the entry timestamp."""

    data: np.ndarray  # uint8
    key_off: np.ndarray  # int64
    key_len: np.ndarray  # uint32
    ts: np.ndarray  # int64


def _sstable_cols(table) -> Optional[_Cols]:
    offs, ks, _fs = table.read_index_columns()
    n = offs.size
    if n == 0:
        return _Cols(
            np.zeros(0, np.uint8),
            np.zeros(0, np.int64),
            np.zeros(0, np.uint32),
            np.zeros(0, np.int64),
        )
    # memmap, not fromfile: the digest touches only header+key bytes,
    # so mapping keeps peak RAM at O(keys) instead of holding every
    # value byte of a (possibly ~GB) table in an anonymous buffer;
    # gathers and the native hash read through the OS page cache.
    data = np.memmap(table.data_path, dtype=np.uint8, mode="r")
    if data.size < int(offs[-1]) + ENTRY_HEADER_SIZE + int(ks[-1]):
        return None  # torn file view; let the caller fall back
    off64 = offs.astype(np.int64)
    # Timestamps: 8 LE bytes at offset+8.
    tpos = off64[:, None] + np.arange(8, 16, dtype=np.int64)[None, :]
    ts = (
        np.ascontiguousarray(data[tpos].reshape(n, 8))
        .view("<i8")
        .reshape(n)
        .astype(np.int64)
    )
    return _Cols(
        data, off64 + ENTRY_HEADER_SIZE, ks.astype(np.uint32), ts
    )


def _memtable_cols(items: Sequence[Tuple[bytes, bytes, int]]) -> _Cols:
    if not items:
        return _Cols(
            np.zeros(0, np.uint8),
            np.zeros(0, np.int64),
            np.zeros(0, np.uint32),
            np.zeros(0, np.int64),
        )
    keys = [k for k, _v, _ts in items]
    lens = np.array([len(k) for k in keys], dtype=np.uint32)
    offs = np.zeros(len(keys), dtype=np.int64)
    np.cumsum(lens[:-1], out=offs[1:])
    blob = np.frombuffer(b"".join(keys), dtype=np.uint8)
    ts = np.array([t for _k, _v, t in items], dtype=np.int64)
    return _Cols(blob, offs, lens, ts)


def _batch_hash(lib, cols: _Cols, seed: int) -> np.ndarray:
    out = np.empty(cols.key_off.size, dtype=np.uint32)
    if cols.key_off.size == 0:
        return out
    off_u64 = np.ascontiguousarray(cols.key_off.astype(np.uint64))
    lens = np.ascontiguousarray(cols.key_len)
    data = (
        cols.data
        if cols.data.flags["C_CONTIGUOUS"]
        else np.ascontiguousarray(cols.data)
    )
    lib.dbeel_murmur3_32_batch(
        # argtype is c_char_p: pass the buffer address via cast
        ctypes.cast(
            data.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
            ctypes.c_char_p,
        ),
        off_u64.ctypes.data_as(ctypes.POINTER(ctypes.c_uint64)),
        lens.ctypes.data_as(ctypes.POINTER(ctypes.c_uint32)),
        ctypes.c_uint64(cols.key_off.size),
        ctypes.c_uint32(seed),
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_uint32)),
    )
    return out


def range_members_mask(
    h: np.ndarray, start: int, end: int
) -> np.ndarray:
    """Vectorized _in_ae_range: half-open wrap [start, end); start ==
    end means the whole ring."""
    width = (end - start) & _MASK
    if width == 0:
        return np.ones(h.size, dtype=bool)
    d = (h.astype(np.uint64) - np.uint64(start)) & np.uint64(_MASK)
    return d < np.uint64(width)


def bucket_of(
    h: np.ndarray, start: int, end: int, nbuckets: int
) -> np.ndarray:
    """Vectorized MyShard._ae_bucket_of (same arithmetic, u64-safe:
    d * nbuckets stays < 2^48 for nbuckets <= 65536)."""
    width = (end - start) & _MASK
    if width == 0:
        width = _RING
    d = (h.astype(np.uint64) - np.uint64(start)) & np.uint64(_MASK)
    b = (d * np.uint64(nbuckets)) // np.uint64(width)
    return np.minimum(b, np.uint64(nbuckets - 1)).astype(np.int64)


def vectorized_range_digests(
    memtable_items: Sequence[Tuple[bytes, bytes, int]],
    tables: Sequence,
    start: int,
    end: int,
    nbuckets: int,
) -> Optional[Tuple[list, list]]:
    """Compute the per-bucket (counts, digests) vectors.  Returns None
    when the native murmur batch is unavailable or a table looks torn
    — the caller then uses the per-entry path.  CPU-heavy: run it
    off-loop on a scan snapshot (LSMTree.scan_snapshot)."""
    lib = native_mod.load_if_built()
    if lib is None:
        return None

    sources: List[_Cols] = []
    for t in tables:
        c = _sstable_cols(t)
        if c is None:
            return None
        sources.append(c)
    sources.append(_memtable_cols(memtable_items))

    hashes = [_batch_hash(lib, c, 0) for c in sources]
    n_total = sum(int(x.size) for x in hashes)
    counts = [0] * nbuckets
    digests = [0] * nbuckets
    if n_total == 0:
        return counts, digests

    h_all = np.concatenate(hashes)
    ts_all = np.concatenate([c.ts for c in sources])
    src_all = np.concatenate(
        [
            np.full(x.size, i, dtype=np.int32)
            for i, x in enumerate(hashes)
        ]
    )
    idx_all = np.concatenate(
        [np.arange(x.size, dtype=np.int64) for x in hashes]
    )

    member = range_members_mask(h_all, start, end)
    if not member.any():
        return counts, digests
    h = h_all[member]
    ts = ts_all[member]
    src = src_all[member]
    idx = idx_all[member]

    def key_bytes(s: int, i: int) -> bytes:
        c = sources[s]
        o = int(c.key_off[i])
        return c.data[o : o + int(c.key_len[i])].tobytes()

    # Resolve duplicates per unique KEY.  Sorting by (hash, ~ts) makes
    # every same-key cluster contiguous; singleton hashes (the vast
    # majority) are unique keys outright, and only multi-entry hash
    # groups — real duplicates plus rare 32-bit collisions — pay a
    # per-entry Python resolution.
    order = np.lexsort((-ts, h))
    h = h[order]
    ts = ts[order]
    src = src[order]
    idx = idx[order]
    boundary = np.empty(h.size, dtype=bool)
    boundary[0] = True
    boundary[1:] = h[1:] != h[:-1]
    group_id = np.cumsum(boundary) - 1
    group_sizes = np.bincount(group_id)
    singleton = group_sizes[group_id] == 1

    surv_src: List[int] = []
    surv_idx: List[int] = []
    surv_ts: List[int] = []
    surv_h: List[int] = []
    multi_groups = np.flatnonzero(group_sizes > 1)
    if multi_groups.size:
        starts_g = np.concatenate(
            [[0], np.cumsum(group_sizes)[:-1]]
        )
        for g in multi_groups:
            lo = int(starts_g[g])
            hi = lo + int(group_sizes[g])
            newest: dict = {}
            for j in range(lo, hi):  # already newest-first within h
                kb = key_bytes(int(src[j]), int(idx[j]))
                if kb not in newest:
                    newest[kb] = (int(ts[j]), int(h[j]), int(src[j]),
                                  int(idx[j]))
            for _kb, (t, hv, s, i) in newest.items():
                surv_ts.append(t)
                surv_h.append(hv)
                surv_src.append(s)
                surv_idx.append(i)

    fin_src = np.concatenate(
        [src[singleton], np.array(surv_src, dtype=np.int32)]
    )
    fin_idx = np.concatenate(
        [idx[singleton], np.array(surv_idx, dtype=np.int64)]
    )
    fin_ts = np.concatenate(
        [ts[singleton], np.array(surv_ts, dtype=np.int64)]
    )
    fin_h = np.concatenate(
        [h[singleton], np.array(surv_h, dtype=np.uint32)]
    )
    n = fin_src.size
    if n == 0:
        return counts, digests

    # Build the digest blobs (key || ts_le8) in one gather per source.
    lens = np.empty(n, dtype=np.uint32)
    for s, c in enumerate(sources):
        m = fin_src == s
        if m.any():
            lens[m] = c.key_len[fin_idx[m]]
    blob_lens = lens.astype(np.int64) + 8
    blob_offs = np.zeros(n, dtype=np.int64)
    np.cumsum(blob_lens[:-1], out=blob_offs[1:])
    blob = np.empty(int(blob_lens.sum()), dtype=np.uint8)
    for s, c in enumerate(sources):
        m = np.flatnonzero(fin_src == s)
        if m.size == 0:
            continue
        dst = ranges_to_positions(
            blob_offs[m], lens[m].astype(np.int64)
        )
        srcpos = ranges_to_positions(
            c.key_off[fin_idx[m]], lens[m].astype(np.int64)
        )
        blob[dst] = c.data[srcpos]
    ts_bytes = (
        np.ascontiguousarray(fin_ts.astype("<i8"))
        .view(np.uint8)
        .reshape(n, 8)
    )
    ts_dst = (blob_offs + lens)[:, None] + np.arange(
        8, dtype=np.int64
    )[None, :]
    blob[ts_dst.reshape(-1)] = ts_bytes.reshape(-1)

    bc = _Cols(
        blob, blob_offs, blob_lens.astype(np.uint32), fin_ts
    )
    d_lo = _batch_hash(lib, bc, _SEED_A).astype(np.uint64)
    d_hi = _batch_hash(lib, bc, _SEED_B).astype(np.uint64)
    d64 = d_lo | (d_hi << np.uint64(32))

    buckets = bucket_of(fin_h, start, end, nbuckets)
    cnt = np.bincount(buckets, minlength=nbuckets)
    dig = np.zeros(nbuckets, dtype=np.uint64)
    np.bitwise_xor.at(dig, buckets, d64)
    return (
        [int(x) for x in cnt[:nbuckets]],
        [int(x) for x in dig],
    )
