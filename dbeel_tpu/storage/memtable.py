"""Capacity-bounded memtables.

Role parity with the reference's arena red-black tree
(/root/reference/rbtree_arena/src/lib.rs:308-649): sorted in-memory map
with a hard capacity that drives the flush trigger (set errors / waits at
capacity, lsm_tree.rs:747-755), in-order iteration, and a consuming
drain for flush.

Two implementations share one contract (and produce byte-identical
SSTables):

* ``Memtable`` — ``sortedcontainers.SortedDict`` kept ordered per insert
  (the idiomatic analog of the reference's cache-friendly arena tree).
* ``HashMemtable`` — the TPU-first variant: a plain hash map (O(1)
  set/get, no per-insert ordering work) whose ordering debt is paid once
  at flush by the device sort (ops/sort.py) — the north star's
  "memtable flush becomes a single-run device sort" (BASELINE.json).
"""

from __future__ import annotations

from typing import Iterator, List, Optional, Tuple

try:
    from sortedcontainers import SortedDict
except ImportError:  # no wheel in the image: bisect-backed fallback
    from ..utils.sorteddict import SortedDict

from ..errors import MemtableCapacityReached

Item = Tuple[bytes, Tuple[bytes, int]]  # key -> (value, timestamp_ns)


class MemtableBase:
    """Shared capacity / conflict semantics; subclasses choose the map
    type and the ordering strategy."""

    def __init__(self, capacity: int) -> None:
        if capacity <= 0:
            raise ValueError("capacity must be positive")
        self.capacity = capacity
        self._map = self._new_map()
        self.data_bytes = 0  # approximate on-disk size of contents
        self.max_ts = 0  # newest timestamp ever inserted

    def _new_map(self):
        raise NotImplementedError

    def __len__(self) -> int:
        return len(self._map)

    def is_full(self) -> bool:
        return len(self._map) >= self.capacity

    def set(self, key: bytes, value: bytes, timestamp: int) -> None:
        """Insert/overwrite; errors at capacity for *new* keys, mirroring
        the arena's capacity error (rbtree_arena/src/lib.rs:7-10)."""
        if timestamp > self.max_ts:
            self.max_ts = timestamp
        prev = self._map.get(key)
        if prev is None:
            if len(self._map) >= self.capacity:
                raise MemtableCapacityReached(
                    f"memtable at capacity {self.capacity}"
                )
            self._map[key] = (value, timestamp)
            self.data_bytes += 16 + len(key) + len(value)
        elif timestamp >= prev[1]:
            # Keep the newest timestamp (reference updates in place).
            self._map[key] = (value, timestamp)
            self.data_bytes += len(value) - len(prev[0])

    def set_batch(
        self, entries: List[Tuple[bytes, bytes, int]]
    ) -> int:
        """Insert entries in order until capacity; returns how many
        were applied.  When the whole batch fits under the CURRENT
        headroom the capacity predicate is evaluated ONCE up front
        (len + batch <= capacity is sufficient even if every key is
        new) and the per-entry insert skips it; otherwise entries
        apply one by one and the count stops at the first capacity
        refusal — the caller flush-waits and retries the remainder,
        exactly like the single-set path."""
        if len(self) + len(entries) > self.capacity:
            done = 0
            for key, value, ts in entries:
                try:
                    self.set(key, value, ts)
                except MemtableCapacityReached:
                    return done
                done += 1
            return done
        m = self._map
        for key, value, ts in entries:
            if ts > self.max_ts:
                self.max_ts = ts
            prev = m.get(key)
            if prev is None:
                m[key] = (value, ts)
                self.data_bytes += 16 + len(key) + len(value)
            elif ts >= prev[1]:
                m[key] = (value, ts)
                self.data_bytes += len(value) - len(prev[0])
        return len(entries)

    def get(self, key: bytes) -> Optional[Tuple[bytes, int]]:
        return self._map.get(key)

    def items(self) -> Iterator[Item]:
        return iter(self._map.items())

    def sorted_items(self) -> List[Item]:
        raise NotImplementedError

    def range(
        self, lo: Optional[bytes] = None, hi: Optional[bytes] = None
    ) -> Iterator[Item]:
        # Default: linear filter over the sorted view (hash pays an
        # O(n log n) sort on first call after a write, cached after;
        # the sorted Memtable overrides with irange).
        for key, val in self.sorted_items():
            if (lo is None or key >= lo) and (hi is None or key <= hi):
                yield key, val


class Memtable(MemtableBase):
    def _new_map(self):
        return SortedDict()

    def sorted_items(self) -> List[Item]:
        return list(self._map.items())

    def range(
        self, lo: Optional[bytes] = None, hi: Optional[bytes] = None
    ) -> Iterator[Item]:
        for key in self._map.irange(lo, hi):
            yield key, self._map[key]


class ArenaMemtable(MemtableBase):
    """C++ arena red-black tree (native/), the direct analog of the
    reference's rbtree_arena crate (lib.rs:308-649): nodes in one
    pre-allocated array, capacity-bounded, sorted in-order iteration.
    Same contract and byte-identical SSTables as the Python maps; the
    per-insert cost moves from interpreted SortedDict bookkeeping to a
    native tree walk."""

    def __init__(self, capacity: int) -> None:
        import ctypes

        from . import native as native_mod

        lib = native_mod.load_if_built()
        if lib is None:
            raise RuntimeError("native library unavailable")
        self._lib = lib
        self._ctypes = ctypes
        self._handle = lib.dbeel_memtable_new(capacity)
        if not self._handle:
            raise MemoryError("arena memtable allocation failed")
        super().__init__(capacity)

    def _new_map(self):
        return None  # storage lives in the native arena

    def __len__(self) -> int:
        return int(self._lib.dbeel_memtable_len(self._handle))

    def __del__(self):
        handle = getattr(self, "_handle", None)
        if handle:
            self._lib.dbeel_memtable_free(handle)
            self._handle = None

    def is_full(self) -> bool:
        return len(self) >= self.capacity

    @property
    def max_ts(self) -> int:
        # The C side tracks it (the native data plane writes bypass
        # this wrapper entirely).
        if hasattr(self._lib, "dbeel_memtable_max_ts"):
            return int(self._lib.dbeel_memtable_max_ts(self._handle))
        return 0

    @max_ts.setter
    def max_ts(self, _v) -> None:
        pass  # base __init__ assigns 0; the C counter is the truth

    def set(self, key: bytes, value: bytes, timestamp: int) -> None:
        ct = self._ctypes
        old_len = ct.c_uint32(0)
        rc = self._lib.dbeel_memtable_set(
            self._handle,
            key,
            len(key),
            value,
            len(value),
            timestamp,
            ct.byref(old_len),
        )
        if rc == -1:
            raise MemtableCapacityReached(
                f"memtable at capacity {self.capacity}"
            )
        if rc == -2:
            raise MemoryError("arena memtable allocation failed")
        if rc == 0:
            self.data_bytes += 16 + len(key) + len(value)
        elif rc == 1:
            self.data_bytes += len(value) - int(old_len.value)

    def get(self, key: bytes) -> Optional[Tuple[bytes, int]]:
        ct = self._ctypes
        val = ct.POINTER(ct.c_uint8)()
        vlen = ct.c_uint32(0)
        ts = ct.c_int64(0)
        if not self._lib.dbeel_memtable_get(
            self._handle,
            key,
            len(key),
            ct.byref(val),
            ct.byref(vlen),
            ct.byref(ts),
        ):
            return None
        # Copy out: the pointer aliases the arena and is only valid
        # until the next set.
        return (
            ct.string_at(val, vlen.value) if vlen.value else b"",
            int(ts.value),
        )

    def set_batch(
        self, entries: List[Tuple[bytes, bytes, int]]
    ) -> int:
        # The arena enforces capacity natively per insert (its node
        # pool is the real bound), so the base class's single up-front
        # check cannot be hoisted; stop-at-refusal semantics match.
        done = 0
        for key, value, ts in entries:
            try:
                self.set(key, value, ts)
            except MemtableCapacityReached:
                return done
            done += 1
        return done

    def sorted_items(self) -> List[Item]:
        ct = self._ctypes
        size = int(self._lib.dbeel_memtable_dump_size(self._handle))
        buf = bytearray(max(1, size))
        n = int(
            self._lib.dbeel_memtable_dump(
                self._handle,
                (ct.c_uint8 * len(buf)).from_buffer(buf),
            )
        )
        raw = bytes(buf)  # one immutable view; slices below share it
        items: List[Item] = []
        off = 0
        for _ in range(n):
            klen = int.from_bytes(raw[off : off + 4], "little")
            vlen = int.from_bytes(raw[off + 4 : off + 8], "little")
            ts = int.from_bytes(
                raw[off + 8 : off + 16], "little", signed=True
            )
            key = raw[off + 16 : off + 16 + klen]
            value = raw[off + 16 + klen : off + 16 + klen + vlen]
            items.append((key, (value, ts)))
            off += 16 + klen + vlen
        return items

    def items(self) -> Iterator[Item]:
        return iter(self.sorted_items())

    @property
    def has_native_flush(self) -> bool:
        """Single capability predicate for the flush dispatch (the
        LSMTree call site keys on this, not on library internals)."""
        return hasattr(self._lib, "dbeel_memtable_flush_write")

    def flush_to_sstable(
        self, dir_path: str, index: int, bloom_min_size: int
    ) -> int:
        """Write this memtable to the SSTable triplet in ONE native
        call (data + index + bloom, byte-identical to the Python
        EntryWriter path, golden-tested).  The ctypes call releases
        the GIL for the whole walk+write, so a flush no longer stalls
        the serving loop — the config-1 Set p999 fix.  Returns the
        entry count; raises on I/O failure (partial outputs are
        unlinked natively)."""
        if not self.has_native_flush:
            raise RuntimeError("native flush writer unavailable")
        rc = int(
            self._lib.dbeel_memtable_flush_write(
                self._handle,
                dir_path.encode(),
                index,
                bloom_min_size,
            )
        )
        if rc < 0:
            raise OSError(
                f"native memtable flush failed for index {index}"
            )
        return rc

    def flush_to_sstable_with_sums(
        self, dir_path: str, index: int, bloom_min_size: int
    ) -> "Tuple[int, bool]":
        """Single-pass flush (ISSUE 15): triplet write + inline
        ``.sums`` sidecar in one GIL-free call — the C writer
        page-CRCs every byte AS it emits it, so the sidecar costs
        zero re-reads (the old path re-read the whole freshly-written
        triplet).  Returns ``(entry_count, sums_written)``;
        ``sums_written`` False means the library predates the ABI (or
        a cap raced) and the caller must fall back to the post-hoc
        sidecar."""
        ct = self._ctypes
        lib = self._lib
        if not hasattr(lib, "dbeel_memtable_flush_write2"):
            return (
                self.flush_to_sstable(dir_path, index, bloom_min_size),
                False,
            )
        # The dump byte format IS the data-file record format, so the
        # dump size bounds the data file exactly; the index file is
        # 16 bytes per entry.  +1 page of slack costs 4 bytes.
        data_bytes = int(lib.dbeel_memtable_dump_size(self._handle))
        n_entries = int(lib.dbeel_memtable_len(self._handle))
        data_cap = data_bytes // 4096 + 2
        index_cap = (n_entries * 16) // 4096 + 2
        data_crcs = (ct.c_uint32 * data_cap)()
        index_crcs = (ct.c_uint32 * index_cap)()
        n_data = ct.c_uint64(0)
        n_index = ct.c_uint64(0)
        bloom_crc = ct.c_uint32(0)
        wrote_bloom = ct.c_int32(0)
        rc = int(
            lib.dbeel_memtable_flush_write2(
                self._handle,
                dir_path.encode(),
                index,
                bloom_min_size,
                data_crcs,
                data_cap,
                index_crcs,
                index_cap,
                ct.byref(n_data),
                ct.byref(n_index),
                ct.byref(bloom_crc),
                ct.byref(wrote_bloom),
            )
        )
        if rc == -1:
            raise OSError(
                f"native memtable flush failed for index {index}"
            )
        if rc == -2:
            # Triplet IS complete on disk; only the CRC handoff was
            # refused (cap mismatch — should not happen given the
            # exact sizing above).  Post-hoc sidecar covers it.
            return n_entries, False
        from . import checksums

        checksums.write_crcs(
            dir_path,
            index,
            list(data_crcs[: n_data.value]),
            list(index_crcs[: n_index.value]),
            data_bytes,
            int(bloom_crc.value),
            bool(wrote_bloom.value),
        )
        return rc, True


class HashMemtable(MemtableBase):
    def _new_map(self):
        self._sorted_cache: Optional[List[Item]] = None
        return {}

    def set(self, key: bytes, value: bytes, timestamp: int) -> None:
        self._sorted_cache = None
        super().set(key, value, timestamp)

    def set_batch(
        self, entries: List[Tuple[bytes, bytes, int]]
    ) -> int:
        self._sorted_cache = None
        return super().set_batch(entries)

    def sorted_items(self) -> List[Item]:
        if self._sorted_cache is None:
            from ..ops.sort import sort_items

            self._sorted_cache = sort_items(list(self._map.items()))
        return self._sorted_cache
