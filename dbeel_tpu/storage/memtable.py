"""Capacity-bounded sorted memtable.

Role parity with the reference's arena red-black tree
(/root/reference/rbtree_arena/src/lib.rs:308-649): sorted in-memory map
with a hard capacity that drives the flush trigger (set errors / waits at
capacity, lsm_tree.rs:747-755), in-order forward iteration, and a
consuming drain for flush.

The idiomatic rebuild uses ``sortedcontainers.SortedDict`` (B-tree-ish
list-of-lists — the same cache-friendly contiguous-storage idea as the
arena).  The flush *sort* itself is a no-op here because the structure is
kept sorted; the device flush path instead drains insertion order and
sorts on the TPU (ops.sort) — both produce identical SSTables.
"""

from __future__ import annotations

from typing import Iterator, Optional, Tuple

from sortedcontainers import SortedDict

from ..errors import MemtableCapacityReached

Item = Tuple[bytes, Tuple[bytes, int]]  # key -> (value, timestamp_ns)


class Memtable:
    def __init__(self, capacity: int) -> None:
        if capacity <= 0:
            raise ValueError("capacity must be positive")
        self.capacity = capacity
        self._map: SortedDict = SortedDict()
        self.data_bytes = 0  # approximate on-disk size of contents

    def __len__(self) -> int:
        return len(self._map)

    def is_full(self) -> bool:
        return len(self._map) >= self.capacity

    def set(self, key: bytes, value: bytes, timestamp: int) -> None:
        """Insert/overwrite; errors at capacity for *new* keys, mirroring
        the arena's capacity error (rbtree_arena/src/lib.rs:7-10)."""
        prev = self._map.get(key)
        if prev is None:
            if len(self._map) >= self.capacity:
                raise MemtableCapacityReached(
                    f"memtable at capacity {self.capacity}"
                )
            self._map[key] = (value, timestamp)
            self.data_bytes += 16 + len(key) + len(value)
        else:
            # Keep the newest timestamp (reference updates in place).
            if timestamp >= prev[1]:
                self._map[key] = (value, timestamp)
                self.data_bytes += len(value) - len(prev[0])

    def get(self, key: bytes) -> Optional[Tuple[bytes, int]]:
        return self._map.get(key)

    def items(self) -> Iterator[Item]:
        """Key-ascending iteration (rbtree in-order iterator)."""
        return iter(self._map.items())

    def range(
        self, lo: Optional[bytes] = None, hi: Optional[bytes] = None
    ) -> Iterator[Item]:
        for key in self._map.irange(lo, hi):
            yield key, self._map[key]
