"""Capacity-bounded memtables.

Role parity with the reference's arena red-black tree
(/root/reference/rbtree_arena/src/lib.rs:308-649): sorted in-memory map
with a hard capacity that drives the flush trigger (set errors / waits at
capacity, lsm_tree.rs:747-755), in-order iteration, and a consuming
drain for flush.

Two implementations share one contract (and produce byte-identical
SSTables):

* ``Memtable`` — ``sortedcontainers.SortedDict`` kept ordered per insert
  (the idiomatic analog of the reference's cache-friendly arena tree).
* ``HashMemtable`` — the TPU-first variant: a plain hash map (O(1)
  set/get, no per-insert ordering work) whose ordering debt is paid once
  at flush by the device sort (ops/sort.py) — the north star's
  "memtable flush becomes a single-run device sort" (BASELINE.json).
"""

from __future__ import annotations

from typing import Iterator, List, Optional, Tuple

from sortedcontainers import SortedDict

from ..errors import MemtableCapacityReached

Item = Tuple[bytes, Tuple[bytes, int]]  # key -> (value, timestamp_ns)


class MemtableBase:
    """Shared capacity / conflict semantics; subclasses choose the map
    type and the ordering strategy."""

    def __init__(self, capacity: int) -> None:
        if capacity <= 0:
            raise ValueError("capacity must be positive")
        self.capacity = capacity
        self._map = self._new_map()
        self.data_bytes = 0  # approximate on-disk size of contents

    def _new_map(self):
        raise NotImplementedError

    def __len__(self) -> int:
        return len(self._map)

    def is_full(self) -> bool:
        return len(self._map) >= self.capacity

    def set(self, key: bytes, value: bytes, timestamp: int) -> None:
        """Insert/overwrite; errors at capacity for *new* keys, mirroring
        the arena's capacity error (rbtree_arena/src/lib.rs:7-10)."""
        prev = self._map.get(key)
        if prev is None:
            if len(self._map) >= self.capacity:
                raise MemtableCapacityReached(
                    f"memtable at capacity {self.capacity}"
                )
            self._map[key] = (value, timestamp)
            self.data_bytes += 16 + len(key) + len(value)
        elif timestamp >= prev[1]:
            # Keep the newest timestamp (reference updates in place).
            self._map[key] = (value, timestamp)
            self.data_bytes += len(value) - len(prev[0])

    def get(self, key: bytes) -> Optional[Tuple[bytes, int]]:
        return self._map.get(key)

    def items(self) -> Iterator[Item]:
        return iter(self._map.items())

    def sorted_items(self) -> List[Item]:
        raise NotImplementedError

    def range(
        self, lo: Optional[bytes] = None, hi: Optional[bytes] = None
    ) -> Iterator[Item]:
        raise NotImplementedError


class Memtable(MemtableBase):
    def _new_map(self):
        return SortedDict()

    def sorted_items(self) -> List[Item]:
        return list(self._map.items())

    def range(
        self, lo: Optional[bytes] = None, hi: Optional[bytes] = None
    ) -> Iterator[Item]:
        for key in self._map.irange(lo, hi):
            yield key, self._map[key]


class HashMemtable(MemtableBase):
    def _new_map(self):
        self._sorted_cache: Optional[List[Item]] = None
        return {}

    def set(self, key: bytes, value: bytes, timestamp: int) -> None:
        self._sorted_cache = None
        super().set(key, value, timestamp)

    def sorted_items(self) -> List[Item]:
        if self._sorted_cache is None:
            from ..ops.sort import sort_items

            self._sorted_cache = sort_items(list(self._map.items()))
        return self._sorted_cache

    def range(
        self, lo: Optional[bytes] = None, hi: Optional[bytes] = None
    ) -> Iterator[Item]:
        # O(n log n) on first call after a write (cached after); the
        # sorted Memtable is the right choice for range-heavy loads.
        for key, val in self.sorted_items():
            if (lo is None or key >= lo) and (hi is None or key <= hi):
                yield key, val
