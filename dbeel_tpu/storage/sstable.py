"""SSTable reader: data + 16-byte-record index + optional bloom.

Role parity with the reference's SSTable triplet and binary-search read
path (/root/reference/src/storage_engine/lsm_tree.rs:86-99 struct,
605-670 binary_search, 690-696 bloom gate).
"""

from __future__ import annotations

import asyncio
import os
import threading
from array import array
from bisect import bisect_left, bisect_right
from typing import Iterator, Optional, Tuple

import numpy as np

from .bloom import BloomFilter
from .entry import (
    BLOOM_FILE_EXT,
    DATA_FILE_EXT,
    ENTRY_HEADER,
    ENTRY_HEADER_SIZE,
    INDEX_ENTRY,
    INDEX_ENTRY_SIZE,
    INDEX_FILE_EXT,
    PAGE_SIZE,
    decode_entry,
    file_name,
)
from .file_io import CachedFileReader
from .page_cache import PartitionPageCache


class SSTable:
    def __init__(
        self,
        dir_path: str,
        index: int,
        cache: Optional[PartitionPageCache],
        counters: Optional[dict] = None,
    ) -> None:
        from . import checksums

        self.dir_path = dir_path
        self.index = index
        self.data_path = os.path.join(
            dir_path, file_name(index, DATA_FILE_EXT)
        )
        self.index_path = os.path.join(
            dir_path, file_name(index, INDEX_FILE_EXT)
        )
        self.bloom_path = os.path.join(
            dir_path, file_name(index, BLOOM_FILE_EXT)
        )
        self.sums_path = checksums.sums_path(dir_path, index)
        # Secondary index run + sidecar (may not exist): included in
        # paths() so the run retires/quarantines in lockstep with its
        # data triplet.
        from .entry import FIDX_FILE_EXT, FIDX_SUMS_FILE_EXT

        self.fidx_path = os.path.join(
            dir_path, file_name(index, FIDX_FILE_EXT)
        )
        self.fidx_sums_path = os.path.join(
            dir_path, file_name(index, FIDX_SUMS_FILE_EXT)
        )
        # CRC sidecar (checksums.py): None = legacy/unverified table
        # (pre-checksum store, or a sidecar that failed its own
        # trailer CRC) — it opens read-only as ever, just without
        # per-page verification.
        self.sums = checksums.load(dir_path, index)
        self._counters = counters  # tree durability counters (or None)
        self._data = CachedFileReader(
            self.data_path,
            (DATA_FILE_EXT, index),
            cache,
            crcs=self.sums.data_crcs if self.sums else None,
        )
        self._index = CachedFileReader(
            self.index_path,
            (INDEX_FILE_EXT, index),
            cache,
            crcs=self.sums.index_crcs if self.sums else None,
        )
        self.entry_count = self._index.size // INDEX_ENTRY_SIZE
        self.data_size = self._data.size
        self.bloom: Optional[BloomFilter] = None
        try:
            with open(self.bloom_path, "rb") as f:
                raw_bloom = f.read()
        except FileNotFoundError:
            raw_bloom = None
        if raw_bloom is not None:
            # The bloom is read once, here: verify the whole file.  A
            # corrupt bloom is NOT a quarantine case — it is a pure
            # optimization, so degrade to bloomless probing (every get
            # pays the binary search) instead of dropping good data.
            import zlib as _zlib

            ok = not (
                self.sums is not None
                and self.sums.has_bloom
                and checksums.verification_enabled()
                and _zlib.crc32(raw_bloom) != self.sums.bloom_crc
            )
            if ok:
                try:
                    self.bloom = BloomFilter.deserialize(raw_bloom)
                except Exception:
                    ok = False
            if not ok:
                import logging

                logging.getLogger(__name__).warning(
                    "sstable %s: bloom failed validation; probing "
                    "without it",
                    self.bloom_path,
                )
                if counters is not None:
                    counters["checksum_failures"] = (
                        counters.get("checksum_failures", 0) + 1
                    )
        # Lazily-built in-memory read index (see _build_read_index):
        # dense below the caps, sparse above them — no table-size cliff.
        self._fast: Optional[tuple] = None
        self._sparse: Optional[tuple] = None
        self._fast_tried = False
        self._build_lock = threading.Lock()
        self._build_future = None  # single-flight async build

    def close(self) -> None:
        self._data.close()
        self._index.close()

    def paths(self) -> Tuple[str, ...]:
        return (
            self.data_path,
            self.index_path,
            self.bloom_path,
            self.sums_path,
            self.fidx_path,
            self.fidx_sums_path,
        )

    @property
    def verified(self) -> bool:
        """True when this table carries a CRC sidecar (reads verify)."""
        return self.sums is not None

    def _corrupt(self, path: str, what: str):
        from ..errors import CorruptedFile

        exc = CorruptedFile(f"{path}: {what}")
        exc.path = path
        return exc

    # -- point lookup ---------------------------------------------------

    def maybe_contains(self, key: bytes) -> bool:
        return self.bloom is None or self.bloom.check(key)

    def _index_record(self, i: int) -> Tuple[int, int, int]:
        raw = self._index.read_at(i * INDEX_ENTRY_SIZE, INDEX_ENTRY_SIZE)
        return INDEX_ENTRY.unpack(raw)

    # In-memory DENSE index limits (24B/entry of RAM when built).
    FAST_INDEX_MAX_ENTRIES = 1 << 20
    FAST_INDEX_MAX_DATA = 32 << 20
    # Above the dense caps, a SPARSE index samples every Nth key's
    # 8-byte prefix (8B RAM per N entries — ~5MB for a 10M-key table):
    # a lookup is one searchsorted plus a <=2N-entry binary search
    # through the page cache, killing the round-1 cliff where tables
    # over the cap fell back to a full-table walk (VERDICT weak #5).
    SPARSE_STRIDE = 16

    def _build_read_index(self) -> None:
        """Build the in-RAM read index — dense (prefix + index columns)
        for small tables, sparse sampled prefixes for big ones.
        Thread-safe and idempotent; runs in an executor when warmed or
        lazily from the serving path."""
        with self._build_lock:
            if self._fast_tried or self.entry_count == 0:
                self._fast_tried = True
                return
            from . import columnar

            dense = (
                self.entry_count <= self.FAST_INDEX_MAX_ENTRIES
                and self.data_size <= self.FAST_INDEX_MAX_DATA
            )
            if dense:
                offs, ks, fs = self.read_index_columns()
                data = np.frombuffer(
                    self.read_data_bytes(), dtype=np.uint8
                )
                words = columnar.prefix_words(
                    data, offs.astype(np.uint64), ks
                )
                p1, p2 = self._prefix_pair(words)
                self._fast = (p1, p2, offs, ks, fs)
            else:
                stride = self.SPARSE_STRIDE
                from . import checksums as _ck

                verify = (
                    self.sums is not None
                    and _ck.verification_enabled()
                )
                # memmap both files: only the touched pages are read
                # and no whole-index RAM copy is made (~160MB for a
                # 10M-key table).
                if verify:
                    # The strided walk touches every index page anyway
                    # (stride 16 × 16 B = one sample per 256 B), so a
                    # full index verification costs the same I/O.
                    mm = np.memmap(
                        self.index_path, dtype=np.uint8, mode="r"
                    )
                    self._verify_pages_mm(
                        mm,
                        self.sums.index_crcs,
                        range(len(self.sums.index_crcs)),
                        self.index_path,
                    )
                    del mm
                idx = np.memmap(
                    self.index_path,
                    dtype=np.dtype(
                        [
                            ("offset", "<u8"),
                            ("key_size", "<u4"),
                            ("full_size", "<u4"),
                        ]
                    ),
                    mode="r",
                )
                s_offs = np.array(idx["offset"][::stride], np.uint64)
                s_ks = np.array(idx["key_size"][::stride], np.uint32)
                del idx
                data = np.memmap(
                    self.data_path, dtype=np.uint8, mode="r"
                )
                if verify:
                    # Verify exactly the data pages the sampled key
                    # prefixes will be gathered from — those pages
                    # fault in for the gather regardless; a flipped
                    # bit in a sample would otherwise silently skew
                    # the candidate range into a false miss.
                    lo = (
                        s_offs + np.uint64(ENTRY_HEADER_SIZE)
                    ) // np.uint64(PAGE_SIZE)
                    hi = (
                        s_offs
                        + np.uint64(ENTRY_HEADER_SIZE + 16 - 1)
                    ) // np.uint64(PAGE_SIZE)
                    pages = np.unique(np.concatenate([lo, hi]))
                    self._verify_pages_mm(
                        data,
                        self.sums.data_crcs,
                        pages.tolist(),
                        self.data_path,
                    )
                words = columnar.prefix_words(data, s_offs, s_ks)
                del data
                p1, p2 = self._prefix_pair(words)
                self._sparse = (p1, p2, stride)
            self._fast_tried = True

    def _verify_pages_mm(self, mm_u8, crcs, pages, path) -> None:
        """CRC-check specific 4 KiB pages of a uint8 memmap (sparse
        read-index build — runs off-loop)."""
        import zlib as _zlib

        n = len(mm_u8)
        for p in pages:
            lo = int(p) * PAGE_SIZE
            if lo >= n:
                continue
            page = bytes(mm_u8[lo : lo + PAGE_SIZE])
            if len(page) < PAGE_SIZE:
                page = page + b"\x00" * (PAGE_SIZE - len(page))
            if int(p) >= len(crcs) or _zlib.crc32(page) != crcs[int(p)]:
                raise self._corrupt(
                    path, f"page {int(p)} failed its CRC"
                )

    def _verify_whole(self, raw, kind: str) -> None:
        """Bulk-read verification (dense read-index build, compaction
        columnarize): one sequential CRC pass over the whole buffer."""
        from . import checksums as _ck

        if self.sums is None or not _ck.verification_enabled():
            return
        if not self.sums.verify_buffer(kind, raw, len(raw)):
            raise self._corrupt(
                self.data_path if kind == "data" else self.index_path,
                "bulk read failed CRC verification",
            )

    @staticmethod
    def _prefix_pair(words: "np.ndarray"):
        """Two-level 16-byte prefix as a pair of sorted array('Q')s:
        bytes 0-8 and bytes 8-16.  Realistic keyspaces cluster under a
        shared head ("user:...", "key-000..."), which collapses a
        single 8-byte prefix index into one giant tie range and turns
        every get into a full-table page-cache binary search; the
        second level re-narrows inside first-level ties via
        bisect(lo, hi) at the same O(log) cost."""
        p1 = (
            words[:, 0].astype(np.uint64) << np.uint64(32)
        ) | words[:, 1].astype(np.uint64)
        p2 = (
            words[:, 2].astype(np.uint64) << np.uint64(32)
        ) | words[:, 3].astype(np.uint64)
        return SSTable._as_q(p1), SSTable._as_q(p2)

    @staticmethod
    def _as_q(prefix: "np.ndarray") -> array:
        """stdlib array('Q') of the sorted prefixes: bisect on it costs
        ~0.8µs/probe vs ~3µs for a numpy searchsorted at point-lookup
        sizes (scalar-call overhead dominates tiny queries)."""
        q = array("Q")
        # native byte order: array('Q') decodes machine-endian, and the
        # probe values are plain Python ints.
        q.frombytes(prefix.astype("=u8").tobytes())
        return q

    def warm(self) -> None:
        """Executor hook: build the read index off-loop so first reads
        don't pay the bulk scan.  Swallows failures (including CRC
        mismatches): the serving read path re-detects them through the
        verified page reads and drives quarantine from there — a warm
        must never crash a flush/compaction commit."""
        try:
            self._build_read_index()
        except Exception:
            import logging

            logging.getLogger(__name__).warning(
                "sstable %d read-index warm failed", self.index,
                exc_info=True,
            )

    def _sparse_range(self, key: bytes) -> Tuple[int, int]:
        """Candidate [lo, hi) entry range for ``key`` from the sparse
        sampled two-level prefixes."""
        p1, p2, stride = self._sparse
        w1 = self._key_prefix64(key)
        lo_s = bisect_left(p1, w1)
        hi_s = bisect_right(p1, w1)
        if hi_s - lo_s > 1:
            w2 = self._key_prefix64b(key)
            lo_s = bisect_left(p2, w2, lo_s, hi_s)
            hi_s = bisect_right(p2, w2, lo_s, hi_s)
        # One sample of slack on the left (the -1) and right (the
        # hi_s-th sample is the first PAST the match, and entries up
        # to it may still match): entries between samples are not
        # represented in p1/p2.
        lo = (lo_s - 1) * stride if lo_s > 0 else 0
        hi = min(self.entry_count, hi_s * stride)
        return lo, hi

    @staticmethod
    def _key_prefix64(key: bytes) -> int:
        return int.from_bytes(key[:8].ljust(8, b"\x00"), "big")

    @staticmethod
    def _key_prefix64b(key: bytes) -> int:
        return int.from_bytes(key[8:16].ljust(8, b"\x00"), "big")

    def _lookup_range(self, key: bytes):
        """(lo, hi, arrays|None): candidate entry range + in-RAM index
        columns when the dense index is present."""
        if self._fast is not None:
            p1, p2, offs, ks, fs = self._fast
            w = self._key_prefix64(key)
            lo = bisect_left(p1, w)
            hi = bisect_right(p1, w)
            if hi - lo > 1:
                w2 = self._key_prefix64b(key)
                lo = bisect_left(p2, w2, lo, hi)
                hi = bisect_right(p2, w2, lo, hi)
            return lo, hi, (offs, ks, fs)
        if self._sparse is not None:
            lo, hi = self._sparse_range(key)
            return lo, hi, None
        return 0, self.entry_count, None

    def get(self, key: bytes) -> Optional[Tuple[bytes, int]]:
        """Point lookup; returns (value, ts).  Dense path: in-memory
        prefix searchsorted + full-key search in the tie range; sparse
        path: sampled-prefix range + page-cache search; fallback:
        whole-table binary search (lsm_tree.rs:605-670)."""
        if not self._fast_tried:
            self._build_read_index()
        lo, hi, arrays = self._lookup_range(key)
        while lo < hi:
            mid = (lo + hi) // 2
            if arrays is not None:
                offs, ks, fs = arrays
                offset, key_size, full_size = (
                    int(offs[mid]),
                    int(ks[mid]),
                    int(fs[mid]),
                )
            else:
                offset, key_size, full_size = self._index_record(mid)
            mid_key = bytes(
                self._data.read_at(
                    offset + ENTRY_HEADER_SIZE, key_size
                )
            )
            if mid_key == key:
                record = self._data.read_at(offset, full_size)
                _, value, ts, _ = decode_entry(record)
                return value, ts
            if mid_key < key:
                lo = mid + 1
            else:
                hi = mid
        return None

    # Sentinel: the cache-only probe couldn't decide (a page missed).
    _CACHE_MISS = object()

    def _get_cached(self, key: bytes):
        """Fully-synchronous probe that touches ONLY cached pages:
        returns (value, ts), None (definitively absent), or
        _CACHE_MISS when any needed page is cold.  Keeps the warm
        serving path free of coroutine hops."""
        lo, hi, arrays = self._lookup_range(key)
        while lo < hi:
            mid = (lo + hi) // 2
            if arrays is not None:
                offs, ks, fs = arrays
                offset, key_size, full_size = (
                    int(offs[mid]),
                    int(ks[mid]),
                    int(fs[mid]),
                )
            else:
                raw = self._index.read_at_cached(
                    mid * INDEX_ENTRY_SIZE, INDEX_ENTRY_SIZE
                )
                if raw is None:
                    return self._CACHE_MISS
                offset, key_size, full_size = INDEX_ENTRY.unpack(raw)
            mid_key = self._data.read_at_cached(
                offset + ENTRY_HEADER_SIZE, key_size
            )
            if mid_key is None:
                return self._CACHE_MISS
            if mid_key == key:
                record = self._data.read_at_cached(offset, full_size)
                if record is None:
                    return self._CACHE_MISS
                _, value, ts, _ = decode_entry(record)
                return value, ts
            if mid_key < key:
                lo = mid + 1
            else:
                hi = mid
        return None

    async def get_async(self, key: bytes) -> Optional[Tuple[bytes, int]]:
        """get() that keeps disk off the event loop: the read-index
        build runs in an executor (single-flight), warm probes resolve
        synchronously from cached pages, and cold probes go through
        read_at_async (misses in one executor pread per probe).  The
        reference's analog is the io_uring DMA read path
        (cached_file_reader.rs:28-88)."""
        if not self._fast_tried:
            if self._build_future is None:
                self._build_future = (
                    asyncio.get_event_loop().run_in_executor(
                        None, self._build_read_index
                    )
                )
            try:
                await self._build_future
            except Exception as e:
                # Transient build failure (fd/memory pressure): don't
                # poison the table — retry on the next get; the disk
                # binary-search fallback below works meanwhile.
                # CORRUPTION is not transient: re-raise so the LSM
                # read path quarantines the table instead of paying a
                # doomed whole-file build on every get.
                self._build_future = None
                from ..errors import CorruptedFile

                if isinstance(e, CorruptedFile):
                    raise
        hit = self._get_cached(key)
        if hit is not self._CACHE_MISS:
            return hit
        lo, hi, arrays = self._lookup_range(key)
        while lo < hi:
            mid = (lo + hi) // 2
            if arrays is not None:
                offs, ks, fs = arrays
                offset, key_size, full_size = (
                    int(offs[mid]),
                    int(ks[mid]),
                    int(fs[mid]),
                )
            else:
                raw = await self._index.read_at_async(
                    mid * INDEX_ENTRY_SIZE, INDEX_ENTRY_SIZE
                )
                offset, key_size, full_size = INDEX_ENTRY.unpack(raw)
            mid_key = bytes(
                await self._data.read_at_async(
                    offset + ENTRY_HEADER_SIZE, key_size
                )
            )
            if mid_key == key:
                record = await self._data.read_at_async(
                    offset, full_size
                )
                _, value, ts, _ = decode_entry(record)
                return value, ts
            if mid_key < key:
                lo = mid + 1
            else:
                hi = mid
        return None

    # -- sequential access ---------------------------------------------

    def entries(self) -> Iterator[Tuple[bytes, bytes, int]]:
        """Stream (key, value, ts) in file order via the cached readers
        (AsyncIter's per-entry walk, lsm_tree.rs:241-271)."""
        for i in range(self.entry_count):
            offset, _key_size, full_size = self._index_record(i)
            record = self._data.read_at(offset, full_size)
            key, value, ts, _ = decode_entry(record)
            yield key, value, ts

    # -- bulk columnar access (device compaction path) ------------------

    def read_index_columns(self) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
        """Whole index file as (offsets u64, key_sizes u32, full_sizes u32)
        column arrays in one read — the host→device staging format."""
        with open(self.index_path, "rb") as f:
            raw = f.read(self.entry_count * INDEX_ENTRY_SIZE)
        self._verify_whole(raw, "index")
        rec = np.frombuffer(
            raw,
            dtype=np.dtype(
                [("offset", "<u8"), ("key_size", "<u4"), ("full_size", "<u4")]
            ),
        )
        return (
            rec["offset"].copy(),
            rec["key_size"].copy(),
            rec["full_size"].copy(),
        )

    def read_data_bytes(self) -> bytes:
        """Whole data file in one bulk read (bypasses the page cache on
        purpose — compaction inputs are about to be deleted)."""
        with open(self.data_path, "rb") as f:
            raw = f.read(self.data_size)
        self._verify_whole(raw, "data")
        return raw
