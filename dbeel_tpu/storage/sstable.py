"""SSTable reader: data + 16-byte-record index + optional bloom.

Role parity with the reference's SSTable triplet and binary-search read
path (/root/reference/src/storage_engine/lsm_tree.rs:86-99 struct,
605-670 binary_search, 690-696 bloom gate).
"""

from __future__ import annotations

import os
from typing import Iterator, Optional, Tuple

import numpy as np

from .bloom import BloomFilter
from .entry import (
    BLOOM_FILE_EXT,
    DATA_FILE_EXT,
    ENTRY_HEADER,
    ENTRY_HEADER_SIZE,
    INDEX_ENTRY,
    INDEX_ENTRY_SIZE,
    INDEX_FILE_EXT,
    decode_entry,
    file_name,
)
from .file_io import CachedFileReader
from .page_cache import PartitionPageCache


class SSTable:
    def __init__(
        self,
        dir_path: str,
        index: int,
        cache: Optional[PartitionPageCache],
    ) -> None:
        self.dir_path = dir_path
        self.index = index
        self.data_path = os.path.join(
            dir_path, file_name(index, DATA_FILE_EXT)
        )
        self.index_path = os.path.join(
            dir_path, file_name(index, INDEX_FILE_EXT)
        )
        self.bloom_path = os.path.join(
            dir_path, file_name(index, BLOOM_FILE_EXT)
        )
        self._data = CachedFileReader(
            self.data_path, (DATA_FILE_EXT, index), cache
        )
        self._index = CachedFileReader(
            self.index_path, (INDEX_FILE_EXT, index), cache
        )
        self.entry_count = self._index.size // INDEX_ENTRY_SIZE
        self.data_size = self._data.size
        self.bloom: Optional[BloomFilter] = None
        try:
            with open(self.bloom_path, "rb") as f:
                self.bloom = BloomFilter.deserialize(f.read())
        except FileNotFoundError:
            pass
        # Lazily-built in-memory prefix index (see _fast_index).
        self._fast: Optional[tuple] = None
        self._fast_tried = False

    def close(self) -> None:
        self._data.close()
        self._index.close()

    def paths(self) -> Tuple[str, ...]:
        return (self.data_path, self.index_path, self.bloom_path)

    # -- point lookup ---------------------------------------------------

    def maybe_contains(self, key: bytes) -> bool:
        return self.bloom is None or self.bloom.check(key)

    def _index_record(self, i: int) -> Tuple[int, int, int]:
        raw = self._index.read_at(i * INDEX_ENTRY_SIZE, INDEX_ENTRY_SIZE)
        return INDEX_ENTRY.unpack(raw)

    def _key_at(self, i: int) -> Tuple[bytes, int, int, int]:
        offset, key_size, full_size = self._index_record(i)
        key = self._data.read_at(offset + ENTRY_HEADER_SIZE, key_size)
        return key, offset, key_size, full_size

    # In-memory fast index limits (24B/entry of RAM when built).  The
    # data cap bounds the synchronous bulk read if the build happens
    # lazily on a serving path (the LSM tree pre-warms new tables in an
    # executor, so this is the cold-open worst case only).
    FAST_INDEX_MAX_ENTRIES = 1 << 20
    FAST_INDEX_MAX_DATA = 32 << 20

    def _fast_index(self) -> Optional[tuple]:
        """(prefix_u64_sorted, offsets, key_sizes, full_sizes) — lets a
        point lookup be ONE numpy searchsorted + usually one data read,
        instead of ~log2(n) page-cache probes through Python.  Built
        lazily on first get; skipped for very large tables."""
        if self._fast_tried:
            return self._fast
        self._fast_tried = True
        if (
            self.entry_count > self.FAST_INDEX_MAX_ENTRIES
            or self.data_size > self.FAST_INDEX_MAX_DATA
            or self.entry_count == 0
        ):
            return None
        from . import columnar

        offs, ks, fs = self.read_index_columns()
        data = np.frombuffer(self.read_data_bytes(), dtype=np.uint8)
        words = columnar.prefix_words(data, offs.astype(np.uint64), ks)
        prefix = (
            words[:, 0].astype(np.uint64) << np.uint64(32)
        ) | words[:, 1].astype(np.uint64)
        self._fast = (prefix, offs, ks, fs)
        return self._fast

    @staticmethod
    def _key_prefix64(key: bytes) -> int:
        return int.from_bytes(key[:8].ljust(8, b"\x00"), "big")

    def get(self, key: bytes) -> Optional[Tuple[bytes, int]]:
        """Point lookup; returns (value, ts).  Fast path: in-memory
        prefix searchsorted; fallback: on-disk binary search through the
        page cache (lsm_tree.rs:605-670)."""
        fast = self._fast_index()
        if fast is not None:
            prefix, offs, ks, fs = fast
            w = np.uint64(self._key_prefix64(key))
            lo = int(np.searchsorted(prefix, w, side="left"))
            hi = int(np.searchsorted(prefix, w, side="right"))
            # Binary search on full keys within the prefix-tie range
            # (realistic keyspaces share prefixes, so hi-lo can be big).
            while lo < hi:
                mid = (lo + hi) // 2
                mid_key = bytes(
                    self._data.read_at(
                        int(offs[mid]) + ENTRY_HEADER_SIZE,
                        int(ks[mid]),
                    )
                )
                if mid_key == key:
                    record = self._data.read_at(
                        int(offs[mid]), int(fs[mid])
                    )
                    _, value, ts, _ = decode_entry(record)
                    return value, ts
                if mid_key < key:
                    lo = mid + 1
                else:
                    hi = mid
            return None
        lo, hi = 0, self.entry_count - 1
        while lo <= hi:
            mid = (lo + hi) // 2
            mid_key, offset, key_size, full_size = self._key_at(mid)
            if mid_key == key:
                record = self._data.read_at(offset, full_size)
                _, value, ts, _ = decode_entry(record)
                return value, ts
            if mid_key < key:
                lo = mid + 1
            else:
                hi = mid - 1
        return None

    # -- sequential access ---------------------------------------------

    def entries(self) -> Iterator[Tuple[bytes, bytes, int]]:
        """Stream (key, value, ts) in file order via the cached readers
        (AsyncIter's per-entry walk, lsm_tree.rs:241-271)."""
        for i in range(self.entry_count):
            offset, _key_size, full_size = self._index_record(i)
            record = self._data.read_at(offset, full_size)
            key, value, ts, _ = decode_entry(record)
            yield key, value, ts

    # -- bulk columnar access (device compaction path) ------------------

    def read_index_columns(self) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
        """Whole index file as (offsets u64, key_sizes u32, full_sizes u32)
        column arrays in one read — the host→device staging format."""
        with open(self.index_path, "rb") as f:
            raw = f.read(self.entry_count * INDEX_ENTRY_SIZE)
        rec = np.frombuffer(
            raw,
            dtype=np.dtype(
                [("offset", "<u8"), ("key_size", "<u4"), ("full_size", "<u4")]
            ),
        )
        return (
            rec["offset"].copy(),
            rec["key_size"].copy(),
            rec["full_size"].copy(),
        )

    def read_data_bytes(self) -> bytes:
        """Whole data file in one bulk read (bypasses the page cache on
        purpose — compaction inputs are about to be deleted)."""
        with open(self.data_path, "rb") as f:
            return f.read(self.data_size)
