"""LSM tree — the single-shard storage engine.

Role parity with /root/reference/src/storage_engine/lsm_tree.rs:
memtable(active + flushing) / WAL / SSTable(data+index+bloom) triplets;
get via memtables → bloom → per-sstable binary search newest→oldest;
set → WAL (page-padded record) + memtable with auto-flush at capacity;
pluggable merge compaction (strategy seam) with tombstone drop on the
bottom level; crash safety via (1) WAL replay, (2) the two-WAL flush
protocol, (3) an idempotent compact-action journal; snapshot-consistent
iteration with reader-drain before input deletion.

Index numbering follows the reference: flushed sstables take even indices
0,2,4,…; a flush first creates WAL index+2, writes sstable ``index``,
then deletes WAL ``index`` (lsm_tree.rs:854-921); compaction outputs take
``max(inputs)+1`` (odd), which ranks them correctly between the remaining
older and newer tables.
"""

from __future__ import annotations

import asyncio
import errno
import logging
import os
import re
import shutil
from typing import AsyncIterator, Callable, List, Optional, Sequence, Tuple

import msgpack
import numpy as np

from .. import flow_events
from ..errors import (
    CorruptedFile,
    MemtableCapacityReached,
    ShardDegraded,
    TooManyWalFiles,
)
from ..utils.event import LocalEvent
from ..utils.timestamps import now_nanos
from . import checksums
from . import file_io
from . import wal as wal_mod
from .bloom import BloomFilter
from .compaction import CompactionStrategy, HeapMergeStrategy
from .entry import (
    BLOOM_FILE_EXT,
    COMPACT_ACTION_FILE_EXT,
    COMPACT_BLOOM_FILE_EXT,
    COMPACT_DATA_FILE_EXT,
    COMPACT_FIDX_FILE_EXT,
    COMPACT_FIDX_SUMS_FILE_EXT,
    COMPACT_INDEX_FILE_EXT,
    COMPACT_SUMS_FILE_EXT,
    DATA_FILE_EXT,
    FIDX_FILE_EXT,
    FIDX_SUMS_FILE_EXT,
    INDEX_FILE_EXT,
    MEMTABLE_FILE_EXT,
    SUMS_FILE_EXT,
    TOMBSTONE,
    file_name,
)
from .entry_writer import EntryWriter
from .memtable import HashMemtable, Memtable
from .page_cache import PartitionPageCache
from .sstable import SSTable

log = logging.getLogger(__name__)

DEFAULT_TREE_CAPACITY = 8192  # reference mod.rs:18
DEFAULT_BLOOM_MIN_SIZE = 1 << 20

_FILE_RE = re.compile(r"^(\d{20})\.(\w+)$")

# Free-space floors (overridable for tests / tiny hosts): a flush or
# compaction that would fill the disk backs off instead of half-writing
# a triplet and cascading into ENOSPC quarantines.
MIN_FREE_BYTES = int(
    os.environ.get("DBEEL_MIN_FREE_BYTES", str(32 << 20))
)
QUARANTINE_DIR = "quarantine"

# Errnos that mean the DISK (not the caller) failed — the degraded-mode
# escalation set.
_DISK_ERRNOS = frozenset(
    {errno.EIO, errno.ENOSPC, errno.EROFS, errno.EDQUOT}
)


class SSTableList:
    """Refcounted sstable vector: compaction swaps the list and waits
    until readers drain before deleting inputs (lsm_tree.rs:1141-1145)."""

    def __init__(self, tables: List[SSTable]) -> None:
        self.tables = sorted(tables, key=lambda t: t.index)
        self.readers = 0
        self.drained = LocalEvent()

    def acquire(self) -> None:
        self.readers += 1

    def release(self) -> None:
        self.readers -= 1
        if self.readers == 0:
            self.drained.notify()


class ScanSnapshot:
    """Point-in-time scan view (see LSMTree.scan_snapshot)."""

    def __init__(self, memtable_items, sstables: SSTableList) -> None:
        self.memtable_items = memtable_items
        self._sstables = sstables
        self._released = False

    @property
    def tables(self):
        return self._sstables.tables

    def release(self) -> None:
        if not self._released:
            self._released = True
            self._sstables.release()


class LSMTree:
    def __init__(
        self,
        dir_path: str,
        cache: Optional[PartitionPageCache] = None,
        capacity: int = DEFAULT_TREE_CAPACITY,
        wal_sync: bool = False,
        wal_sync_delay_us: int = 0,
        bloom_min_size: int = DEFAULT_BLOOM_MIN_SIZE,
        strategy: Optional[CompactionStrategy] = None,
        memtable_kind: str = "sorted",
        gc_grace_s: float = 0.0,
        index_fields: Optional[list] = None,
    ) -> None:
        self.dir_path = dir_path
        # Secondary-index DDL (ISSUE 17): value fields whose per-table
        # index runs the flush/compaction writers emit inline and the
        # scan planner consults.  None/empty = no index maintenance.
        self.index_fields = list(index_fields) if index_fields else None
        self.cache = cache
        self.capacity = capacity
        self.wal_sync = wal_sync
        self.wal_sync_delay_us = wal_sync_delay_us
        self.bloom_min_size = bloom_min_size
        # Tombstone GC grace (delete-resurrection hazard): a
        # drop-tombstones compaction keeps any tombstone younger than
        # this window, so a replica that missed the delete (down past
        # its hints, anti-entropy not yet run) cannot resurrect the
        # old value after the tombstone would have been GC'd.  0 =
        # reference behavior (drop all at the bottom level).
        self.gc_grace_s = gc_grace_s
        self.strategy = strategy or HeapMergeStrategy()
        # "sorted" = SortedDict kept ordered per insert (reference's
        # rbtree contract); "hash" = O(1) dict, ordered once at flush by
        # the device sort (ops/sort.py) — the north-star flush path;
        # "arena" = the C++ arena red-black tree (native/), the direct
        # rbtree_arena analog (falls back to "sorted" if unbuilt).
        if memtable_kind not in ("auto", "sorted", "hash", "arena"):
            raise ValueError(
                f"memtable_kind must be 'auto', 'sorted', 'hash' or "
                f"'arena', got {memtable_kind!r}"
            )
        if memtable_kind == "auto":
            # Arena when the native library is present: it is the
            # rbtree_arena analog AND what the native serving data
            # plane writes into; otherwise the Python sorted map.
            from .native import load_if_built

            memtable_kind = (
                "arena" if load_if_built() is not None else "sorted"
            )
        self.memtable_kind = memtable_kind
        if memtable_kind == "hash":
            self._memtable_cls = HashMemtable
        elif memtable_kind == "arena":
            from .native import load_if_built

            if load_if_built() is not None:
                from .memtable import ArenaMemtable

                self._memtable_cls = ArenaMemtable
            else:
                log.warning(
                    "memtable_kind=arena: native library not built; "
                    "using the sorted Python memtable"
                )
                self._memtable_cls = Memtable
        else:
            self._memtable_cls = Memtable

        self._active = self._memtable_cls(capacity)
        # WAL appends into the active memtable since its last swap —
        # the update-heavy flush trigger (see set_with_timestamp).
        self._appends_since_swap = 0
        # Newest timestamp that may exist in a FLUSHED layer
        # (conservative: stamped with wall clock at each swap and at
        # recovery).  Explicit-timestamp replica/hint/AE writes at or
        # below it must take the read-guarded apply path: point reads
        # resolve by LAYER order (first match), so inserting an
        # OLDER-ts version into a fresh memtable above a flushed
        # newer one would serve the stale value until compaction —
        # the stuck-divergence class the scale-churn soak caught.
        self.max_flushed_ts = 0
        self._flushing: Optional[Memtable] = None
        self._sstables = SSTableList([])
        self._wal: Optional[wal_mod.Wal] = None
        self._index = 0  # next flush sstable index (even)
        self._is_flushing = False
        # (flush_index, old_wal) of a swap whose sstable write hasn't
        # committed yet; survives a failed attempt so the next flush()
        # retries it instead of clobbering the flushing memtable.
        self._pending_flush: Optional[Tuple[int, wal_mod.Wal]] = None
        self._disposing_wal: Optional[wal_mod.Wal] = None

        # ---- durability plane (PR 3) ------------------------------
        # Degraded mode: WAL EIO/ENOSPC flips the tree read-only —
        # writes raise ShardDegraded (clients walk to healthy
        # replicas) while reads keep serving.
        self.read_only = False
        # Escalation hooks wired by the owning shard: disk errors flip
        # the whole shard degraded; a quarantine spawns a replica
        # repair pull.
        self.on_disk_error: Optional[Callable] = None
        self.on_quarantine: Optional[Callable] = None
        # Change-feed hook (ISSUE 20): fired once per acked mutation
        # at the WAL group-commit release point — after the append's
        # sync ticket releases, before the caller sees success — with
        # (key, value, timestamp).  Stale-aborted inserts never fire
        # (they were not applied).  Wired by the owning shard's watch
        # plane; None when no watch plane observes this tree.
        self.on_commit: Optional[Callable] = None
        self.durability = {
            "checksum_failures": 0,
            "quarantined_tables": 0,
            "repairs_completed": 0,
        }
        self._quarantined_indices: set = set()
        # Quarantines not yet covered by a completed repair: while
        # non-zero, a local miss is SUSPECT (the key may have lived in
        # the dropped table) and read paths surface CorruptedFile
        # instead of a confident absence.
        self._quarantine_pending = 0
        # Highest PENDING quarantined index: any surviving-table hit
        # from a LOWER index is equally suspect under single-evidence
        # reads — the quarantined newer table may have held a newer
        # value or a tombstone that would shadow it (resurrection
        # hazard).  Reset when repairs cover every pending quarantine.
        self._suspect_max_index = -1
        # In-flight quarantine file moves (reader-drain + os.replace):
        # finish_repair must not race them when deleting quarantine/.
        self._retire_tasks: set = set()

        # Streaming scan plane (PR 12): cached vectorized scan stage
        # (key-sorted deduplicated columns) + the validity token and
        # the sstable-list reader ref that pins its files.
        self._scan_stage = None
        self._scan_stage_key: Optional[tuple] = None
        self._scan_stage_list: Optional[SSTableList] = None
        # Secondary-index runs (ISSUE 17): table index -> IndexRun (or
        # None for absent/torn), loaded lazily off-loop by the scan
        # planner; invalidated with the scan stage.  Quarantined run
        # indices never reload until the table itself turns over.
        self._index_runs: dict = {}
        self._fidx_quarantined: set = set()

        self.flush_start_event = LocalEvent()
        self.flush_done_event = LocalEvent()
        self.flow = flow_events.FlowEventNotifier()
        # Serving-data-plane hook: called with this tree whenever the
        # write state (active/flushing memtable, WAL) changes, so the
        # native fast path re-registers fresh handles.
        self.write_state_listener = None

    # ------------------------------------------------------------------
    # Open / recovery (lsm_tree.rs:401-545)
    # ------------------------------------------------------------------

    @classmethod
    def open_or_create(cls, dir_path: str, **kwargs) -> "LSMTree":
        tree = cls(dir_path, **kwargs)
        tree._open()
        return tree

    def _scan_dir(self):
        by_ext: dict = {}
        for name in os.listdir(self.dir_path):
            m = _FILE_RE.match(name)
            if m:
                by_ext.setdefault(m.group(2), []).append(int(m.group(1)))
        return by_ext

    def _open(self) -> None:
        os.makedirs(self.dir_path, exist_ok=True)

        # (1) Idempotent compact-action journal replay (424-438).
        for name in sorted(os.listdir(self.dir_path)):
            if name.endswith("." + COMPACT_ACTION_FILE_EXT):
                self._replay_compact_action(
                    os.path.join(self.dir_path, name)
                )

        # Orphaned compact_* outputs (crash before the journal was
        # written) are garbage: delete them.
        for name in os.listdir(self.dir_path):
            m = _FILE_RE.match(name)
            if m and m.group(2) in (
                COMPACT_DATA_FILE_EXT,
                COMPACT_INDEX_FILE_EXT,
                COMPACT_BLOOM_FILE_EXT,
                COMPACT_SUMS_FILE_EXT,
                COMPACT_FIDX_FILE_EXT,
                COMPACT_FIDX_SUMS_FILE_EXT,
            ):
                os.unlink(os.path.join(self.dir_path, name))

        by_ext = self._scan_dir()
        data_indices = sorted(
            set(by_ext.get(DATA_FILE_EXT, []))
            & set(by_ext.get(INDEX_FILE_EXT, []))
        )
        wal_indices = sorted(by_ext.get(MEMTABLE_FILE_EXT, []))

        if len(wal_indices) > 2:
            raise TooManyWalFiles(
                f"{len(wal_indices)} WAL files in {self.dir_path}"
            )

        # (2) Two-WAL flush protocol (478-513): two WALs mean a flush of
        # the older one was interrupted — complete it now.
        if len(wal_indices) == 2:
            older, newer = wal_indices
            if newer != older + 2:
                raise CorruptedFile(
                    f"unexpected WAL pair {wal_indices} in {self.dir_path}"
                )
            recovered = Memtable(max(self.capacity, 1 << 30))
            try:
                for key, value, ts in wal_mod.replay(
                    self._wal_path(older)
                ):
                    recovered.set(key, value, ts)
            except FileNotFoundError:
                # An in-process close->reopen can race the previous
                # instance's off-loop disposal: the retired WAL
                # vanished between our listing and this open.  Only
                # disposal unlinks WALs, and it runs strictly after
                # the flush commit — the contents are already durable
                # in an sstable, so there is nothing to recover.
                # (replay streams from an open fd, so a mid-iteration
                # vanish is impossible; the race is open-time only.)
                recovered = Memtable(1)
            if len(recovered):
                self._write_sstable_from_items(
                    older, recovered.sorted_items()
                )
                if older not in data_indices:
                    data_indices.append(older)
                    data_indices.sort()
            try:
                os.unlink(self._wal_path(older))
            except FileNotFoundError:
                pass  # the racing disposal beat us to it
            wal_indices = [newer]

        # (3) Load sstables.
        self._sstables = SSTableList(
            [
                SSTable(
                    self.dir_path, i, self.cache,
                    counters=self.durability,
                )
                for i in data_indices
            ]
        )

        # (4) WAL replay into the active memtable (552-574).
        if wal_indices:
            self._index = wal_indices[0]
            replayed = Memtable(max(self.capacity, 1 << 30))
            replay_appends = 0
            for key, value, ts in wal_mod.replay(
                self._wal_path(self._index)
            ):
                replayed.set(key, value, ts)
                replay_appends += 1
            self._active = self._memtable_cls(
                max(self.capacity, len(replayed) + 1)
            )
            for key, (value, ts) in replayed.items():
                self._active.set(key, value, ts)
            # The replayed WAL can hold far more appends than live
            # keys (the very workload the append trigger bounds):
            # carry its append count so a post-recovery write flushes
            # promptly instead of growing this WAL further.
            self._appends_since_swap = replay_appends
        else:
            self._index = (
                (max(data_indices) // 2 + 1) * 2 if data_indices else 0
            )
        self._wal = wal_mod.Wal(
            self._wal_path(self._index),
            sync=self.wal_sync,
            sync_delay_us=self.wal_sync_delay_us,
            on_error=self._report_disk_error,
        )
        if data_indices or wal_indices:
            # Anything recovered from disk may hold entries up to
            # "now" (or beyond, under clock skew — cover the replayed
            # WAL's real newest ts); later old-ts writes must go
            # read-guarded.
            self.max_flushed_ts = max(
                now_nanos(),
                int(getattr(self._active, "max_ts", 0) or 0),
            )
        self._notify_write_state()

    def _notify_write_state(self) -> None:
        # Scan plane: every write-state change (flush swap, table-list
        # swap, quarantine) invalidates the cached scan stage HERE —
        # not lazily on the next scan — because compaction and
        # quarantine retirement wait for the old list's readers to
        # drain, and a cached stage's reader ref with no scan running
        # would stall them indefinitely.
        self._drop_scan_stage()
        if self.write_state_listener is not None:
            try:
                self.write_state_listener(self)
            except Exception:
                log.exception("write_state_listener failed")

    def _wal_path(self, index: int) -> str:
        return os.path.join(
            self.dir_path, file_name(index, MEMTABLE_FILE_EXT)
        )

    def _replay_compact_action(self, path: str) -> None:
        try:
            with open(path, "rb") as f:
                action = msgpack.unpackb(f.read(), raw=False)
        except Exception:
            os.unlink(path)  # torn journal write: compaction never
            return  # committed; inputs are all still live.
        for src, dst in action.get("renames", []):
            if os.path.exists(src):
                os.replace(src, dst)
        for victim in action.get("deletes", []):
            if os.path.exists(victim):
                os.unlink(victim)
        os.unlink(path)

    # ------------------------------------------------------------------
    # Durability plane: disk-error escalation + corruption quarantine
    # (no reference analog — the reference trusts every byte it reads
    # back and dies on WAL I/O errors).
    # ------------------------------------------------------------------

    def _report_disk_error(self, e: BaseException) -> None:
        """Escalate a disk-level failure (WAL append/fsync EIO/ENOSPC,
        flush/compaction out of space): flip this tree read-only and
        tell the shard so it degrades the whole serving plane instead
        of dying mid-pipeline.  Always called on the loop thread."""
        if isinstance(e, OSError) and (
            e.errno is not None and e.errno not in _DISK_ERRNOS
        ):
            return  # EBADF during a close race etc. — not the disk
        first = not self.read_only
        self.read_only = True
        if first:
            log.error(
                "disk failure on %s: entering read-only degraded "
                "mode (%s)",
                self.dir_path,
                e,
            )
            self.flow.notify(flow_events.FlowEvent.SHARD_DEGRADED)
        if self.on_disk_error is not None:
            try:
                self.on_disk_error(e)
            except Exception:
                log.exception("on_disk_error callback failed")

    async def rearm_precheck(self) -> None:
        """Admin ``rearm`` pre-checks (operator replaced the disk):
        prove this store's filesystem is writable again — free space
        back above the flush floor, plus a write+fsync round trip
        through the same fault seam the WAL append path uses —
        WITHOUT clearing read-only (the shard layer does, once every
        collection's tree passes).  Raises ShardDegraded while the
        disk is still bad.  The probe uses a scratch file, not the
        live WAL: a post-EIO WAL fd may be stale regardless, and the
        flush the shard spawns right after re-arming rotates to a
        fresh WAL anyway (two-WAL protocol) — if THAT still fails,
        the on_error hook re-degrades immediately."""
        probe = os.path.join(self.dir_path, ".rearm-probe")
        if file_io.free_disk_space(probe) < MIN_FREE_BYTES:
            raise ShardDegraded(
                f"rearm {self.dir_path}: still below the "
                f"free-space floor"
            )

        def _probe_write() -> None:
            file_io.check_write_fault(probe)
            fd = os.open(
                probe, os.O_WRONLY | os.O_CREAT | os.O_TRUNC, 0o644
            )
            try:
                os.write(fd, b"\x00" * 4096)
                os.fsync(fd)
            finally:
                os.close(fd)
                try:
                    os.unlink(probe)
                except OSError:
                    pass

        try:
            await asyncio.get_event_loop().run_in_executor(
                None, _probe_write
            )
        except OSError as e:
            raise ShardDegraded(
                f"rearm {self.dir_path}: WAL-append probe failed: {e}"
            ) from e

    @property
    def reads_suspect(self) -> bool:
        """True while a quarantine awaits repair: a local miss may be
        LOST data, not a genuine absence — callers answering clients
        from this tree alone must error (retryable) instead."""
        return self._quarantine_pending > 0

    def quarantine_table(self, table: SSTable, reason: str) -> None:
        """Contain a corrupt table: drop it from the read set NOW
        (synchronously — the very next probe must not touch it), purge
        its page-cache entries, and move its files aside off-loop once
        in-flight readers drain.  Never unlinks: the quarantined
        triplet is retired only after a completed replica repair
        (finish_repair) — extending the torn-journal containment at
        _replay_compact_action to read-path corruption."""
        if table.index in self._quarantined_indices:
            return
        self._quarantined_indices.add(table.index)
        self.durability["quarantined_tables"] += 1
        self._quarantine_pending += 1
        self._suspect_max_index = max(
            self._suspect_max_index, table.index
        )
        log.error(
            "quarantining sstable %d of %s: %s",
            table.index,
            self.dir_path,
            reason,
        )
        old_list = self._sstables
        self._sstables = SSTableList(
            [t for t in old_list.tables if t.index != table.index]
        )
        if self.cache is not None:
            # A recycled (ext, index) file id must never serve the
            # corrupt (or merely stale) pages.
            self.cache.invalidate_file((DATA_FILE_EXT, table.index))
            self.cache.invalidate_file((INDEX_FILE_EXT, table.index))
        self._notify_write_state()
        retire = asyncio.ensure_future(
            self._retire_quarantined_files(old_list, table)
        )
        self._retire_tasks.add(retire)
        retire.add_done_callback(self._retire_tasks.discard)
        if self.on_quarantine is not None:
            try:
                self.on_quarantine(self)
            except Exception:
                log.exception("on_quarantine callback failed")
        self.flow.notify(flow_events.FlowEvent.TABLE_QUARANTINED)

    def _handle_table_corruption(
        self, table: SSTable, exc: BaseException
    ) -> None:
        self.durability["checksum_failures"] += 1
        self.quarantine_table(table, str(exc))

    def quarantine_by_exception(self, exc, tables) -> bool:
        """Attribute a bulk-read CorruptedFile to its source table by
        the ``.path`` the verifier stamped (the compaction-merge
        pattern) and quarantine it.  Used by the scan paths
        (anti-entropy digests, range collection) whose readers are
        table-agnostic: without this, a corrupt page found by a SCAN
        raised without quarantining — repair never started, and every
        later scan re-tripped on the same page.  Returns True when a
        victim was identified and quarantined."""
        bad = self._table_index_from_path(getattr(exc, "path", None))
        if bad is None:
            return False
        victim = next(
            (t for t in tables if t.index == bad), None
        )
        if victim is None:
            return False
        self._handle_table_corruption(victim, exc)
        return True

    async def _retire_quarantined_files(self, old_list, table) -> None:
        # Reader drain first (same contract as compaction input
        # deletion): probes already inside the old snapshot may still
        # hold offsets into these files.
        while old_list.readers > 0:
            await old_list.drained.listen()
        table.close()
        qdir = os.path.join(self.dir_path, QUARANTINE_DIR)

        def _move():
            os.makedirs(qdir, exist_ok=True)
            for p in table.paths():
                try:
                    if os.path.exists(p):
                        os.replace(
                            p, os.path.join(qdir, os.path.basename(p))
                        )
                except OSError:
                    log.warning("quarantine move failed for %s", p)

        await asyncio.get_event_loop().run_in_executor(None, _move)

    def finish_repair(self, covered: int, recovered: bool = True) -> None:
        """A replica repair pull completed, covering ``covered``
        quarantines observed when it started: retire the quarantined
        files for good and clear the suspect-miss state.
        ``recovered=False`` (no replica existed to pull from — the
        quarantined data is lost) clears the state without counting a
        completed repair in the stats."""
        self._quarantine_pending = max(
            0, self._quarantine_pending - max(0, covered)
        )
        if self._quarantine_pending == 0:
            self._suspect_max_index = -1
        if recovered:
            self.durability["repairs_completed"] += 1
        qdir = os.path.join(self.dir_path, QUARANTINE_DIR)

        def _rm():
            try:
                for name in os.listdir(qdir):
                    os.unlink(os.path.join(qdir, name))
                os.rmdir(qdir)
            except OSError:
                pass

        # A fast repair can beat the reader-drained file move
        # (_retire_quarantined_files): deleting first would leave the
        # late-moved triplet leaking in quarantine/ forever — wait for
        # every in-flight retire before removing the dir.
        pending = [t for t in self._retire_tasks if not t.done()]

        async def _rm_after_retires():
            if pending:
                await asyncio.gather(*pending, return_exceptions=True)
            await asyncio.get_event_loop().run_in_executor(None, _rm)

        try:
            asyncio.get_running_loop()
            asyncio.ensure_future(_rm_after_retires())
        except RuntimeError:
            _rm()
        self.flow.notify(flow_events.FlowEvent.REPAIR_DONE)

    def close(self) -> None:
        if self._wal is not None:
            self._wal.close()
        if self._disposing_wal is not None:
            # An in-process close->reopen (test harness node restarts)
            # must not leave the retired WAL's off-loop unlink racing
            # the next open()'s recovery listing.
            self._disposing_wal.join_disposed()
            self._disposing_wal = None
        self._drop_scan_stage()
        for t in self._sstables.tables:
            t.close()

    # ------------------------------------------------------------------
    # Reads (lsm_tree.rs:674-723)
    # ------------------------------------------------------------------

    def newest_memtable_ts(self, key: bytes) -> Optional[int]:
        """Newest timestamp for ``key`` across the active + flushing
        memtables, or None — a synchronous probe for callers that must
        re-check freshness with no awaits before writing."""
        newest = None
        hit = self._active.get(key)
        if hit is not None:
            newest = hit[1]
        if self._flushing is not None:
            hit = self._flushing.get(key)
            if hit is not None and (newest is None or hit[1] > newest):
                newest = hit[1]
        return newest

    async def get_entry(
        self, key: bytes, suspect_guard: bool = False
    ) -> Optional[Tuple[bytes, int]]:
        """Async point read: memtable hits return inline; sstable
        probes go through the executor-backed async read path so a
        cache-miss binary search never stalls the shard loop (VERDICT
        round 1 weak #2/#5; reference analog: io_uring DMA reads).  The
        sstable list is refcounted across awaits so a concurrent
        compaction cannot delete tables under us (lsm_tree.rs:
        1141-1145 reader-drain semantics).

        ``suspect_guard`` (single-evidence callers: RF=1 /
        consistency=1 — quorum reads must NOT set it, their merge
        outvotes staleness by timestamp): while a quarantine awaits
        repair, a hit from a table OLDER than the quarantined one is
        reported as a miss — the dropped table may have held a newer
        value or a tombstone that would shadow it (resurrection
        hazard), and the caller's suspect-miss handling turns the
        miss into a retryable error."""
        hit = self._active.get(key)
        if hit is not None:
            return hit
        if self._flushing is not None:
            hit = self._flushing.get(key)
            if hit is not None:
                return hit
        tables_list = self._sstables
        tables_list.acquire()
        try:
            for table in reversed(tables_list.tables):
                if table.index in self._quarantined_indices:
                    continue  # snapshot taken before a quarantine
                if not table.maybe_contains(key):
                    continue
                try:
                    hit = await table.get_async(key)
                except CorruptedFile as e:
                    # Detect → contain → fall back: quarantine the
                    # table and keep probing the surviving (older)
                    # tables; the caller's replica walk covers the
                    # rest.
                    self._handle_table_corruption(table, e)
                    continue
                if hit is not None:
                    if (
                        suspect_guard
                        and self._quarantine_pending
                        and table.index < self._suspect_max_index
                    ):
                        return None  # shadow-suspect: treat as miss
                    return hit
        finally:
            tables_list.release()
        return None

    async def get(
        self, key: bytes, suspect_guard: bool = False
    ) -> Optional[bytes]:
        """Live value or None (tombstone = None)."""
        hit = await self.get_entry(key, suspect_guard=suspect_guard)
        if hit is None or hit[0] == TOMBSTONE:
            return None
        return hit[0]

    async def multi_get(
        self, keys: Sequence[bytes], suspect_guard: bool = False
    ) -> "dict[bytes, Optional[Tuple[bytes, int]]]":
        """Batched point reads: one entry per DISTINCT key (None =
        absent).  Shares the probe setup a per-key loop would pay N
        times: the memtable probes run synchronously up front, then
        ONE sstable-list acquire/release covers every remaining key,
        probed in sorted key order so adjacent keys revisit the same
        index/data pages while they are hot in the page cache."""
        out: dict = {}
        missing: List[bytes] = []
        for key in keys:
            if key in out:
                continue
            hit = self._active.get(key)
            if hit is None and self._flushing is not None:
                hit = self._flushing.get(key)
            out[key] = hit
            if hit is None:
                missing.append(key)
        if not missing:
            return out
        tables_list = self._sstables
        tables_list.acquire()
        try:
            for key in sorted(missing):
                for table in reversed(tables_list.tables):
                    if table.index in self._quarantined_indices:
                        continue
                    if not table.maybe_contains(key):
                        continue
                    try:
                        hit = await table.get_async(key)
                    except CorruptedFile as e:
                        self._handle_table_corruption(table, e)
                        continue
                    if hit is not None:
                        if (
                            suspect_guard
                            and self._quarantine_pending
                            and table.index < self._suspect_max_index
                        ):
                            break  # shadow-suspect: report a miss
                        out[key] = hit
                        break
        finally:
            tables_list.release()
        return out

    # ------------------------------------------------------------------
    # Writes (lsm_tree.rs:731-837)
    # ------------------------------------------------------------------

    async def set(self, key: bytes, value: bytes) -> None:
        await self.set_with_timestamp(key, value, now_nanos())

    async def set_with_timestamp(
        self, key: bytes, value: bytes, timestamp: int,
        stale_abort: bool = False,
        stale_abort_from: "int | None" = None,
    ) -> bool:
        """Insert (key, value, timestamp).  With ``stale_abort``,
        return False WITHOUT inserting if, at the moment of the
        actual memtable insert, ``timestamp`` is no newer than the
        flush watermark — closing the race where a capacity wait
        spans a flush swap and the pre-checked guard in the shard
        layer goes stale (the caller then applies read-guarded).
        The check sits synchronously before the insert (no awaits
        between), so it cannot itself race a swap.

        ``stale_abort_from=wm`` is the read-guarded variant (the
        apply_if_newer final insert, ADVICE r5 low #2): abort only
        when the watermark has MOVED past ``wm`` since the caller's
        probe AND covers ``timestamp`` — an already-below-watermark
        ts whose probe proved it newest for its key must still land
        (the plain flag would starve it forever), while a swap that
        raced the probe forces a re-probe against the new layers."""
        if self.read_only:
            raise ShardDegraded(
                f"{self.dir_path}: read-only (disk failure)"
            )
        while True:
            try:
                if (
                    stale_abort
                    and timestamp <= self.max_flushed_ts
                ):
                    return False
                if (
                    stale_abort_from is not None
                    and self.max_flushed_ts > stale_abort_from
                    and timestamp <= self.max_flushed_ts
                ):
                    return False
                self._active.set(key, value, timestamp)
                break
            except MemtableCapacityReached:
                # Wait for a flush to swap in a fresh memtable
                # (lsm_tree.rs:747-755).
                waiter = self.flush_start_event.listen()
                self._spawn_flush()
                await waiter
                if self.read_only:
                    # The flush we waited on backed off (out of disk):
                    # escape instead of spinning on a full memtable.
                    raise ShardDegraded(
                        f"{self.dir_path}: read-only (disk failure)"
                    )
        assert self._wal is not None
        try:
            await self._wal.append(key, value, timestamp)
        except OSError as e:
            # The memtable holds the entry but durability failed: the
            # WAL's on_error hook already flipped degraded mode —
            # surface a retryable, typed error so the client walks to
            # a replica with a working disk (timestamps make the
            # retry idempotent under LWW).
            raise ShardDegraded(
                f"WAL append failed: {e}"
            ) from e
        self._appends_since_swap += 1
        if self.on_commit is not None:
            self.on_commit(key, value, timestamp)
        # Flush on capacity DISTINCT keys (reference semantics,
        # lsm_tree.rs:747-755) — or on capacity APPENDS: an
        # update-heavy workload hammering fewer than ``capacity`` hot
        # keys never fills the memtable, so the page-padded WAL grows
        # without bound (the 17-minute chaos soak wrote a 3.6 GB WAL
        # for 240 live keys) and a crash replays all of it.  Counting
        # appends bounds WAL size and replay work while changing
        # nothing for insert-only workloads, where appends == distinct
        # keys.  The C data plane keeps its own counter for the writes
        # it serves (FastCollection::appends) — the two streams are
        # disjoint, so mixed-path traffic flushes by ~2x capacity
        # appends worst-case, still a hard bound.  The reference
        # inherits the unbounded-WAL behavior.
        if (
            self._active.is_full()
            or self._appends_since_swap >= self.capacity
        ):
            self._spawn_flush()
        return True

    async def set_batch_with_timestamp(
        self,
        entries: Sequence[Tuple[bytes, bytes, int]],
        stale_abort: bool = False,
    ) -> List[Tuple[bytes, bytes, int]]:
        """Insert a batch: memtable inserts under one capacity check
        per chunk (Memtable.set_batch), then ONE WAL append_batch per
        chunk — so a durable batch pays one fdatasync wait, not N
        (group commit).  A capacity refusal mid-batch flush-waits and
        continues with the remainder, like the single-set path.

        With ``stale_abort``, entries whose timestamp is no newer
        than the flush watermark AT INSERT TIME are skipped and
        returned (the caller applies them read-guarded) — the same
        race-closing contract as set_with_timestamp(stale_abort=True);
        the watermark check and the memtable insert have no awaits
        between them."""
        if self.read_only:
            raise ShardDegraded(
                f"{self.dir_path}: read-only (disk failure)"
            )
        rejected: List[Tuple[bytes, bytes, int]] = []
        pending = list(entries)
        while pending:
            if stale_abort:
                wm = self.max_flushed_ts
                fresh = []
                for e in pending:
                    (rejected if e[2] <= wm else fresh).append(e)
                pending = fresh
                if not pending:
                    break
            applied = self._active.set_batch(pending)
            if applied == 0:
                waiter = self.flush_start_event.listen()
                self._spawn_flush()
                await waiter
                continue
            chunk, pending = pending[:applied], pending[applied:]
            assert self._wal is not None
            try:
                await self._wal.append_batch(chunk)
            except OSError as e:
                raise ShardDegraded(
                    f"WAL batch append failed: {e}"
                ) from e
            self._appends_since_swap += applied
            if self.on_commit is not None:
                for k, v, ts in chunk:
                    self.on_commit(k, v, ts)
            if (
                self._active.is_full()
                or self._appends_since_swap >= self.capacity
            ):
                self._spawn_flush()
        return rejected

    async def delete(self, key: bytes) -> None:
        await self.set_with_timestamp(key, TOMBSTONE, now_nanos())

    async def delete_with_timestamp(self, key: bytes, timestamp: int):
        await self.set_with_timestamp(key, TOMBSTONE, timestamp)

    # ------------------------------------------------------------------
    # Flush (lsm_tree.rs:844-946)
    # ------------------------------------------------------------------

    def _spawn_flush(self) -> None:
        asyncio.ensure_future(self.flush())

    async def flush(self) -> None:
        while self._is_flushing:
            await self.flush_done_event.listen()
        if self._pending_flush is None and len(self._active) == 0:
            return
        self._is_flushing = True
        try:
            if self._pending_flush is None:
                # The previous flush's WAL disposal runs off-loop
                # (close/unlink of a dirty multi-MB file blocks for
                # tens of ms): wait it out before creating a third
                # WAL, or a crash in the window would leave >2 WALs
                # on disk and trip the recovery invariant.
                if self._disposing_wal is not None:
                    await self._disposing_wal.wait_disposed()
                    self._disposing_wal = None
                flush_index = self._index
                next_index = flush_index + 2
                # ENOSPC back-off: a flush that would fill the disk is
                # refused up front (degraded mode takes over) rather
                # than half-writing a triplet and cascading into
                # checksum quarantines of its own torn output.
                if (
                    file_io.free_disk_space(
                        self._wal_path(next_index)
                    )
                    < MIN_FREE_BYTES
                ):
                    self._report_disk_error(
                        OSError(
                            errno.ENOSPC,
                            f"flush of {self.dir_path}: below the "
                            f"free-space floor",
                        )
                    )
                    self.flush_start_event.notify()  # release waiters
                    return
                # Two-WAL protocol: the next WAL must exist before the
                # sstable write starts (lsm_tree.rs:854-873).
                try:
                    new_wal = wal_mod.Wal(
                        self._wal_path(next_index),
                        sync=self.wal_sync,
                        sync_delay_us=self.wal_sync_delay_us,
                        on_error=self._report_disk_error,
                    )
                except OSError as e:
                    self._report_disk_error(e)
                    self.flush_start_event.notify()
                    return
                assert self._wal is not None
                self._pending_flush = (flush_index, self._wal)
                self._flushing = self._active
                self._active = self._memtable_cls(self.capacity)
                self._appends_since_swap = 0
                # Conservative: wall clock, AND the swapped-out
                # memtable's real newest ts (remote-coordinator
                # timestamps can exceed local now under clock skew).
                self.max_flushed_ts = max(
                    now_nanos(),
                    int(getattr(self._flushing, "max_ts", 0) or 0),
                )
                self._wal = new_wal
                self._index = next_index
                self._notify_write_state()
                self.flush_start_event.notify()

            flush_index, old_wal = self._pending_flush
            flushing = self._flushing
            assert flushing is not None
            # Sort (a no-op for the sorted memtable, a device sort for
            # the hash memtable) AND write off-loop: the flushing
            # memtable is no longer mutated, so the worker may read it.
            # Arena memtables write the whole triplet in one GIL-free
            # native call (byte-identical, golden-tested) — the Python
            # per-entry writer held the GIL for tens of ms per flush,
            # which surfaced as the serving Set p999 tail.
            try:
                if getattr(flushing, "has_native_flush", False):

                    def _native_flush():
                        from .compaction import compaction_stats

                        # Single-pass flush (ISSUE 15): the C writer
                        # page-CRCs every byte AS it emits it and the
                        # .sums sidecar is written from those inline
                        # CRCs — no re-read of the fresh triplet.
                        _n, inline = (
                            flushing.flush_to_sstable_with_sums(
                                self.dir_path,
                                flush_index,
                                self.bloom_min_size,
                            )
                        )
                        written = 0
                        for ext in (
                            DATA_FILE_EXT,
                            INDEX_FILE_EXT,
                            BLOOM_FILE_EXT,
                            SUMS_FILE_EXT,
                        ):
                            try:
                                written += os.path.getsize(
                                    os.path.join(
                                        self.dir_path,
                                        file_name(flush_index, ext),
                                    )
                                )
                            except OSError:
                                pass
                        if not inline:
                            # Stale .so without the single-pass ABI:
                            # post-hoc sidecar (counted — the re-read
                            # shows up in read amplification).
                            data_p = os.path.join(
                                self.dir_path,
                                file_name(flush_index, DATA_FILE_EXT),
                            )
                            index_p = os.path.join(
                                self.dir_path,
                                file_name(
                                    flush_index, INDEX_FILE_EXT
                                ),
                            )
                            bloom_p = os.path.join(
                                self.dir_path,
                                file_name(
                                    flush_index, BLOOM_FILE_EXT
                                ),
                            )
                            checksums.compute_and_write(
                                self.dir_path,
                                flush_index,
                                data_p,
                                index_p,
                                bloom_p,
                            )
                            reread = 0
                            for p in (data_p, index_p, bloom_p):
                                try:
                                    reread += os.path.getsize(p)
                                except OSError:
                                    pass
                            compaction_stats.note_sidecar(
                                False, reread
                            )
                        else:
                            compaction_stats.note_sidecar(True)
                        compaction_stats.note_flush(written)
                        if self.index_fields:
                            # Index run (ISSUE 17): extracted from the
                            # arena's RAM dump — the same records the
                            # C writer just emitted — so building it
                            # reads zero data-file bytes.
                            from . import secondary_index as si

                            nb = si.emit_run(
                                self.dir_path,
                                flush_index,
                                self.index_fields,
                                si.rows_from_items(
                                    flushing.sorted_items()
                                ),
                                compact=False,
                            )
                            compaction_stats.note_index(nb)

                    await asyncio.get_event_loop().run_in_executor(
                        None, _native_flush
                    )
                else:
                    await asyncio.get_event_loop().run_in_executor(
                        None,
                        lambda: self._write_sstable_from_items(
                            flush_index, flushing.sorted_items()
                        ),
                    )
            except OSError as e:
                # Sstable write failed on the disk: keep the flushing
                # memtable + old WAL (_pending_flush retries once the
                # operator frees space / replaces the disk) and
                # degrade instead of crashing the flush task.
                self._report_disk_error(e)
                return
            table = SSTable(
                self.dir_path, flush_index, self.cache,
                counters=self.durability,
            )
            # Pre-warm the in-memory read index off-loop so the first
            # point lookup doesn't pay the bulk read; when it lands,
            # re-notify so the native data plane picks up the built
            # prefix arrays (callback runs on the loop thread).
            warm_fut = asyncio.get_event_loop().run_in_executor(
                None, table.warm
            )
            warm_fut.add_done_callback(
                lambda _f: self._notify_write_state()
            )
            self._sstables = SSTableList(
                self._sstables.tables + [table]
            )
            self._flushing = None
            self._pending_flush = None
            self._notify_write_state()
            old_wal.delete()  # disposal completes off-loop
            self._disposing_wal = old_wal
        finally:
            self._is_flushing = False
            self.flush_done_event.notify()
            self.flow.notify(flow_events.FlowEvent.MEMTABLE_FLUSH_DONE)

    def _write_sstable_from_items(
        self, index: int, items: Sequence[Tuple[bytes, Tuple[bytes, int]]]
    ) -> None:
        """Write a live (non-compact) sstable triplet from sorted items.
        Runs off-loop during flush: mirrors no pages (cache is loop-owned);
        the freshly-written table warms on first read instead."""
        writer = EntryWriter(self.dir_path, index, cache=None)
        data_size = sum(16 + len(k) + len(v) for k, (v, _) in items)
        bloom = (
            BloomFilter.with_capacity(max(1, len(items)))
            if data_size >= self.bloom_min_size
            else None
        )
        for key, (value, ts) in items:
            writer.write(key, value, ts)
        written = writer.close()
        bloom_bytes = None
        if bloom is not None:
            bloom.add_batch([k for k, _ in items])
            bloom_bytes = bloom.serialize()
            with open(
                os.path.join(
                    self.dir_path, file_name(index, BLOOM_FILE_EXT)
                ),
                "wb",
            ) as f:
                f.write(bloom_bytes)
                f.flush()
                os.fsync(f.fileno())
        data_crcs, index_crcs = writer.page_crcs()
        checksums.write(
            self.dir_path,
            index,
            data_crcs,
            index_crcs,
            written,
            bloom_bytes,
            ext=SUMS_FILE_EXT,
        )
        from .compaction import compaction_stats

        compaction_stats.note_sidecar(True)  # writer-tracked CRCs
        compaction_stats.note_flush(
            written
            + len(items) * 16
            + (len(bloom_bytes) if bloom_bytes is not None else 0)
        )
        # getattr: golden-writer tests drive this method on a bare
        # LSMTree.__new__ skeleton that never ran __init__.
        if getattr(self, "index_fields", None):
            # Index run (ISSUE 17) from the same in-RAM items the
            # writer just serialized — zero data-file reads.
            from . import secondary_index as si

            nb = si.emit_run(
                self.dir_path,
                index,
                self.index_fields,
                si.rows_from_items(items),
                compact=False,
            )
            compaction_stats.note_index(nb)

    # ------------------------------------------------------------------
    # Compaction (lsm_tree.rs:950-1156)
    # ------------------------------------------------------------------

    @property
    def memtable_entries(self) -> int:
        """Entries living only in memory (active + in-flight flush)."""
        n = len(self._active)
        if self._flushing is not None:
            n += len(self._flushing)
        return n

    def sstable_indices_and_sizes(self) -> List[Tuple[int, int]]:
        return [
            (t.index, t.data_size) for t in self._sstables.tables
        ]

    def sstable_entry_count(self) -> int:
        return sum(t.entry_count for t in self._sstables.tables)

    async def compact(
        self,
        indices: Sequence[int],
        output_index: int,
        keep_tombstones: bool,
    ) -> None:
        index_set = set(indices)
        inputs = [
            t for t in self._sstables.tables if t.index in index_set
        ]
        if len(inputs) != len(index_set):
            raise ValueError(
                f"compact: missing inputs {index_set} in "
                f"{[t.index for t in self._sstables.tables]}"
            )
        if not inputs:
            return

        # ENOSPC back-off: the merge output peaks at roughly the sum
        # of its inputs before the old files are deleted — refuse up
        # front and retry on a later cycle rather than tearing a
        # half-written compact_* triplet on a full disk.
        needed = sum(t.data_size for t in inputs) + MIN_FREE_BYTES
        if file_io.free_disk_space(self.dir_path) < needed:
            log.warning(
                "compaction of %s backing off: need ~%d free bytes",
                self.dir_path,
                needed,
            )
            return

        # Merge runs off-loop so reads/writes stay responsive; it gets
        # cache-free sstable handles (the page cache is loop-owned).
        # Strategies exposing merge_async (the coalescer) coordinate on
        # the loop instead and offload their heavy stages themselves.
        inputs_nocache = [
            SSTable(self.dir_path, t.index, None) for t in inputs
        ]
        try:
            throttle = getattr(self.strategy, "throttle", None)
            if throttle is not None:
                # A fresh merge must not inherit debt accumulated since
                # the previous merge's last tick.
                throttle.reset()
            # gc_grace: when this merge DROPS tombstones, those newer
            # than (now - grace) survive anyway.  Stamped per merge so
            # the window tracks wall time, not tree lifetime.
            self.strategy.tombstone_drop_before = (
                now_nanos() - int(self.gc_grace_s * 1e9)
                if not keep_tombstones and self.gc_grace_s > 0
                else None
            )
            # Index DDL rides the strategy the same way (ISSUE 17):
            # every built-in merge emits a compact_fidx run from its
            # still-resident output buffers when this is set.
            self.strategy.index_fields = self.index_fields
            merge_async = getattr(self.strategy, "merge_async", None)
            if merge_async is not None:
                result = await merge_async(
                    inputs_nocache,
                    self.dir_path,
                    output_index,
                    None,
                    keep_tombstones,
                    self.bloom_min_size,
                )
            else:
                result = await asyncio.get_event_loop().run_in_executor(
                    None,
                    self.strategy.merge,
                    inputs_nocache,
                    self.dir_path,
                    output_index,
                    None,
                    keep_tombstones,
                    self.bloom_min_size,
                )
        except CorruptedFile as e:
            # The merge read a corrupt input block (compaction rewrites
            # every byte of the store, so it is also a scrubber):
            # quarantine the offending input so the next cycle never
            # re-feeds it, then surface to the compaction loop's
            # error handling.
            bad = self._table_index_from_path(getattr(e, "path", None))
            victim = next(
                (t for t in inputs if t.index == bad), None
            )
            if victim is not None:
                self._handle_table_corruption(victim, e)
            raise
        finally:
            for t in inputs_nocache:
                t.close()

        # Journal {renames, deletes}, fsync, then apply (1090-1111).
        renames = [
            [
                os.path.join(
                    self.dir_path,
                    file_name(output_index, COMPACT_DATA_FILE_EXT),
                ),
                os.path.join(
                    self.dir_path, file_name(output_index, DATA_FILE_EXT)
                ),
            ],
            [
                os.path.join(
                    self.dir_path,
                    file_name(output_index, COMPACT_INDEX_FILE_EXT),
                ),
                os.path.join(
                    self.dir_path, file_name(output_index, INDEX_FILE_EXT)
                ),
            ],
        ]
        if result.wrote_bloom:
            renames.append(
                [
                    os.path.join(
                        self.dir_path,
                        file_name(output_index, COMPACT_BLOOM_FILE_EXT),
                    ),
                    os.path.join(
                        self.dir_path,
                        file_name(output_index, BLOOM_FILE_EXT),
                    ),
                ]
            )
        # Checksum sidecar rides the same journaled rename.  Every
        # merge strategy now writes compact_sums INLINE (single-pass,
        # ISSUE 15: CRCs accumulated while the output bytes were
        # still in RAM / in the writer); this post-hoc re-read is the
        # safety net for exotic strategies or a stale native library,
        # and it is COUNTED — the re-read shows up in
        # get_stats.compaction's read amplification.
        from .compaction import compaction_stats

        compact_sums = os.path.join(
            self.dir_path,
            file_name(output_index, COMPACT_SUMS_FILE_EXT),
        )
        if not os.path.exists(compact_sums):
            await asyncio.get_event_loop().run_in_executor(
                None,
                checksums.compute_and_write,
                self.dir_path,
                output_index,
                renames[0][0],
                renames[1][0],
                os.path.join(
                    self.dir_path,
                    file_name(output_index, COMPACT_BLOOM_FILE_EXT),
                ),
                COMPACT_SUMS_FILE_EXT,
            )
            reread = 0
            for p in (
                renames[0][0],
                renames[1][0],
                os.path.join(
                    self.dir_path,
                    file_name(output_index, COMPACT_BLOOM_FILE_EXT),
                ),
            ):
                try:
                    reread += os.path.getsize(p)
                except OSError:
                    pass
            compaction_stats.note_sidecar(False, reread)
        else:
            compaction_stats.note_sidecar(True)
        # One completed merge pass: inputs (data + index) are read
        # exactly once; outputs = the renamed triplet + sidecar.
        input_bytes = sum(
            t.data_size + t.entry_count * 16 for t in inputs
        )
        written_bytes = 0
        for src, _dst in renames:
            try:
                written_bytes += os.path.getsize(src)
            except OSError:
                pass
        try:
            written_bytes += os.path.getsize(compact_sums)
        except OSError:
            pass
        compaction_stats.note_merge(input_bytes, written_bytes)
        renames.append(
            [
                compact_sums,
                os.path.join(
                    self.dir_path,
                    file_name(output_index, SUMS_FILE_EXT),
                ),
            ]
        )
        # Secondary-index run (ISSUE 17): when the merge emitted one,
        # it rides the SAME action journal — data and index runs
        # rename (and below, retire) in lockstep, so a crash replay
        # can never leave one without the other.
        compact_fidx = os.path.join(
            self.dir_path,
            file_name(output_index, COMPACT_FIDX_FILE_EXT),
        )
        if os.path.exists(compact_fidx):
            try:
                compaction_stats.note_index(
                    os.path.getsize(compact_fidx)
                )
            except OSError:
                pass
            renames.append(
                [
                    compact_fidx,
                    os.path.join(
                        self.dir_path,
                        file_name(output_index, FIDX_FILE_EXT),
                    ),
                ]
            )
            renames.append(
                [
                    os.path.join(
                        self.dir_path,
                        file_name(
                            output_index, COMPACT_FIDX_SUMS_FILE_EXT
                        ),
                    ),
                    os.path.join(
                        self.dir_path,
                        file_name(output_index, FIDX_SUMS_FILE_EXT),
                    ),
                ]
            )
        deletes = [p for t in inputs for p in t.paths()]
        action_path = os.path.join(
            self.dir_path, file_name(output_index, COMPACT_ACTION_FILE_EXT)
        )

        def _write_journal():
            # The journal's fsync blocks ~30ms on this filesystem
            # (loopwatch-measured): write it off-loop.  It must be
            # durable BEFORE the renames mutate live files, so the
            # executor call is awaited here.
            with open(action_path, "wb") as f:
                f.write(
                    msgpack.packb(
                        {"renames": renames, "deletes": deletes},
                        use_bin_type=True,
                    )
                )
                f.flush()
                os.fsync(f.fileno())

        await asyncio.get_event_loop().run_in_executor(
            None, _write_journal
        )

        for src, dst in renames:
            # Audited sync I/O: rename is metadata-only (µs-scale)
            # and must stay ordered between the journal fsync above
            # and the table-list swap below — an executor hop would
            # open a window where a crash-recovery scan sees neither
            # the journal'd nor the renamed state applied.
            os.replace(src, dst)  # lint: allow(async-blocking)

        old_list = self._sstables
        survivors = [
            t for t in self._sstables.tables if t.index not in index_set
        ]
        output_table = SSTable(
            self.dir_path, output_index, self.cache,
            counters=self.durability,
        )
        warm_fut = asyncio.get_event_loop().run_in_executor(
            None, output_table.warm
        )
        warm_fut.add_done_callback(
            lambda _f: self._notify_write_state()
        )
        survivors.append(output_table)
        # SSTableList sorts by index: the even/odd scheme ranks the
        # output (max(inputs)+1) below any table flushed DURING this
        # compaction, so reversed() keeps probing newest data first.
        self._sstables = SSTableList(survivors)
        # The native data plane must swap to the new table list before
        # the inputs are closed/unlinked below (its dup'd fds make the
        # old tables safe mid-probe, but it should pick up the merged
        # table's bloom/prefix index promptly).
        self._notify_write_state()

        # Reader drain before deleting inputs (1141-1145).
        while old_list.readers > 0:
            await old_list.drained.listen()
        for t in inputs:
            t.close()
            if self.cache is not None:
                self.cache.invalidate_file((DATA_FILE_EXT, t.index))
                self.cache.invalidate_file((INDEX_FILE_EXT, t.index))

        def _dispose_inputs():
            # Unlinking hundreds of MB of input tables blocks for
            # tens of ms on this filesystem (measured as 30-43ms
            # serving stalls right after each merge commit) — run it
            # off-loop.  The action journal goes LAST, preserving the
            # replay contract: a crash mid-disposal re-runs the
            # journal's idempotent deletes on open.
            for victim in deletes:
                if os.path.exists(victim):
                    os.unlink(victim)
            os.unlink(action_path)

        await asyncio.get_event_loop().run_in_executor(
            None, _dispose_inputs
        )
        self.flow.notify(flow_events.FlowEvent.COMPACTION_DONE)

    # ------------------------------------------------------------------
    # Iteration (lsm_tree.rs:141-282) — sstables oldest→newest, then the
    # memtables; duplicates possible, consumers resolve by timestamp.
    # ------------------------------------------------------------------

    async def iter_filter(
        self,
        filter_fn: Optional[Callable[[bytes, bytes, int], bool]] = None,
    ) -> AsyncIterator[Tuple[bytes, bytes, int]]:
        # Snapshot the memtables NOW, before any await, exactly like the
        # reference snapshots them at AsyncIter construction (lsm_tree.rs
        # :155-172) — a flush completing mid-iteration must not make
        # entries vanish from the view.
        memtable_items: List[Tuple[bytes, bytes, int]] = []
        if self._flushing is not None:
            memtable_items.extend(
                (k, v, ts)
                for k, (v, ts) in self._flushing.sorted_items()
            )
        memtable_items.extend(
            (k, v, ts) for k, (v, ts) in self._active.sorted_items()
        )
        snapshot = self._sstables
        snapshot.acquire()
        try:
            for table in snapshot.tables:
                count = 0
                try:
                    for key, value, ts in table.entries():
                        if filter_fn is None or filter_fn(
                            key, value, ts
                        ):
                            yield key, value, ts
                        count += 1
                        if count % 256 == 0:
                            await asyncio.sleep(0)
                except CorruptedFile as e:
                    # Scan-path corruption: quarantine the source
                    # table (repair owns the heal) and re-raise — a
                    # partial scan must not masquerade as a complete
                    # one (AE digests would claim authority over
                    # entries the scan never saw).
                    self.quarantine_by_exception(
                        e, snapshot.tables
                    )
                    raise
            for key, value, ts in memtable_items:
                if filter_fn is None or filter_fn(key, value, ts):
                    yield key, value, ts
        finally:
            snapshot.release()

    def iter(self) -> AsyncIterator[Tuple[bytes, bytes, int]]:
        return self.iter_filter(None)

    # ------------------------------------------------------------------
    # Streaming scan pages (scan plane, PR 12): batched columnar
    # iteration through a cached ScanStage — the vectorized
    # range-digest staging generalized to ordered, value-bearing
    # pages.  Chunks of one cursor walk hit the same stage; any write
    # or table-list change invalidates it.
    # ------------------------------------------------------------------

    def _scan_stage_token(self) -> tuple:
        return (
            tuple(t.index for t in self._sstables.tables),
            id(self._active),
            self._appends_since_swap,
            len(self._active),
            self._flushing is not None,
        )

    def _drop_scan_stage(self) -> None:
        if self._scan_stage is not None:
            self._scan_stage = None
            self._scan_stage_key = None
            self._scan_stage_list.release()
            self._scan_stage_list = None
        # Index runs are per-table immutable artifacts, but the cache
        # is keyed by table index; a table-list swap (flush/compaction/
        # quarantine) can retire an index and a later table can reuse
        # nothing — still, drop with the stage so stale runs never
        # outlive the tables they describe.
        if self._index_runs:
            self._index_runs = {}

    async def _current_scan_stage(self):
        """The cached vectorized stage for the CURRENT tree state, or
        None (guard tripped — caller uses the per-entry path).  Holds
        one reader ref on the staged sstable list so compaction
        cannot retire the files under later pages of the same
        stage."""
        from . import scan_stage as ss

        token = self._scan_stage_token()
        if (
            self._scan_stage is not None
            and self._scan_stage_key == token
        ):
            return self._scan_stage
        self._drop_scan_stage()
        total = self.memtable_entries + self.sstable_entry_count()
        if total < ss.MIN_VECTORIZED_ENTRIES:
            return None
        snap = self.scan_snapshot()
        try:
            stage = await asyncio.get_event_loop().run_in_executor(
                None,
                ss.build_stage,
                snap.memtable_items,
                snap.tables,
            )
        except CorruptedFile as e:
            self.quarantine_by_exception(e, snap.tables)
            snap.release()
            raise
        except BaseException:
            snap.release()
            raise
        if stage is None:
            snap.release()
            return None
        if self._scan_stage_token() != token:
            # A write or swap landed during the executor build: the
            # stage is already stale — serve this one page from it
            # (it is a valid point-in-time view) but don't cache it.
            # The snapshot ref is released by scan_page's finally.
            stage._hold = snap
            return stage
        if (
            self._scan_stage is not None
            and self._scan_stage_key == token
        ):
            # A concurrent cold-cache build won the race and already
            # cached an identical stage: use it and release OUR
            # snapshot ref — overwriting the cache here would orphan
            # the winner's reader ref and stall compaction's reader
            # drain forever.
            snap.release()
            return self._scan_stage
        self._drop_scan_stage()  # release any stale cached ref
        self._scan_stage = stage
        self._scan_stage_key = token
        self._scan_stage_list = snap._sstables  # cache owns the ref
        snap._released = True  # ownership moved to the cache
        return stage

    async def scan_page(
        self,
        start: int,
        end: int,
        start_after,
        prefix,
        limit: int,
        max_bytes: int,
        with_values: bool,
    ) -> Tuple[list, bool]:
        """One ordered scan page: up to ``limit`` entries /
        ``max_bytes`` emitted bytes of [key, value|nil, ts] with
        hash(key) in the wrap range [start, end), key > start_after
        (and starting with ``prefix`` when given), ascending by key;
        newest entry per key, tombstones included as value=b"".
        Returns (entries, more).  Vectorized through the cached
        ScanStage; per-entry fallback otherwise."""
        stage = await self._current_scan_stage()
        if stage is not None:
            # Pin the staged table files across the materialization's
            # cooperative yields: a flush/compaction swap during an
            # await drops the CACHE's ref, and without this per-call
            # ref the input files could be retired mid-read.
            hold_list = None
            if stage._hold is None and stage is self._scan_stage:
                hold_list = self._scan_stage_list
                if hold_list is not None:
                    hold_list.acquire()
            try:
                # Selection is pure numpy over the remaining
                # keyspace.  Only genuinely large stages go off-loop
                # (mask/cumsum there would stall point ops for ms);
                # below the threshold the executor hand-off latency
                # (~ms of idle epoll per hop, measured) costs more
                # than the selection itself.
                if stage.n >= 200_000:
                    pos, more = await asyncio.get_event_loop(
                    ).run_in_executor(
                        None,
                        stage.select,
                        start, end, start_after, prefix, limit,
                        max_bytes, with_values,
                    )
                else:
                    pos, more = stage.select(
                        start, end, start_after, prefix, limit,
                        max_bytes, with_values,
                    )
                entries: list = []
                for j in range(0, len(pos), 512):
                    entries.extend(
                        stage.entries_at(
                            pos[j : j + 512], with_values
                        )
                    )
                    # Yield between slices of value reads so point
                    # ops interleave within a large page.
                    await asyncio.sleep(0)
                return entries, more
            except CorruptedFile as e:
                # Stage-read corruption (value-page CRC): quarantine
                # the attributed table so repair starts NOW, then
                # error the page retryably — the coordinator's
                # stream dies and the client resumes elsewhere.
                self.quarantine_by_exception(
                    e,
                    [
                        s.table
                        for s in stage.sources
                        if not isinstance(s, list)
                    ],
                )
                raise
            finally:
                if hold_list is not None:
                    hold_list.release()
                if stage._hold is not None:
                    stage._hold.release()
                    stage._hold = None
        return await self._scan_page_fallback(
            start, end, start_after, prefix, limit, max_bytes,
            with_values,
        )

    def _quarantine_index_run(self, tidx: int) -> None:
        """Contain a corrupt secondary-index run WITHOUT touching its
        data table: the run is a derived artifact, so it moves to
        quarantine/ alone (the triplet keeps serving) and the caller
        surfaces a retryable CorruptedFile — the client's retry
        replans without the run."""
        from . import secondary_index as si

        if tidx in self._fidx_quarantined:
            return
        self._fidx_quarantined.add(tidx)
        self._index_runs[tidx] = None
        self.durability["checksum_failures"] += 1
        si.index_stats.note_quarantine()
        fidx_p, fsums_p = si.run_paths(self.dir_path, tidx)
        qdir = os.path.join(self.dir_path, QUARANTINE_DIR)
        log.error(
            "quarantining corrupt index run %s (data table stays "
            "live)",
            fidx_p,
        )

        def _move():
            os.makedirs(qdir, exist_ok=True)
            for p in (fidx_p, fsums_p):
                try:
                    if os.path.exists(p):
                        os.replace(
                            p,
                            os.path.join(qdir, os.path.basename(p)),
                        )
                except OSError:
                    log.warning(
                        "index-run quarantine move failed for %s", p
                    )

        # The loader reads the whole file and closes it, so nothing
        # holds the run open — the move needs no reader drain.
        asyncio.get_event_loop().run_in_executor(None, _move)

    async def _load_index_runs(self, stage) -> dict:
        """stage source position -> IndexRun for every staged table
        with a usable run, loading uncached runs off-loop.  A
        provably-corrupt run quarantines (alone) and raises a
        retryable CorruptedFile tagged ``index_run_only``."""
        from . import secondary_index as si

        runs_by_src: dict = {}
        loop = asyncio.get_event_loop()
        for s, source in enumerate(stage.sources):
            if isinstance(source, list):
                continue
            tidx = source.table.index
            if tidx in self._fidx_quarantined:
                continue
            if tidx not in self._index_runs:
                try:
                    run = await loop.run_in_executor(
                        None, si.load_run, self.dir_path, tidx
                    )
                except CorruptedFile as e:
                    self._quarantine_index_run(tidx)
                    e.index_run_only = True
                    raise
                self._index_runs[tidx] = run
            run = self._index_runs[tidx]
            if run is not None:
                runs_by_src[s] = run
        return runs_by_src

    async def _scan_filter_indexed(
        self,
        stage,
        start: int,
        end: int,
        start_after,
        prefix,
        limit: int,
        max_bytes: int,
        where,
        agg,
    ):
        """Index-planned page: ``(pos, more, sbytes, matched,
        partial)`` or None (planner miss — the caller runs the
        vectorized evaluator).  The window cut is the exact
        ``select_window`` the non-indexed path uses; only the
        EVALUATION shrinks, to a golden ``match_entry`` re-check of
        the index's candidate rows — so results, covers and
        accounting cannot diverge."""
        from .. import query as Q
        from . import query_vec
        from . import secondary_index as si
        from .entry import ENTRY_HEADER_SIZE

        runs_by_src = await self._load_index_runs(stage)

        def _plan_and_select():
            cand = si.candidate_mask(
                stage, where, runs_by_src, self.index_fields
            )
            if cand is None:
                return None
            pos, more, sbytes = stage.select_window(
                start, end, start_after, prefix, limit, max_bytes
            )
            flags = np.zeros(pos.size, dtype=bool)
            csub = np.flatnonzero(cand[pos])
            vlen = stage.vlen
            for i in csub.tolist():
                p = int(pos[i])
                if vlen[p] == 0:
                    continue  # tombstone: matches nothing
                source = stage.sources[int(stage.src[p])]
                if isinstance(source, list):
                    value = source[int(stage.off[p])][1]
                else:
                    value = source.value_at(
                        int(stage.off[p])
                        + ENTRY_HEADER_SIZE
                        + int(stage.klen[p]),
                        int(vlen[p]),
                    )
                if Q.match_entry(where, stage.key_at(p), value):
                    flags[i] = True
            matched = pos[flags]
            partial = (
                query_vec.agg_partial_for(stage, matched, agg)
                if agg is not None
                else None
            )
            return pos, more, sbytes, matched, partial

        # Candidate-mask searchsorteds + per-candidate value reads:
        # off-loop (a selective predicate touches few values, but the
        # membership probe is O(stage rows) per leaf).
        return await asyncio.get_event_loop().run_in_executor(
            None, _plan_and_select
        )

    async def scan_filter_page(
        self,
        start: int,
        end: int,
        start_after,
        prefix,
        limit: int,
        max_bytes: int,
        with_values: bool,
        where,
        agg,
        mode: str,
    ) -> tuple:
        """One filtered/aggregated scan page (query compute plane,
        PR 13): ``(entries, more, cover, scanned_rows,
        scanned_bytes, agg_partial, eval_path)``.

        The window advances by bytes SCANNED (key+value+overhead of
        every arc-member row examined), so a selective predicate
        still pages in bounded work and the ``cover`` key lets the
        coordinator resume past a window that matched nothing.
        ``mode`` is the peer-spec contract (query.MODE_DROP /
        MODE_MARK — see query.py): drop emits matching rows only
        (or, with ``agg``, just a partial state); mark emits EVERY
        newest-per-key row as [key, payload, ts, flag] so the
        coordinator's newest-wins dedup decides acceptance.
        ``eval_path`` says which evaluator ran ("device" / "numpy" /
        "cached" / "golden") for the stats plane."""
        from .. import query as Q
        from . import query_vec

        stage = await self._current_scan_stage()
        if stage is None:
            return await self._scan_filter_page_fallback(
                start, end, start_after, prefix, limit, max_bytes,
                with_values, where, agg, mode,
            )
        hold_list = None
        if stage._hold is None and stage is self._scan_stage:
            hold_list = self._scan_stage_list
            if hold_list is not None:
                hold_list.acquire()
        try:
            # Secondary-index plan (ISSUE 17): when this collection
            # declares indexed fields and the spec is plannable
            # (predicate present, drop mode, no agg or count — other
            # aggs need the full field column anyway), consult the
            # per-table index runs to shrink the exact evaluation to
            # the candidate rows inside the SAME select_window cut.
            # Windows, covers and scanned-byte accounting are shared
            # with the non-indexed path, so results stay
            # byte-identical; a planner miss falls through to the
            # vectorized evaluator below.
            if (
                self.index_fields
                and where is not None
                and mode == Q.MODE_DROP
                and (agg is None or agg.get("op") == "count")
            ):
                got = await self._scan_filter_indexed(
                    stage, start, end, start_after, prefix, limit,
                    max_bytes, where, agg,
                )
                if got is not None:
                    pos, more, sbytes, matched, partial = got
                    cover = (
                        stage.key_at(int(pos[-1]))
                        if pos.size
                        else None
                    )
                    entries = []
                    if agg is None:
                        for j in range(0, len(matched), 512):
                            entries.extend(
                                stage.entries_at(
                                    matched[j : j + 512],
                                    with_values,
                                )
                            )
                            await asyncio.sleep(0)
                    return (
                        entries,
                        more,
                        cover,
                        int(pos.size),
                        int(sbytes),
                        partial,
                        "indexed",
                    )
            need_build = bool(
                Q.spec_fields(where, agg)
                - set(stage._field_cols)
            ) or (
                # A mask-cache miss re-evaluates the whole tree —
                # including any O(n) scalar-leaf loops (trailing-NUL
                # operands, >2^53 ints) — so it goes off-loop even
                # when every column already exists.
                where is not None
                and msgpack.packb(where, use_bin_type=True)
                not in stage._mask_cache
            )

            def _select():
                pos, more, sbytes = stage.select_window(
                    start, end, start_after, prefix, limit,
                    max_bytes,
                )
                mask, path = query_vec.eval_where(stage, where)
                sub = mask[pos]
                matched = pos[sub]
                partial = None
                if agg is not None and mode == Q.MODE_DROP:
                    partial = query_vec.agg_partial_for(
                        stage, matched, agg
                    )
                return pos, more, sbytes, sub, matched, partial, path

            # The first evaluation of a spec decodes the targeted
            # field for EVERY staged row (the batched column build):
            # always off-loop.  Re-evaluations are cached-mask
            # lookups plus a window searchsorted — loop-side below
            # the same size bar scan_page uses.
            if need_build or stage.n >= 200_000:
                (
                    pos, more, sbytes, sub, matched, partial, path,
                ) = await asyncio.get_event_loop().run_in_executor(
                    None, _select
                )
            else:
                (
                    pos, more, sbytes, sub, matched, partial, path,
                ) = _select()
            cover = (
                stage.key_at(int(pos[-1])) if pos.size else None
            )
            if mode == Q.MODE_DROP:
                entries: list = []
                if agg is None:
                    for j in range(0, len(matched), 512):
                        entries.extend(
                            stage.entries_at(
                                matched[j : j + 512], with_values
                            )
                        )
                        await asyncio.sleep(0)
            else:  # mark: every newest-per-key row, flagged
                keys = stage.keys[pos].tolist()
                ts = stage.ts[pos].tolist()
                vl = stage.vlen[pos].tolist()
                flags = sub.tolist()
                fcol = (
                    query_vec.field_column(stage, agg["field"])
                    if agg is not None and agg.get("field")
                    else None
                )
                entries = []
                for i, p in enumerate(pos.tolist()):
                    if vl[i] == 0:
                        entries.append([keys[i], b"", ts[i], 0])
                        continue
                    if not flags[i]:
                        entries.append([keys[i], None, ts[i], 0])
                        continue
                    if agg is not None:
                        payload = (
                            fcol.typed_at(p)
                            if fcol is not None
                            else None
                        )
                        if isinstance(payload, bytes):
                            payload = None  # non-numeric: never folds
                    elif with_values:
                        payload = query_vec._value_bytes(stage, p)
                    else:
                        payload = None
                    entries.append([keys[i], payload, ts[i], 1])
                    if i and i % 512 == 0:
                        await asyncio.sleep(0)
            return (
                entries,
                more,
                cover,
                int(pos.size),
                int(sbytes),
                partial,
                path,
            )
        except CorruptedFile as e:
            # Column build / value materialization hit a flipped
            # page: quarantine the attributed table so repair starts
            # NOW, then error retryably (the coordinator stream dies
            # and the client resumes elsewhere) — same contract as
            # the unfiltered staged path.  A corrupt INDEX RUN is
            # contained separately (_quarantine_index_run): the data
            # triplet is untouched, so it must NOT be quarantined
            # off the run's path attribution.
            if not getattr(e, "index_run_only", False):
                self.quarantine_by_exception(
                    e,
                    [
                        s.table
                        for s in stage.sources
                        if not isinstance(s, list)
                    ],
                )
            raise
        finally:
            if hold_list is not None:
                hold_list.release()
            if stage._hold is not None:
                stage._hold.release()
                stage._hold = None

    async def _scan_filter_page_fallback(
        self,
        start: int,
        end: int,
        start_after,
        prefix,
        limit: int,
        max_bytes: int,
        with_values: bool,
        where,
        agg,
        mode: str,
    ) -> tuple:
        """Golden per-entry filtered page (tiny trees / guard trips):
        the reference evaluator the vectorized path is byte-identical
        to, with the same scanned-window accounting."""
        from ..utils.murmur import hash_bytes as _hash_bytes
        from .. import query as Q
        from . import scan_stage as ss

        newest: dict = {}
        async for key, value, ts in self.iter_filter(None):
            if start_after is not None and key <= start_after:
                continue
            if prefix and not key.startswith(prefix):
                continue
            h = _hash_bytes(key)
            width = (end - start) & 0xFFFFFFFF
            if width != 0 and ((h - start) & 0xFFFFFFFF) >= width:
                continue
            prev = newest.get(key)
            if prev is None or ts > prev[1]:
                newest[key] = (value, ts)
        items = sorted(newest.items())
        entries: list = []
        partial_state = None
        agg_rows: list = []
        scanned = 0
        used = 0
        more = False
        cover = None
        for i, (key, (value, ts)) in enumerate(items):
            # Window cut mirrors ScanStage.select_window exactly
            # (the byte-identical contract includes covers and
            # scanned accounting): rows accumulate until the first
            # one that REACHES the budget, inclusive.
            cost = len(key) + ss.ENTRY_OVERHEAD + len(value)
            used += cost
            scanned += 1
            cover = key
            stop = scanned >= limit or used >= max_bytes
            matched = Q.match_entry(where, key, value)
            if mode == Q.MODE_DROP:
                if matched:
                    if agg is not None:
                        agg_rows.append((key, value))
                    elif with_values:
                        entries.append([key, value, ts])
                    else:
                        entries.append([key, None, ts])
            else:  # mark
                if len(value) == 0:
                    entries.append([key, b"", ts, 0])
                elif not matched:
                    entries.append([key, None, ts, 0])
                elif agg is not None:
                    x = Q.field_value(
                        Q.decode_doc(value), agg["field"]
                    ) if agg.get("field") else None
                    if isinstance(x, (str, bytes)):
                        x = None
                    entries.append([key, x, ts, 1])
                elif with_values:
                    entries.append([key, value, ts, 1])
                else:
                    entries.append([key, None, ts, 1])
            if stop:
                more = i + 1 < len(items)
                break
        if agg is not None and mode == Q.MODE_DROP:
            group = agg["group"]
            if group:
                groups: dict = {}
                for key, value in agg_rows:
                    x = (
                        Q.field_value(
                            Q.decode_doc(value), agg["field"]
                        )
                        if agg.get("field")
                        else None
                    )
                    if not Q.contributes(agg["op"], x):
                        continue
                    g = key[:group]
                    st = groups.get(g)
                    if st is None:
                        st = groups[g] = Q.agg_new()
                    Q.agg_fold(
                        st,
                        agg["op"],
                        None if agg["op"] == "count" else x,
                    )
                partial_state = [
                    [g, st] for g, st in sorted(groups.items())
                ]
            else:
                partial_state = Q.agg_new()
                for key, value in agg_rows:
                    x = (
                        Q.field_value(
                            Q.decode_doc(value), agg["field"]
                        )
                        if agg.get("field")
                        else None
                    )
                    if not Q.contributes(agg["op"], x):
                        continue
                    Q.agg_fold(
                        partial_state,
                        agg["op"],
                        None if agg["op"] == "count" else x,
                    )
        return (
            entries, more, cover, scanned, used, partial_state,
            "golden",
        )

    async def _scan_page_fallback(
        self,
        start: int,
        end: int,
        start_after,
        prefix,
        limit: int,
        max_bytes: int,
        with_values: bool,
    ) -> Tuple[list, bool]:
        """Per-entry page (tiny trees / no native lib / guard trips):
        one full newest-wins walk, then the page cut.  Byte-identical
        ordering and dedup to the staged path."""
        from ..utils.murmur import hash_bytes as _hash_bytes
        from . import scan_stage as ss

        newest: dict = {}
        async for key, value, ts in self.iter_filter(None):
            if start_after is not None and key <= start_after:
                continue
            if prefix and not key.startswith(prefix):
                continue
            h = _hash_bytes(key)
            width = (end - start) & 0xFFFFFFFF
            if width != 0 and ((h - start) & 0xFFFFFFFF) >= width:
                continue
            prev = newest.get(key)
            if prev is None or ts > prev[1]:
                newest[key] = (value, ts)
        entries: list = []
        used = 0
        items = sorted(newest.items())
        for i, (key, (value, ts)) in enumerate(items):
            vlen = len(value)
            cost = len(key) + ss.ENTRY_OVERHEAD + (
                vlen if with_values else 0
            )
            if entries and (
                used + cost > max_bytes or len(entries) >= limit
            ):
                return entries, True
            used += cost
            if vlen == 0:
                entries.append([key, b"", ts])
            elif with_values:
                entries.append([key, value, ts])
            else:
                entries.append([key, None, ts])
            if len(entries) >= limit and i + 1 < len(items):
                return entries, True
        return entries, False

    def scan_snapshot(self) -> "ScanSnapshot":
        """Synchronous point-in-time view for OFF-LOOP bulk scans
        (vectorized anti-entropy digests): memtable items materialized
        now, sstable list acquired so compaction cannot delete the
        files under the scan.  Caller MUST release()."""
        items: List[Tuple[bytes, bytes, int]] = []
        if self._flushing is not None:
            items.extend(
                (k, v, ts)
                for k, (v, ts) in self._flushing.sorted_items()
            )
        items.extend(
            (k, v, ts) for k, (v, ts) in self._active.sorted_items()
        )
        snapshot = self._sstables
        snapshot.acquire()
        return ScanSnapshot(items, snapshot)

    # ------------------------------------------------------------------

    def _table_index_from_path(self, path) -> Optional[int]:
        """Sstable index encoded in a triplet file path (CorruptedFile
        attribution from merge workers), or None."""
        if not path:
            return None
        m = _FILE_RE.match(os.path.basename(path))
        return int(m.group(1)) if m else None

    async def purge(self) -> None:
        """Delete the tree from disk (drop collection, shards.rs:369-381).

        Every table's cached pages are invalidated BEFORE the files
        go: page-cache keys are (collection-name-hash, (ext, index),
        address), all of which a re-created same-name collection
        recycles from 0 — without the invalidation its reads would
        serve the DROPPED collection's pages (satellite fix, PR 3;
        regression-tested in tests/test_disk_faults.py)."""
        self.close()
        if self.cache is not None:
            for t in self._sstables.tables:
                self.cache.invalidate_file((DATA_FILE_EXT, t.index))
                self.cache.invalidate_file((INDEX_FILE_EXT, t.index))
        # Audited sync I/O: purge runs on the operator-rate DROP path
        # after close() — nothing else serves this tree anymore.
        shutil.rmtree(self.dir_path, ignore_errors=True)  # lint: allow(async-blocking)
