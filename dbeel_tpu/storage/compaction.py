"""CompactionStrategy seam — pluggable merge backends.

The reference hard-codes a single-threaded k-way BinaryHeap merge
(/root/reference/src/storage_engine/lsm_tree.rs:1003-1066).  Here the
merge is a strategy (SURVEY.md §7 stage 3):

  * HeapMergeStrategy    — the reference-semantics oracle: per-entry heap
                           pop/push, streamed through EntryWriter.
  * ColumnarMergeStrategy — vectorized host path: bulk columnarize, one
                           numpy lexsort + dedup mask, range-gather, bulk
                           write.
  * DeviceMergeStrategy  — (dbeel_tpu.ops.device_compaction) same pipeline
                           with the sort+dedup kernel jitted on the TPU.
  * NativeMergeStrategy  — (dbeel_tpu.storage.native) C++ k-way merge.

All strategies must produce byte-identical SSTable files — golden tests
enforce it.  A strategy writes the ``compact_*`` triplet; the LSM tree
owns the journal/rename/swap choreography around it.
"""

from __future__ import annotations

import heapq
from abc import ABC, abstractmethod
from dataclasses import dataclass
from typing import List, Optional, Sequence, Tuple

import numpy as np

from . import checksums, columnar
from .bloom import BloomFilter
from .entry import (
    COMPACT_BLOOM_FILE_EXT,
    COMPACT_DATA_FILE_EXT,
    COMPACT_INDEX_FILE_EXT,
    COMPACT_SUMS_FILE_EXT,
    ENTRY_HEADER_SIZE,
    INDEX_ENTRY,
    file_name,
)
from .entry_writer import EntryWriter
from .file_io import PageMirroringWriter
from .page_cache import PartitionPageCache
from .sstable import SSTable


@dataclass
class MergeResult:
    entry_count: int
    data_size: int
    wrote_bloom: bool


class CompactionStats:
    """Process-wide single-pass compaction/flush accounting
    (ISSUE 15): bytes read and written per background pass, and
    whether each output's ``.sums`` sidecar was emitted INLINE
    (single-pass, CRCs accumulated as bytes were written) or rebuilt
    POST-HOC (the legacy full-triplet re-read, which roughly doubled
    compaction read amplification).  ``read_amplification`` is the
    measurable claim: bytes_read / merge input bytes — ~1.0 when every
    pass is single-pass, ~2.0 when every output is re-read for its
    sidecar.  One instance per process (merges from all shards of a
    node fold in), mirrored into ``get_stats.compaction``."""

    def __init__(self) -> None:
        import threading

        self._lock = threading.Lock()
        self.merge_passes = 0
        self.flush_passes = 0
        self.bytes_read = 0
        self.bytes_written = 0
        self.merge_input_bytes = 0
        self.sidecar_inline = 0
        self.sidecar_posthoc = 0
        self.posthoc_bytes_reread = 0
        # Secondary-index maintenance (ISSUE 17): bytes of .fidx runs
        # written alongside flush/compaction outputs.  Kept OUT of
        # bytes_written/bytes_read — runs are built from the writers'
        # still-resident buffers, so they add zero data reads and
        # read_amplification stays a pure data-plane measure; their
        # cost is reported as index_maintenance_amplification.
        self.index_bytes_written = 0

    def note_merge(
        self, input_bytes: int, bytes_written: int
    ) -> None:
        """One completed merge pass: inputs are read exactly once by
        every strategy (the single-pass contract), outputs written
        once."""
        with self._lock:
            self.merge_passes += 1
            self.merge_input_bytes += int(input_bytes)
            self.bytes_read += int(input_bytes)
            self.bytes_written += int(bytes_written)

    def note_flush(self, bytes_written: int) -> None:
        with self._lock:
            self.flush_passes += 1
            self.bytes_written += int(bytes_written)

    def note_sidecar(
        self, inline: bool, reread_bytes: int = 0
    ) -> None:
        """One sidecar emitted: inline (no extra IO) or post-hoc
        (the whole freshly-written triplet re-read and summed —
        ``reread_bytes`` joins the read-amplification numerator)."""
        with self._lock:
            if inline:
                self.sidecar_inline += 1
            else:
                self.sidecar_posthoc += 1
                self.posthoc_bytes_reread += int(reread_bytes)
                self.bytes_read += int(reread_bytes)

    def note_index(self, nbytes: int) -> None:
        """One index run emitted inline with a flush/merge output."""
        with self._lock:
            self.index_bytes_written += int(nbytes)

    def stats(self) -> dict:
        from . import native as native_mod

        with self._lock:
            amp = (
                round(
                    self.bytes_read / self.merge_input_bytes, 3
                )
                if self.merge_input_bytes > 0
                else None
            )
            idx_amp = (
                round(
                    self.index_bytes_written / self.bytes_written, 4
                )
                if self.bytes_written > 0
                else None
            )
            block = {
                "merge_passes": self.merge_passes,
                "flush_passes": self.flush_passes,
                "bytes_read": self.bytes_read,
                "bytes_written": self.bytes_written,
                "merge_input_bytes": self.merge_input_bytes,
                "sidecar_inline": self.sidecar_inline,
                "sidecar_posthoc": self.sidecar_posthoc,
                "posthoc_bytes_reread": self.posthoc_bytes_reread,
                "read_amplification": amp,
                "index_bytes_written": self.index_bytes_written,
                "index_maintenance_amplification": idx_amp,
            }
        overlap = native_mod.read_overlap_stats()
        block["overlapped_read_passes"] = overlap[0]
        block["serial_read_passes"] = overlap[1]
        return block


# One per process — every shard's trees fold into it, like the
# device-coalescer counters.
compaction_stats = CompactionStats()


class CompactionStrategy(ABC):
    name = "abstract"

    # Optional intra-merge throttle (server.scheduler.BgThrottle): the
    # shard attaches one per tree so long merges yield CPU to serving
    # between bounded quanta even though they run on a worker thread.
    # Strategies tick it between partitions / entry blocks / write
    # chunks; None (the default, e.g. in tests and bench) is free.
    throttle = None

    # Tombstone GC grace (gc_grace, the delete-resurrection hazard):
    # when a merge is asked to DROP tombstones, any tombstone whose
    # timestamp is >= this nanosecond cutoff is kept anyway — it is
    # younger than the window a delete needs to out-live its laggard
    # replicas (hint replay / anti-entropy could otherwise resurrect
    # the old value after the tombstone was GC'd).  None/0 = drop all
    # (reference behavior; tests/benches constructing strategies
    # directly are unchanged).  Set per merge by LSMTree.compact.
    tombstone_drop_before = None

    # Secondary-index DDL (ISSUE 17): when LSMTree.compact sets this
    # to the collection's indexed field list, the merge also emits a
    # compact_fidx index run for its output — extracted from the
    # output records while they are STILL RESIDENT in the writer
    # (zero extra data reads), never by re-reading the triplet.
    # None (the default) = no index emission.
    index_fields = None

    def _tick(self) -> None:
        t = self.throttle
        if t is not None:
            t.tick()

    @abstractmethod
    def merge(
        self,
        sources: Sequence[SSTable],
        dir_path: str,
        output_index: int,
        cache: Optional[PartitionPageCache],
        keep_tombstones: bool,
        bloom_min_size: int,
    ) -> MergeResult:
        """Merge ``sources`` (oldest→newest) into the compact_* triplet at
        ``output_index``. Bloom file written iff final data size >=
        ``bloom_min_size`` (lsm_tree.rs:1026-1034)."""


class HeapMergeStrategy(CompactionStrategy):
    """Reference-semantics oracle (lsm_tree.rs:1038-1066): min-heap by
    (key, newest-ts-first, newest-source-first); pop, write first per key,
    skip the rest; optional tombstone drop."""

    name = "heap"

    def merge(
        self,
        sources,
        dir_path,
        output_index,
        cache,
        keep_tombstones,
        bloom_min_size,
    ) -> MergeResult:
        writer = EntryWriter(
            dir_path,
            output_index,
            cache,
            data_ext=COMPACT_DATA_FILE_EXT,
            index_ext=COMPACT_INDEX_FILE_EXT,
        )
        iters = [iter(t.entries()) for t in sources]
        heap: List[Tuple] = []
        for i, it in enumerate(iters):
            for key, value, ts in it:
                # (~ts, -i): newest timestamp first, tie toward the
                # newer (higher-positioned) source.
                heapq.heappush(heap, (key, ~ts, -i, value, i))
                break
        keys: List[bytes] = []
        last_key: Optional[bytes] = None
        popped = 0
        # Index-run extraction (ISSUE 17): collected AS entries
        # stream through the writer — the values are in hand, so the
        # run costs zero re-reads even on this per-entry path.
        idx_rows: Optional[List[Tuple[int, bytes]]] = (
            [] if self.index_fields else None
        )
        run_off = 0
        while heap:
            popped += 1
            if popped % 8192 == 0:
                self._tick()
            key, _nts, _ni, value, i = heapq.heappop(heap)
            for nkey, nvalue, nts in iters[i]:
                heapq.heappush(heap, (nkey, ~nts, -i, nvalue, i))
                break
            if key == last_key:
                continue  # dedup: first occurrence was the newest
            last_key = key
            if value == b"" and not keep_tombstones:
                cutoff = self.tombstone_drop_before
                if not cutoff or (~_nts) < cutoff:
                    continue
                # gc_grace: the tombstone is younger than the grace
                # window — keep it so a laggard replica cannot
                # resurrect the deleted value.
            writer.write(key, value, ~_nts)
            keys.append(key)
            if idx_rows is not None:
                idx_rows.append((run_off, value))
            run_off += ENTRY_HEADER_SIZE + len(key) + len(value)
        data_size = writer.close()
        wrote_bloom = False
        bloom_bytes = None
        if data_size >= bloom_min_size:
            bloom = BloomFilter.with_capacity(max(1, len(keys)))
            bloom.add_batch(keys)
            bloom_bytes = _write_bloom(dir_path, output_index, bloom)
            wrote_bloom = True
        data_crcs, index_crcs = writer.page_crcs()
        checksums.write(
            dir_path,
            output_index,
            data_crcs,
            index_crcs,
            data_size,
            bloom_bytes,
            ext=COMPACT_SUMS_FILE_EXT,
        )
        if idx_rows is not None:
            from . import secondary_index as si

            si.emit_run(
                dir_path,
                output_index,
                self.index_fields,
                idx_rows,
                compact=True,
            )
        return MergeResult(writer.entries_written, data_size, wrote_bloom)


class ColumnarMergeStrategy(CompactionStrategy):
    """Vectorized host path; also the template the device strategy fills
    in (it overrides ``sort_and_dedup``)."""

    name = "columnar"

    def sort_and_dedup(
        self, cols: columnar.MergeColumns
    ) -> Tuple[np.ndarray, np.ndarray]:
        perm = columnar.sort_columns_numpy(cols)
        perm = columnar.fixup_long_key_ties(cols, perm)
        return perm, columnar.dedup_mask(cols, perm)

    def merge(
        self,
        sources,
        dir_path,
        output_index,
        cache,
        keep_tombstones,
        bloom_min_size,
    ) -> MergeResult:
        cols = columnar.load_columns(sources)
        self._tick()
        perm, keep = self.sort_and_dedup(cols)
        self._tick()
        if not keep_tombstones:
            keep = keep & ~drop_tombstones_mask(
                cols.is_tombstone[perm],
                cols.timestamp[perm],
                self.tombstone_drop_before,
            )
        order = perm[keep]
        return write_output_columnar(
            cols, order, dir_path, output_index, cache, bloom_min_size,
            throttle=self.throttle, index_fields=self.index_fields,
        )


def drop_tombstones_mask(
    is_tombstone: np.ndarray,
    timestamps: np.ndarray,
    cutoff: "int | None",
) -> np.ndarray:
    """Vectorized tombstone-drop mask honoring the gc_grace cutoff:
    True where the record is a tombstone OLD enough to GC.  Shared by
    every columnar-shaped merge path so the grace semantics can never
    diverge between backends."""
    if not cutoff:
        return is_tombstone
    return is_tombstone & (timestamps < np.uint64(max(0, cutoff)))


def write_output_columnar(
    cols: columnar.MergeColumns,
    order: np.ndarray,
    dir_path: str,
    output_index: int,
    cache: Optional[PartitionPageCache],
    bloom_min_size: int,
    throttle=None,
    index_fields=None,
) -> MergeResult:
    """Bulk-write the compact_* triplet from a surviving-record order."""
    full_sizes = cols.full_size[order].astype(np.uint64)
    data_size = int(full_sizes.sum())
    n = int(order.size)

    # Index columns: offsets are the running sum of record sizes.
    offsets = np.zeros(n, dtype=np.uint64)
    if n > 1:
        np.cumsum(full_sizes[:-1], out=offsets[1:])
    index_arr = np.zeros(
        n,
        dtype=np.dtype(
            [("offset", "<u8"), ("key_size", "<u4"), ("full_size", "<u4")]
        ),
    )
    index_arr["offset"] = offsets
    index_arr["key_size"] = cols.key_size[order]
    index_arr["full_size"] = cols.full_size[order]

    data_arr = columnar.gather_records_array(cols, order)

    from .entry import DATA_FILE_EXT, INDEX_FILE_EXT

    data_w = PageMirroringWriter(
        f"{dir_path}/{file_name(output_index, COMPACT_DATA_FILE_EXT)}",
        (DATA_FILE_EXT, output_index),
        cache,
    )
    # Chunked writes from memoryviews: avoids duplicating the (possibly
    # ~GB) gathered blob as one bytes object.
    view = memoryview(data_arr)
    chunk = 32 << 20
    for off in range(0, len(view), chunk):
        data_w.write(view[off : off + chunk])
        if throttle is not None:
            throttle.tick()
    data_w.close()
    index_w = PageMirroringWriter(
        f"{dir_path}/{file_name(output_index, COMPACT_INDEX_FILE_EXT)}",
        (INDEX_FILE_EXT, output_index),
        cache,
    )
    index_w.write(index_arr.tobytes())
    index_w.close()

    wrote_bloom = False
    bloom_bytes = None
    if data_size >= bloom_min_size:
        key_pos = columnar.ranges_to_positions(
            cols.start[order] + np.uint64(ENTRY_HEADER_SIZE),
            cols.key_size[order],
        )
        key_blob = cols.data[key_pos].tobytes()
        key_sizes = cols.key_size[order]
        bounds = np.zeros(n + 1, dtype=np.int64)
        np.cumsum(key_sizes, out=bounds[1:])
        keys = [
            key_blob[bounds[i] : bounds[i + 1]] for i in range(n)
        ]
        bloom = BloomFilter.with_capacity(max(1, n))
        bloom.add_batch(keys)
        bloom_bytes = _write_bloom(dir_path, output_index, bloom)
        wrote_bloom = True
    checksums.write(
        dir_path,
        output_index,
        data_w.page_crcs,
        index_w.page_crcs,
        data_size,
        bloom_bytes,
        ext=COMPACT_SUMS_FILE_EXT,
    )
    if index_fields:
        # Index run (ISSUE 17) sliced straight out of the gathered
        # output blob still resident in RAM — zero re-reads.
        from . import secondary_index as si

        dview = memoryview(data_arr)
        offs = index_arr["offset"].tolist()
        kss = index_arr["key_size"].tolist()
        fss = index_arr["full_size"].tolist()
        si.emit_run(
            dir_path,
            output_index,
            index_fields,
            (
                (
                    offs[i],
                    bytes(
                        dview[
                            offs[i]
                            + ENTRY_HEADER_SIZE
                            + kss[i] : offs[i] + fss[i]
                        ]
                    ),
                )
                for i in range(n)
            ),
            compact=True,
        )
    return MergeResult(n, data_size, wrote_bloom)


def _write_bloom(
    dir_path: str, output_index: int, bloom: BloomFilter
) -> bytes:
    path = f"{dir_path}/{file_name(output_index, COMPACT_BLOOM_FILE_EXT)}"
    import os

    blob = bloom.serialize()
    with open(path, "wb") as f:
        f.write(blob)
        f.flush()
        os.fsync(f.fileno())
    return blob


def _jax_marked_dead(backend: str) -> bool:
    """True when the server's startup probe (utils/jax_gate) found the
    jax backend wedged/dead — device strategies must then degrade to
    host merges instead of hanging the compaction worker."""
    from ..utils.jax_gate import jax_marked_dead

    if not jax_marked_dead():
        return False
    import logging

    logging.getLogger(__name__).warning(
        "compaction_backend=%s: jax backend marked dead by the "
        "startup probe; using the host merge path",
        backend,
    )
    return True


def get_strategy(name: str) -> CompactionStrategy:
    """Resolve a strategy by config name (config.compaction_backend)."""
    if name == "heap":
        return HeapMergeStrategy()
    if name == "cpu" or name == "columnar":
        return ColumnarMergeStrategy()
    if name == "native":
        try:
            from .native import NativeMergeStrategy, native_available
        except ImportError:
            return ColumnarMergeStrategy()
        if native_available():
            return NativeMergeStrategy()
        return ColumnarMergeStrategy()
    if name == "device":
        if _jax_marked_dead("device"):
            return ColumnarMergeStrategy()
        try:
            from ..ops.device_compaction import DeviceMergeStrategy
        except ImportError:
            return ColumnarMergeStrategy()
        return DeviceMergeStrategy()
    if name == "coalesced":
        if _jax_marked_dead("coalesced"):
            return ColumnarMergeStrategy()
        try:
            from ..server.coalescer import CoalescedDeviceMergeStrategy
        except ImportError:
            return ColumnarMergeStrategy()
        return CoalescedDeviceMergeStrategy()
    if name == "device_full":
        if _jax_marked_dead("device_full"):
            return ColumnarMergeStrategy()
        try:
            from ..ops.device_compaction import DeviceFullMergeStrategy
        except ImportError:
            return ColumnarMergeStrategy()
        return DeviceFullMergeStrategy()
    if name == "distributed":
        # Multi-chip sample sort over the whole mesh (BASELINE config 5).
        # Falls back to the single-device kernel on a 1-chip host and to
        # the host path when jax is unavailable — loudly, so an operator
        # who configured the mesh backend can see it didn't engage.
        if _jax_marked_dead("distributed"):
            return ColumnarMergeStrategy()
        try:
            import jax

            from ..parallel.dist_merge import DistributedMergeStrategy
            from ..parallel.mesh import shard_mesh

            devices = jax.devices()
        except Exception as e:
            import logging

            logging.getLogger(__name__).warning(
                "compaction_backend=distributed unavailable (%r); "
                "falling back to the host columnar merge",
                e,
            )
            return ColumnarMergeStrategy()
        if len(devices) <= 1:
            return get_strategy("device")
        return DistributedMergeStrategy(shard_mesh())
    if name == "auto":
        try:
            if _jax_marked_dead("auto"):
                raise RuntimeError("jax marked dead by startup probe")
            import jax

            platform = jax.default_backend()
            n_devices = len(jax.devices())
        except Exception:
            platform = "cpu"
            n_devices = 1
        if platform != "cpu":
            if n_devices > 1:
                return get_strategy("distributed")
            return get_strategy("device")
        return get_strategy("native")
    raise ValueError(f"unknown compaction backend {name!r}")
