"""On-disk entry formats.

Role parity with /root/reference/src/storage_engine/mod.rs:14-95 (Entry /
EntryValue / EntryOffset / TOMBSTONE / file-extension registry), with our
own fixed-width little-endian layout chosen for zero-copy numpy views —
the whole data or index file parses into column arrays in one
``np.frombuffer`` for the device compaction path.

Data file record:
    [u32 key_len][u32 value_len][i64 timestamp_ns][key bytes][value bytes]
Index file record (16 bytes, like the reference's INDEX_ENTRY_SIZE):
    [u64 offset][u32 key_size][u32 full_size]

``full_size`` covers the whole data record including its 16-byte header.
An empty value is the tombstone (reference TOMBSTONE = vec![]; legitimate
document values are msgpack-encoded and therefore never empty).

Ordering invariant (mod.rs:75-81): entries sort by key, ties by timestamp;
within one file keys are unique and ascending.
"""

from __future__ import annotations

import struct
from typing import Tuple

PAGE_SIZE = 4096  # reference page_cache.rs:10

ENTRY_HEADER = struct.Struct("<IIq")  # key_len, value_len, timestamp_ns
ENTRY_HEADER_SIZE = ENTRY_HEADER.size  # 16
INDEX_ENTRY = struct.Struct("<QII")  # offset, key_size, full_size
INDEX_ENTRY_SIZE = INDEX_ENTRY.size  # 16

TOMBSTONE = b""

# File extensions (mod.rs:23-30).
MEMTABLE_FILE_EXT = "memtable"
DATA_FILE_EXT = "data"
INDEX_FILE_EXT = "index"
BLOOM_FILE_EXT = "bloom"
COMPACT_DATA_FILE_EXT = "compact_data"
COMPACT_INDEX_FILE_EXT = "compact_index"
COMPACT_BLOOM_FILE_EXT = "compact_bloom"
COMPACT_ACTION_FILE_EXT = "compact_action"
# Per-block CRC32 sidecar (storage/checksums.py) — no reference analog.
SUMS_FILE_EXT = "sums"
COMPACT_SUMS_FILE_EXT = "compact_sums"
# Secondary index run + its CRC sidecar (storage/secondary_index.py):
# built inline by flush/compaction, renamed/retired by the same action
# journal as the data triplet.
FIDX_FILE_EXT = "fidx"
FIDX_SUMS_FILE_EXT = "fidx_sums"
COMPACT_FIDX_FILE_EXT = "compact_fidx"
COMPACT_FIDX_SUMS_FILE_EXT = "compact_fidx_sums"

# Zero-padded index in file names so lexicographic order == numeric order
# (reference INDEX_PADDING = 20, mod.rs:21).
INDEX_PADDING = 20


def file_name(index: int, ext: str) -> str:
    return f"{index:0{INDEX_PADDING}}.{ext}"


def encode_entry(key: bytes, value: bytes, timestamp: int) -> bytes:
    return ENTRY_HEADER.pack(len(key), len(value), timestamp) + key + value


def decode_entry(buf, offset: int = 0) -> Tuple[bytes, bytes, int, int]:
    """Returns (key, value, timestamp, total_size)."""
    key_len, value_len, ts = ENTRY_HEADER.unpack_from(buf, offset)
    ko = offset + ENTRY_HEADER_SIZE
    key = bytes(buf[ko : ko + key_len])
    value = bytes(buf[ko + key_len : ko + key_len + value_len])
    return key, value, ts, ENTRY_HEADER_SIZE + key_len + value_len


def entry_size(key: bytes, value: bytes) -> int:
    return ENTRY_HEADER_SIZE + len(key) + len(value)
