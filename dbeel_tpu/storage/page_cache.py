"""W-TinyLFU page cache of 4 KiB pages.

Role parity with /root/reference/src/storage_engine/page_cache.rs:10-67:
one cache per shard sized ``page_cache_size / PAGE_SIZE / num_shards``
pages, partitioned per collection by murmur3 name-hash; cache key =
(partition-name-hash, (file-type, file-index), page-address).

This is a real W-TinyLFU (same family as the reference's ``wtinylfu``
crate): a small admission window (LRU) in front of a segmented-LRU main
region (probation/protected), with a 4-bit count-min sketch deciding
admission on window eviction and periodic halving ("reset") to age the
sketch.
"""

from __future__ import annotations

from collections import OrderedDict
from typing import Optional, Tuple

import numpy as np

from .entry import PAGE_SIZE
from ..utils.murmur import murmur3_32

CacheKey = Tuple[int, Tuple[str, int], int]  # (partition, file id, page addr)


def align_down(n: int) -> int:
    return n & ~(PAGE_SIZE - 1)


def align_up(n: int) -> int:
    return (n + PAGE_SIZE - 1) & ~(PAGE_SIZE - 1)


class _CountMinSketch:
    """4-bit frequency sketch with conservative reset, a la TinyLFU.

    The table is one flat bytearray (scalar bytearray indexing costs
    ~50 ns vs ~1 µs for a numpy scalar access): increment/estimate run
    on EVERY page-cache get and set, so they sit squarely on the
    serving path's per-probe cost."""

    def __init__(self, capacity: int) -> None:
        size = 1
        while size < max(64, capacity):
            size <<= 1
        self._mask = size - 1
        self._size = size
        self._table = bytearray(4 * size)
        self._ops = 0
        self._reset_at = 10 * size

    _ROW_SEEDS = (0x9E3779B1, 0x85EBCA77, 0xC2B2AE3D, 0x27D4EB2F)

    def _indices(self, h: int):
        mask = self._mask
        size = self._size
        s0, s1, s2, s3 = self._ROW_SEEDS
        return (
            ((h ^ s0) * 0x9E3779B1 & 0xFFFFFFFF) >> 12 & mask,
            size + (((h ^ s1) * 0x9E3779B1 & 0xFFFFFFFF) >> 12 & mask),
            2 * size
            + (((h ^ s2) * 0x9E3779B1 & 0xFFFFFFFF) >> 12 & mask),
            3 * size
            + (((h ^ s3) * 0x9E3779B1 & 0xFFFFFFFF) >> 12 & mask),
        )

    def increment(self, h: int) -> None:
        table = self._table
        for i in self._indices(h):
            if table[i] < 15:
                table[i] += 1
        self._ops += 1
        if self._ops >= self._reset_at:
            # Rare: halve all counters in one vectorized pass.
            arr = np.frombuffer(self._table, dtype=np.uint8)
            np.right_shift(arr, 1, out=arr)
            self._ops //= 2

    def estimate(self, h: int) -> int:
        table = self._table
        return min(table[i] for i in self._indices(h))


class PageCache:
    """Shard-global W-TinyLFU over immutable 4 KiB pages."""

    def __init__(self, capacity_pages: int) -> None:
        capacity_pages = max(8, capacity_pages)
        self.capacity = capacity_pages
        self._window_cap = max(1, capacity_pages // 100)
        main_cap = capacity_pages - self._window_cap
        self._protected_cap = max(1, (main_cap * 4) // 5)
        self._probation_cap = max(1, main_cap - self._protected_cap)
        self._window: "OrderedDict[CacheKey, bytes]" = OrderedDict()
        self._probation: "OrderedDict[CacheKey, bytes]" = OrderedDict()
        self._protected: "OrderedDict[CacheKey, bytes]" = OrderedDict()
        self._sketch = _CountMinSketch(capacity_pages)
        self.hits = 0
        self.misses = 0

    def __len__(self) -> int:
        return len(self._window) + len(self._probation) + len(self._protected)

    @staticmethod
    def _hash(key: CacheKey) -> int:
        return hash(key) & 0xFFFFFFFFFFFF

    def get(self, key: CacheKey) -> Optional[bytes]:
        self._sketch.increment(self._hash(key))
        page = self._window.get(key)
        if page is not None:
            self._window.move_to_end(key)
            self.hits += 1
            return page
        page = self._protected.pop(key, None)
        if page is not None:
            self._protected[key] = page
            self.hits += 1
            return page
        page = self._probation.pop(key, None)
        if page is not None:
            # Promote probation -> protected (SLRU).
            self._protected[key] = page
            if len(self._protected) > self._protected_cap:
                demoted, dpage = self._protected.popitem(last=False)
                self._insert_probation(demoted, dpage)
            self.hits += 1
            return page
        self.misses += 1
        return None

    def set(self, key: CacheKey, page: bytes) -> None:
        assert len(page) == PAGE_SIZE, len(page)
        if (
            key in self._window
            or key in self._probation
            or key in self._protected
        ):
            # Overwrite in place (writers mirror freshly-written pages).
            for seg in (self._window, self._probation, self._protected):
                if key in seg:
                    seg[key] = page
                    return
        self._sketch.increment(self._hash(key))
        self._window[key] = page
        if len(self._window) > self._window_cap:
            cand_key, cand_page = self._window.popitem(last=False)
            self._admit(cand_key, cand_page)

    def _admit(self, key: CacheKey, page: bytes) -> None:
        if len(self._probation) + len(self._protected) < (
            self._probation_cap + self._protected_cap
        ):
            self._insert_probation(key, page)
            return
        victim_key = next(iter(self._probation), None)
        if victim_key is None:
            self._insert_probation(key, page)
            return
        # TinyLFU admission: candidate must beat the SLRU victim.
        if self._sketch.estimate(self._hash(key)) > self._sketch.estimate(
            self._hash(victim_key)
        ):
            self._probation.pop(victim_key, None)
            self._insert_probation(key, page)
        # else: candidate dropped.

    def _insert_probation(self, key: CacheKey, page: bytes) -> None:
        self._probation[key] = page
        while len(self._probation) > self._probation_cap:
            self._probation.popitem(last=False)

    def invalidate_file(self, partition: int, file_id: Tuple[str, int]):
        for seg in (self._window, self._probation, self._protected):
            dead = [k for k in seg if k[0] == partition and k[1] == file_id]
            for k in dead:
                del seg[k]


class PartitionPageCache:
    """Per-collection view of the shard cache, keyed by name hash
    (page_cache.rs:27-67)."""

    def __init__(self, name: str, cache: PageCache) -> None:
        self._partition = murmur3_32(name.encode("utf-8"), 0)
        self._cache = cache

    def full_key(self, file_id: Tuple[str, int], address: int) -> CacheKey:
        return (self._partition, file_id, address)

    def get_copied(
        self, file_id: Tuple[str, int], address: int
    ) -> Optional[bytes]:
        return self._cache.get(self.full_key(file_id, address))

    def set(self, file_id: Tuple[str, int], address: int, page: bytes):
        self._cache.set(self.full_key(file_id, address), page)

    def invalidate_file(self, file_id: Tuple[str, int]) -> None:
        self._cache.invalidate_file(self._partition, file_id)
