"""Error taxonomy.

Mirrors the reference's error system (/root/reference/src/error.rs:8-74 and
db_server.rs:34-48): every error has a stable *kind name* that crosses the
wire as ``ResponseError{name, message}`` so clients compare by kind, never
by message text.
"""

from __future__ import annotations

from typing import Any, List


class DbeelError(Exception):
    """Base error. ``kind`` is the stable wire name."""

    kind = "Internal"

    def to_wire(self) -> List[Any]:
        # rmp-serde encodes the reference's ResponseError struct as a
        # 2-array [name, message]; keep that shape for client parity.
        return [self.kind, str(self)]


def _mk(kind_name: str, doc: str) -> type:
    return type(
        kind_name, (DbeelError,), {"kind": kind_name, "__doc__": doc}
    )


ShardStopped = _mk("ShardStopped", "The shard is shutting down.")
CollectionNotFound = _mk("CollectionNotFound", "No such collection.")
CollectionAlreadyExists = _mk(
    "CollectionAlreadyExists", "Collection already exists."
)
KeyNotFound = _mk("KeyNotFound", "No live entry for key (or tombstoned).")
KeyNotOwnedByShard = _mk(
    "KeyNotOwnedByShard",
    "This shard is not an owner of the key's hash ring range.",
)
MissingField = _mk("MissingField", "Required request field is missing.")
BadFieldType = _mk("BadFieldType", "Request field has the wrong type.")
UnsupportedField = _mk("UnsupportedField", "Unknown request type.")
MemtableCapacityReached = _mk(
    "MemtableCapacityReached", "Arena memtable is at capacity."
)
Timeout = _mk("Timeout", "Operation timed out.")
ConnectionError_ = _mk("ConnectionError", "Network failure talking to shard.")
ProtocolError = _mk("ProtocolError", "Malformed frame or message.")
CorruptedFile = _mk("CorruptedFile", "On-disk structure failed validation.")
NoRemoteShardsFound = _mk(
    "NoRemoteShardsFound", "Not enough distinct nodes for replication."
)
TooManyWalFiles = _mk(
    "TooManyWalFiles", "More than two WAL files found on open."
)
PeerDead = _mk(
    "PeerDead",
    "A replica needed for this op is marked Dead by the failure "
    "detector.",
)
ShardDegraded = _mk(
    "ShardDegraded",
    "The shard's disk failed (EIO/ENOSPC on the WAL); it is serving "
    "reads only — retry the write on another replica.",
)
Overloaded = _mk(
    "Overloaded",
    "The shard (or a peer's outbound queue) is past its hard load "
    "limit and shed this request; retry after backoff — the backlog "
    "drains, this is a transient condition, not a failure.",
)
QuotaExceeded = _mk(
    "QuotaExceeded",
    "The tenant's token bucket for this collection is exhausted; "
    "retry after backoff — tokens refill continuously at the "
    "configured per-tenant rate (QoS plane).",
)
CasConflict = _mk(
    "CasConflict",
    "A conditional write's expectation did not match the key's "
    "current state at the arc owner (atomic plane); re-read and "
    "retry with fresh expectations — the decided state is intact.",
)

_BY_KIND = {
    cls.kind: cls
    for cls in list(globals().values())
    if isinstance(cls, type) and issubclass(cls, DbeelError)
}


# ---------------------------------------------------------------------
# Failure taxonomy: every client-visible FAILURE maps to one stable
# class, shared by server metrics, the smart clients, and the chaos
# soak report, so an error rate can always be broken down the same way
# on both sides of the wire.
# ---------------------------------------------------------------------

ERROR_CLASS_COORDINATOR_DEAD = "coordinator-dead"
ERROR_CLASS_QUORUM_TIMEOUT = "quorum-timeout"
ERROR_CLASS_PEER_DEAD = "peer-dead"
ERROR_CLASS_NOT_OWNED = "not-owned"
# Disk plane (PR 3): a read hit a checksum failure / quarantined range
# on this replica, or the shard is in read-only degraded mode after a
# WAL EIO/ENOSPC — both retryable, the client walks to a healthy
# replica.
ERROR_CLASS_CORRUPTION = "data-corruption"
ERROR_CLASS_DEGRADED = "degraded"
# Overload-control plane (PR 5): the shard's load governor shed this
# request past its hard limits (or a peer's capped outbound queue
# refused it).  Retryable after backoff — shedding IS the mechanism
# that keeps the node alive, so clients must treat it as "try again
# shortly", never as data loss.
ERROR_CLASS_OVERLOAD = "overload"
# Multi-tenant QoS plane (ISSUE 14): the tenant's token bucket for
# the target collection is exhausted.  Retryable after backoff —
# tokens refill continuously, so "try again shortly" is the contract;
# distinct from `overload` because the SHARD is healthy: only this
# tenant is over its configured rate.
ERROR_CLASS_QUOTA = "quota"
# Atomic plane (ISSUE 19): a cas/atomic_batch expectation lost the
# race against a concurrent decided write.  Retryable by CONTRACT —
# but unlike the infrastructure classes the client must re-read and
# recompute its expectations first (the rmw helper does exactly
# that); blind resubmission would just lose again.
ERROR_CLASS_CONFLICT = "conflict"
ERROR_CLASS_OTHER = "other"
ERROR_CLASSES = (
    ERROR_CLASS_COORDINATOR_DEAD,
    ERROR_CLASS_QUORUM_TIMEOUT,
    ERROR_CLASS_PEER_DEAD,
    ERROR_CLASS_NOT_OWNED,
    ERROR_CLASS_CORRUPTION,
    ERROR_CLASS_DEGRADED,
    ERROR_CLASS_OVERLOAD,
    ERROR_CLASS_QUOTA,
    ERROR_CLASS_CONFLICT,
    ERROR_CLASS_OTHER,
)

# Application OUTCOMES, not failures: a get of an absent key or a
# duplicate create_collection is the protocol working as designed.
_BENIGN_KINDS = frozenset(
    {
        "KeyNotFound",
        "CollectionNotFound",
        "CollectionAlreadyExists",
    }
)

# Errors whose cause is the coordinator (or the path to it) being
# unreachable: the client should walk to the next replica.
_CONNECTION_KINDS = frozenset({"ConnectionError", "ProtocolError"})


def classify_error(exc: BaseException) -> "str | None":
    """Taxonomy class of a client-visible failure, or None for benign
    application outcomes (KeyNotFound et al.) that are not failures."""
    import asyncio

    if isinstance(exc, DbeelError):
        kind = exc.kind
        if kind in _BENIGN_KINDS:
            return None
        if kind == "KeyNotOwnedByShard":
            return ERROR_CLASS_NOT_OWNED
        if kind == "Timeout":
            return ERROR_CLASS_QUORUM_TIMEOUT
        if kind == "PeerDead":
            return ERROR_CLASS_PEER_DEAD
        if kind == "CorruptedFile":
            return ERROR_CLASS_CORRUPTION
        if kind == "ShardDegraded":
            return ERROR_CLASS_DEGRADED
        if kind == "Overloaded":
            return ERROR_CLASS_OVERLOAD
        if kind == "QuotaExceeded":
            return ERROR_CLASS_QUOTA
        if kind == "CasConflict":
            return ERROR_CLASS_CONFLICT
        if kind in _CONNECTION_KINDS:
            return ERROR_CLASS_COORDINATOR_DEAD
        return ERROR_CLASS_OTHER
    if isinstance(exc, asyncio.TimeoutError):
        return ERROR_CLASS_QUORUM_TIMEOUT
    if isinstance(
        exc, (OSError, asyncio.IncompleteReadError, EOFError)
    ):
        # Connect refused/reset, half-closed stream: the coordinator
        # (or the node being dialed) is gone.
        return ERROR_CLASS_COORDINATOR_DEAD
    return ERROR_CLASS_OTHER


def is_retryable_class(error_class: "str | None") -> bool:
    """Should a smart client walk to the next replica / retry after
    backoff for this failure class?  Benign outcomes and application
    errors are final; infrastructure failures are not."""
    return error_class in (
        ERROR_CLASS_COORDINATOR_DEAD,
        ERROR_CLASS_QUORUM_TIMEOUT,
        ERROR_CLASS_PEER_DEAD,
        ERROR_CLASS_NOT_OWNED,
        # Another replica may hold a clean copy (corruption) or a
        # writable WAL (degraded): always worth the walk.
        ERROR_CLASS_CORRUPTION,
        ERROR_CLASS_DEGRADED,
        # Shedding is transient by design: back off and retry (walk
        # too — another replica may be below its limits).
        ERROR_CLASS_OVERLOAD,
        # Quota refusals refill with time: back off and retry — the
        # same transient contract as shedding, scoped to one tenant.
        ERROR_CLASS_QUOTA,
        # A lost CAS race is retryable AFTER a re-read: the rmw
        # helper recomputes expectations; generic retry loops must
        # not replay the same expectation blindly.
        ERROR_CLASS_CONFLICT,
    )


def from_wire(payload: Any) -> DbeelError:
    """Rebuild a typed error from a wire ``[name, message]`` payload."""
    try:
        name, message = payload[0], payload[1]
    except Exception:
        return DbeelError(f"unparseable error payload: {payload!r}")
    cls = _BY_KIND.get(name)
    if cls is None:
        err = DbeelError(message)
        err.kind = name
        return err
    return cls(message)
