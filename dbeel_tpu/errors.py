"""Error taxonomy.

Mirrors the reference's error system (/root/reference/src/error.rs:8-74 and
db_server.rs:34-48): every error has a stable *kind name* that crosses the
wire as ``ResponseError{name, message}`` so clients compare by kind, never
by message text.
"""

from __future__ import annotations

from typing import Any, List


class DbeelError(Exception):
    """Base error. ``kind`` is the stable wire name."""

    kind = "Internal"

    def to_wire(self) -> List[Any]:
        # rmp-serde encodes the reference's ResponseError struct as a
        # 2-array [name, message]; keep that shape for client parity.
        return [self.kind, str(self)]


def _mk(kind_name: str, doc: str) -> type:
    return type(
        kind_name, (DbeelError,), {"kind": kind_name, "__doc__": doc}
    )


ShardStopped = _mk("ShardStopped", "The shard is shutting down.")
CollectionNotFound = _mk("CollectionNotFound", "No such collection.")
CollectionAlreadyExists = _mk(
    "CollectionAlreadyExists", "Collection already exists."
)
KeyNotFound = _mk("KeyNotFound", "No live entry for key (or tombstoned).")
KeyNotOwnedByShard = _mk(
    "KeyNotOwnedByShard",
    "This shard is not an owner of the key's hash ring range.",
)
MissingField = _mk("MissingField", "Required request field is missing.")
BadFieldType = _mk("BadFieldType", "Request field has the wrong type.")
UnsupportedField = _mk("UnsupportedField", "Unknown request type.")
MemtableCapacityReached = _mk(
    "MemtableCapacityReached", "Arena memtable is at capacity."
)
Timeout = _mk("Timeout", "Operation timed out.")
ConnectionError_ = _mk("ConnectionError", "Network failure talking to shard.")
ProtocolError = _mk("ProtocolError", "Malformed frame or message.")
CorruptedFile = _mk("CorruptedFile", "On-disk structure failed validation.")
NoRemoteShardsFound = _mk(
    "NoRemoteShardsFound", "Not enough distinct nodes for replication."
)
TooManyWalFiles = _mk(
    "TooManyWalFiles", "More than two WAL files found on open."
)

_BY_KIND = {
    cls.kind: cls
    for cls in list(globals().values())
    if isinstance(cls, type) and issubclass(cls, DbeelError)
}


def from_wire(payload: Any) -> DbeelError:
    """Rebuild a typed error from a wire ``[name, message]`` payload."""
    try:
        name, message = payload[0], payload[1]
    except Exception:
        return DbeelError(f"unparseable error payload: {payload!r}")
    cls = _BY_KIND.get(name)
    if cls is None:
        err = DbeelError(message)
        err.kind = name
        return err
    return cls(message)
