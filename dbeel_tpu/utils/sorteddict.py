"""Minimal pure-python SortedDict fallback.

The arena-image python has no ``sortedcontainers`` wheel; the sorted
memtable only needs a small slice of its API (ordered ``items()``,
``irange`` scans, plain dict reads/writes), so this module provides a
drop-in for exactly that slice and ``storage.memtable`` imports it
when the real package is absent.  Keys are kept in a bisect-maintained
list: O(n) worst-case insert for a NEW key, O(log n) lookup — fine for
capacity-bounded memtables, and the hash/arena memtables don't pass
through here at all.
"""

from __future__ import annotations

from bisect import bisect_left, bisect_right, insort
from typing import Any, Iterator, List, Optional, Tuple


class SortedDict:
    __slots__ = ("_data", "_keys")

    def __init__(self, *args, **kwargs) -> None:
        self._data: dict = {}
        self._keys: List[Any] = []
        if args or kwargs:
            for k, v in dict(*args, **kwargs).items():
                self[k] = v

    # -- writes --------------------------------------------------------

    def __setitem__(self, key, value) -> None:
        if key not in self._data:
            insort(self._keys, key)
        self._data[key] = value

    def __delitem__(self, key) -> None:
        del self._data[key]
        i = bisect_left(self._keys, key)
        del self._keys[i]

    def pop(self, key, *default):
        if key in self._data:
            value = self._data[key]
            del self[key]
            return value
        if default:
            return default[0]
        raise KeyError(key)

    def clear(self) -> None:
        self._data.clear()
        self._keys.clear()

    # -- reads ---------------------------------------------------------

    def __getitem__(self, key):
        return self._data[key]

    def get(self, key, default=None):
        return self._data.get(key, default)

    def __contains__(self, key) -> bool:
        return key in self._data

    def __len__(self) -> int:
        return len(self._data)

    def __bool__(self) -> bool:
        return bool(self._data)

    def __iter__(self) -> Iterator:
        return iter(self._keys)

    def keys(self):
        return list(self._keys)

    def values(self):
        return [self._data[k] for k in self._keys]

    def items(self) -> Iterator[Tuple[Any, Any]]:
        return iter([(k, self._data[k]) for k in self._keys])

    def peekitem(self, index: int = -1) -> Tuple[Any, Any]:
        key = self._keys[index]
        return key, self._data[key]

    def irange(
        self,
        minimum: Optional[Any] = None,
        maximum: Optional[Any] = None,
        inclusive: Tuple[bool, bool] = (True, True),
        reverse: bool = False,
    ) -> Iterator:
        """Ordered key scan over [minimum, maximum] (bounds optional,
        inclusive by default — the sortedcontainers contract)."""
        lo = 0
        hi = len(self._keys)
        if minimum is not None:
            lo = (
                bisect_left(self._keys, minimum)
                if inclusive[0]
                else bisect_right(self._keys, minimum)
            )
        if maximum is not None:
            hi = (
                bisect_right(self._keys, maximum)
                if inclusive[1]
                else bisect_left(self._keys, maximum)
            )
        span = self._keys[lo:hi]
        return iter(reversed(span) if reverse else span)
