"""Server-assigned timestamps.

The reference stamps every write with nanosecond UTC time on the receiving
shard and resolves replica conflicts by max timestamp
(/root/reference/src/utils/timestamp_nanos.rs:6-24, db_server.rs:353-363).
We represent timestamps as int64 nanoseconds since the Unix epoch — the
same total order, and directly usable as a device sort column.
"""

from __future__ import annotations

import time


def now_nanos() -> int:
    return time.time_ns()
