from .murmur import hash_bytes, hash_string, murmur3_32  # noqa: F401
from .event import LocalEvent  # noqa: F401
from .timestamps import now_nanos  # noqa: F401
