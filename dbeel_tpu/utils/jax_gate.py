"""Liveness gate for the jax device backend.

The tunneled TPU plugin can wedge hard — blocked in a plain
``recvfrom`` during backend init — in a way no except-clause can
catch, and it does so even under ``JAX_PLATFORMS=cpu`` because plugin
discovery still phones the tunnel.  Observed in production: a dead
tunnel turned ``jax.devices()`` into an unbounded hang, so the whole
node (which only needs jax for background compaction) never came up.

The gate probes backend init in a THROWAWAY SUBPROCESS with a
timeout: a wedged child is killed, the parent never blocks, and the
verdict is cached in ``DBEEL_JAX_PROBED`` so per-core shard processes
(``--processes``) inherit it instead of re-probing.  On failure the
server still serves — device compaction backends degrade loudly to
the native host merge (storage/compaction.py get_strategy), matching
the reference's always-available single-threaded merge
(/root/reference/src/storage_engine/lsm_tree.rs:950-1156).
"""

from __future__ import annotations

import logging
import os
import subprocess
import sys
from typing import Optional

log = logging.getLogger(__name__)

_verdict: Optional[bool] = None


def probe_jax_alive(
    timeout_s: Optional[float] = None, force: bool = False
) -> bool:
    """Probe jax backend init in a subprocess (once per process tree).
    Returns False when init wedges past the timeout or fails.
    ``force=True`` ignores a cached verdict and re-probes — for
    callers that retry while waiting on a flapping tunnel."""
    global _verdict
    if not force:
        if _verdict is not None:
            return _verdict
        cached = os.environ.get("DBEEL_JAX_PROBED")
        if cached in ("ok", "fail"):
            _verdict = cached == "ok"
            return _verdict
    # Already initialized in this process (tests, embedders): devices()
    # cannot wedge anymore, so skip the subprocess (which would pay a
    # redundant multi-second backend init).
    if "jax" in sys.modules:
        try:
            from jax._src import xla_bridge

            if xla_bridge.backends_are_initialized():
                _verdict = True
                os.environ["DBEEL_JAX_PROBED"] = "ok"
                return True
        except Exception:
            pass
    if timeout_s is None:
        timeout_s = float(
            os.environ.get("DBEEL_JAX_INIT_TIMEOUT_S", "45")
        )
    try:
        # Popen + wait(timeout), NOT subprocess.run: run()'s timeout
        # path calls kill() then an UNBOUNDED wait(), which blocks
        # forever if the child is wedged in an uninterruptible
        # (D-state) syscall — the exact condition being probed.  Here
        # the child is killed and, if it still won't reap, abandoned
        # (it is kill-pending; init will reap it eventually).
        proc = subprocess.Popen(
            [sys.executable, "-c", "import jax; jax.devices()"],
            stdout=subprocess.DEVNULL,
            stderr=subprocess.DEVNULL,
        )
        try:
            rc = proc.wait(timeout=timeout_s)
            _verdict = rc == 0
            if rc != 0:
                log.warning(
                    "jax backend init failed (probe exit %d); device "
                    "compaction disabled for this run",
                    rc,
                )
        except subprocess.TimeoutExpired:
            proc.kill()
            try:
                proc.wait(timeout=5)
            except subprocess.TimeoutExpired:
                pass  # D-state child: abandon, never block startup
            log.warning(
                "jax backend init wedged for %.0fs (dead TPU "
                "tunnel?); device compaction disabled for this run",
                timeout_s,
            )
            _verdict = False
    except Exception as e:
        log.warning(
            "jax backend init failed (%s); device compaction disabled "
            "for this run",
            e,
        )
        _verdict = False
    os.environ["DBEEL_JAX_PROBED"] = "ok" if _verdict else "fail"
    return _verdict


def jax_marked_dead() -> bool:
    """True only when a prior probe (this process or a parent) marked
    the backend unusable.  Never probes — safe for library contexts."""
    if _verdict is not None:
        return not _verdict
    return os.environ.get("DBEEL_JAX_PROBED") == "fail"
