"""LocalEvent — single-consumer-loop async event with listen-before-notify
sticky semantics.

Mirrors the reference's LocalEvent (/root/reference/src/utils/local_event.rs
:17-100): a listener created *before* a notify observes that notify even if
it only awaits afterwards; a listener created after misses it.  Used for
flush start/done, WAL-sync coalescing, and collections-changed signaling.
"""

from __future__ import annotations

import asyncio
from typing import List


class LocalEvent:
    def __init__(self) -> None:
        self._futures: List[asyncio.Future] = []

    def listen(self) -> "asyncio.Future[None]":
        """Arm a listener now; await the returned future later."""
        fut: asyncio.Future = asyncio.get_event_loop().create_future()
        self._futures.append(fut)
        return fut

    async def wait(self) -> None:
        """Arm and await in one step (misses earlier notifies)."""
        await self.listen()

    def notify(self) -> int:
        """Wake every currently-armed listener; returns how many."""
        woken = 0
        for fut in self._futures:
            if not fut.done():
                fut.set_result(None)
                woken += 1
        self._futures.clear()
        return woken
